"""jaxpr → ONNX graph converter.

TPU-native design: instead of an op-by-op layer converter (paddle2onnx's
approach over the reference's Program protobuf), the layer is traced ONCE to
a jaxpr — the same functional trace jit/export use — and each jax primitive
is lowered to standard ONNX ops (opset 13). Composite layers therefore export
as their mathematical decomposition (LayerNorm → ReduceMean/Sub/Div chain,
softmax → max/exp/sum/div), which any ONNX runtime executes without custom
domains. Reference parity target: python/paddle/onnx/export.py:21.

Supported primitive set covers the traced graphs of LeNet, ResNet, and the
GPT block family (Conv/MatMul/Relu-as-Max/Gelu-as-Erf/softmax chain/
LayerNorm chain/MaxPool/Reshape/Transpose/Add/Gather...). Unsupported
primitives raise UnsupportedOpError naming the primitive.
"""
import numpy as np

from . import proto


class UnsupportedOpError(RuntimeError):
    pass


def _np_dtype(aval):
    return str(np.dtype(aval.dtype))


class _Graph:
    """Accumulates ONNX nodes/initializers with SSA naming."""

    def __init__(self):
        # nodes stay as SPECS (op_type, inputs, outputs, name, attrs)
        # until build_nodes(): the dynamic-batch rewrite and the
        # initializer dedup pass both need to compare/remap inputs before
        # anything is serialized
        self.node_specs = []
        self.initializers = []
        self._init_names = set()
        self.var_names = {}     # jax Var -> onnx value name
        self.produced = set()   # names produced by a node (not init/input)
        self._value_cache = {}  # (dtype, shape, bytes) -> initializer name
        self.counter = 0
        # dynamic-batch bookkeeping: raw arrays + list index per
        # initializer (so a shape const can be REWRITTEN after the
        # two-trace diff), and (op_type, operand position) per consumer of
        # each value name — position matters: only the SHAPE operand
        # (input 1) of Reshape/Expand is rewritable
        self.init_arrays = {}   # name -> (index in initializers, ndarray)
        self.consumers = {}     # value name -> set of (op_type, arg_pos)
        # Slice-ends const name -> per-entry "is a full-span slice" flags
        # (written by _op_slice; consulted by the dynamic-batch rewrite)
        self.ends_full_span = {}

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, atom):
        """ONNX value name for a jaxpr atom (Var or Literal)."""
        from jax._src.core import Literal

        if isinstance(atom, Literal):
            return self.const(np.asarray(atom.val))
        if atom not in self.var_names:
            self.var_names[atom] = self.fresh("v")
        return self.var_names[atom]

    def const(self, array, name=None, dedup=True):
        arr = np.asarray(array)
        if name is None:
            # dedup small constants by value: jaxpr Literals repeat the
            # same scalars (1.0, 0.5, sqrt(2)...) once per layer.
            # Shape vectors opt OUT (dedup=False): a batch-carrying shape
            # like [B*T, H] can coincidentally equal an unrelated constant
            # at one batch size but not another, which would break the
            # dynamic-batch two-trace structural diff.
            if dedup and arr.size <= 64:
                key = (str(arr.dtype), arr.shape, arr.tobytes())
                cached = self._value_cache.get(key)
                if cached is not None:
                    return cached
                name = self.fresh("const")
                self._value_cache[key] = name
            else:
                name = self.fresh("const")
        if name not in self._init_names:
            self._init_names.add(name)
            self.init_arrays[name] = (len(self.initializers), arr)
            self.initializers.append(proto.tensor_proto(name, arr))
        return name

    def replace_const(self, name, arr):
        """Rewrite an initializer in place (dynamic-batch shape surgery)."""
        idx, _ = self.init_arrays[name]
        arr = np.asarray(arr)
        self.init_arrays[name] = (idx, arr)
        self.initializers[idx] = proto.tensor_proto(name, arr)

    def shape_const(self, dims):
        return self.const(np.asarray(dims, np.int64), dedup=False)

    def add(self, op_type, inputs, n_out=1, attrs=None, out_names=None):
        outs = out_names or [self.fresh(op_type.lower())
                             for _ in range(n_out)]
        self.node_specs.append([op_type, list(inputs), list(outs),
                                self.fresh(f"n_{op_type}"), attrs])
        self.produced.update(outs)
        for pos, nm in enumerate(inputs):
            self.consumers.setdefault(nm, set()).add((op_type, pos))
        return outs if n_out != 1 or out_names else outs[0]

    def build_nodes(self):
        return [proto.node_proto(op, ins, outs, name=nm, attrs=attrs)
                for op, ins, outs, nm, attrs in self.node_specs]


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "max": "Max",
    "min": "Min", "pow": "Pow", "neg": "Neg", "exp": "Exp", "log": "Log",
    "sqrt": "Sqrt", "tanh": "Tanh", "logistic": "Sigmoid", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "erf": "Erf",
    "sin": "Sin", "cos": "Cos", "not": "Not", "and": "And", "or": "Or",
}
_COMPARE = {"lt": "Less", "le": "LessOrEqual", "gt": "Greater",
            "ge": "GreaterOrEqual", "eq": "Equal"}
_REDUCE_ATTR_AXES = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                     "reduce_prod": "ReduceProd"}


class Converter:
    def __init__(self):
        self.g = _Graph()

    # -- entry ---------------------------------------------------------------
    def convert_jaxpr(self, closed_jaxpr, input_names):
        """closed_jaxpr: jax ClosedJaxpr whose first invars are weights
        (callers pass them via env pre-binding), remaining are graph inputs.
        input_names: names for the GRAPH inputs (last len(input_names)
        invars). Weights invars must already be bound in self.g.var_names
        (as initializers)."""
        jaxpr = closed_jaxpr.jaxpr
        for var, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
            self.g.var_names[var] = self.g.const(np.asarray(cval))
        n_in = len(input_names)
        graph_inputs = jaxpr.invars[len(jaxpr.invars) - n_in:]
        for var, nm in zip(graph_inputs, input_names):
            self.g.var_names[var] = nm
        self._eqns(jaxpr.eqns)
        out_names = []
        for ov in jaxpr.outvars:
            nm = self.g.name_of(ov)
            out_names.append(nm)
        return graph_inputs, jaxpr.outvars, out_names

    def _eqns(self, eqns):
        for eqn in eqns:
            self._eqn(eqn)

    # -- dispatch ------------------------------------------------------------
    def _eqn(self, eqn):
        p = eqn.primitive.name
        handler = getattr(self, f"_op_{p}", None)
        if handler is not None:
            return handler(eqn)
        if p in _ELEMENTWISE:
            ins = [self.g.name_of(v) for v in eqn.invars]
            self.g.add(_ELEMENTWISE[p], ins,
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        if p in _COMPARE:
            ins = [self.g.name_of(v) for v in eqn.invars]
            self.g.add(_COMPARE[p], ins,
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        if p in _REDUCE_ATTR_AXES:
            (x,) = eqn.invars
            axes = [int(a) for a in eqn.params["axes"]]
            if not axes:  # reduce over no axes is the identity (ONNX's
                # empty-axes attr means reduce-ALL with noop_with_empty_axes
                # unset, so it cannot express this case directly)
                self.g.add("Identity", [self.g.name_of(x)],
                           out_names=[self.g.name_of(eqn.outvars[0])])
                return
            self.g.add(_REDUCE_ATTR_AXES[p], [self.g.name_of(x)],
                       attrs={"axes": axes, "keepdims": 0},
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        raise UnsupportedOpError(
            f"paddle_tpu.onnx: no ONNX lowering for jax primitive '{p}' "
            f"(eqn: {eqn})")

    # -- call-like primitives: inline ---------------------------------------
    def _inline_body(self, inner_jaxpr, consts, input_names):
        """Emit a sub-jaxpr's eqns with its invars bound to `input_names`;
        returns the body's output value names. PROPER SCOPING: jax caches
        and SHARES jaxpr objects (two relu eqns carry the identical
        call_jaxpr with the same Var objects), so the inner vars' name
        bindings must be saved/cleared per inline and restored after —
        otherwise the second inline of a shared jaxpr silently reuses the
        first one's SSA names and two nodes write the same output."""
        from jax._src.core import Literal

        owned = list(inner_jaxpr.invars) + list(inner_jaxpr.constvars)
        for e in inner_jaxpr.eqns:
            owned.extend(e.outvars)   # nested sub-jaxprs scope themselves
        saved = {v: self.g.var_names[v] for v in owned
                 if v in self.g.var_names}
        for v in owned:
            self.g.var_names.pop(v, None)

        for var, cval in zip(inner_jaxpr.constvars, consts):
            self.g.var_names[var] = self.g.const(np.asarray(cval))
        for inner_v, nm in zip(inner_jaxpr.invars, input_names):
            self.g.var_names[inner_v] = nm
        self._eqns(inner_jaxpr.eqns)
        out_names = []
        for inner_v in inner_jaxpr.outvars:
            if isinstance(inner_v, Literal):
                out_names.append(self.g.const(np.asarray(inner_v.val)))
            else:
                out_names.append(self.g.name_of(inner_v))

        for v in owned:
            self.g.var_names.pop(v, None)
        self.g.var_names.update(saved)
        return out_names

    def _inline(self, eqn, inner_jaxpr, consts):
        out_names = self._inline_body(
            inner_jaxpr, consts,
            [self.g.name_of(a) for a in eqn.invars])
        for outer_v, nm in zip(eqn.outvars, out_names):
            self.g.var_names[outer_v] = nm

    def _op_scan(self, eqn):
        """lax.scan UNROLLED (static length — the RNN/LSTM/GRU layer
        family's time loop): each step inlines the body with the carries
        threaded through and xs[t] sliced out; stacked ys re-assemble with
        Concat. The unrolled form needs no ONNX Loop subgraph and the
        numpy re-executor verifies it like any other graph (the
        reference's paddle2onnx emits recurrent layers as fused ONNX
        LSTM/GRU kernels — an unrolled graph trades file size for exact
        per-step parity with the traced model)."""
        closed = eqn.params["jaxpr"]
        inner = closed.jaxpr
        nc = int(eqn.params["num_consts"])
        nk = int(eqn.params["num_carry"])
        L = int(eqn.params["length"])
        rev = bool(eqn.params.get("reverse", False))
        const_names = [self.g.name_of(a) for a in eqn.invars[:nc]]
        carry_names = [self.g.name_of(a) for a in eqn.invars[nc:nc + nk]]
        xs = eqn.invars[nc + nk:]
        ax0 = self.g.const(np.asarray([0], np.int64))
        one = self.g.const(np.asarray([1], np.int64))
        n_ys = len(eqn.outvars) - nk
        ys_steps = [[] for _ in range(n_ys)]
        order = range(L - 1, -1, -1) if rev else range(L)
        for t in order:
            x_names = []
            for xv in xs:
                sl = self.g.add("Slice", [
                    self.g.name_of(xv),
                    self.g.const(np.asarray([t], np.int64)),
                    self.g.const(np.asarray([t + 1], np.int64)),
                    ax0, one])
                step_shape = list(xv.aval.shape[1:])
                x_names.append(self.g.add(
                    "Reshape", [sl, self.g.shape_const(step_shape)]))
            outs = self._inline_body(inner, closed.consts,
                                     const_names + carry_names + x_names)
            carry_names = outs[:nk]
            for i, y in enumerate(outs[nk:]):
                yv = eqn.outvars[nk + i]
                ys_steps[i].append(self.g.add(
                    "Reshape", [y, self.g.shape_const(
                        [1] + list(yv.aval.shape[1:]))]))
        for ov, nm in zip(eqn.outvars[:nk], carry_names):
            self.g.var_names[ov] = nm
        for i, ov in enumerate(eqn.outvars[nk:]):
            steps = ys_steps[i][::-1] if rev else ys_steps[i]
            if len(steps) == 1:
                self.g.var_names[ov] = steps[0]
            else:
                self.g.var_names[ov] = self.g.add(
                    "Concat", steps, attrs={"axis": 0})

    def _op_pjit(self, eqn):
        closed = eqn.params["jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    _op_jit = _op_pjit
    _op_closed_call = _op_pjit

    def _op_custom_jvp_call(self, eqn):
        closed = eqn.params["call_jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    def _op_custom_vjp_call(self, eqn):
        closed = eqn.params["call_jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    def _op_remat2(self, eqn):
        self._inline(eqn, eqn.params["jaxpr"], ())

    _op_checkpoint = _op_remat2

    # -- structural ----------------------------------------------------------
    def _op_copy(self, eqn):
        self.g.add("Identity", [self.g.name_of(eqn.invars[0])],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    _op_stop_gradient = _op_copy
    _op_copy_p = _op_copy

    def _op_convert_element_type(self, eqn):
        to = proto.NP_TO_ONNX[str(np.dtype(eqn.params["new_dtype"]))]
        self.g.add("Cast", [self.g.name_of(eqn.invars[0])],
                   attrs={"to": to},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reshape(self, eqn):
        if eqn.params.get("dimensions") is not None:
            raise UnsupportedOpError("reshape with dimension permutation")
        shape = self.g.shape_const(eqn.params["new_sizes"])
        self.g.add("Reshape", [self.g.name_of(eqn.invars[0]), shape],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_transpose(self, eqn):
        perm = [int(d) for d in eqn.params["permutation"]]
        self.g.add("Transpose", [self.g.name_of(eqn.invars[0])],
                   attrs={"perm": perm},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_broadcast_in_dim(self, eqn):
        (x,) = eqn.invars
        out_shape = [int(d) for d in eqn.params["shape"]]
        bdims = [int(d) for d in eqn.params["broadcast_dimensions"]]
        in_shape = list(x.aval.shape)
        # place each input dim at its broadcast position, 1 elsewhere
        mid = [1] * len(out_shape)
        for src, dst in enumerate(bdims):
            mid[dst] = in_shape[src]
        nm = self.g.name_of(x)
        if mid != in_shape or len(mid) != len(in_shape):
            nm = self.g.add("Reshape", [nm, self.g.shape_const(mid)])
        self.g.add("Expand", [nm, self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_concatenate(self, eqn):
        ins = [self.g.name_of(v) for v in eqn.invars]
        self.g.add("Concat", ins,
                   attrs={"axis": int(eqn.params["dimension"])},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_split(self, eqn):
        sizes = [int(s) for s in eqn.params["sizes"]]
        split = self.g.const(np.asarray(sizes, np.int64))
        self.g.add("Split", [self.g.name_of(eqn.invars[0]), split],
                   n_out=len(eqn.outvars),
                   attrs={"axis": int(eqn.params["axis"])},
                   out_names=[self.g.name_of(v) for v in eqn.outvars])

    def _op_squeeze(self, eqn):
        out_shape = list(eqn.outvars[0].aval.shape)
        self.g.add("Reshape", [self.g.name_of(eqn.invars[0]),
                               self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    _op_expand_dims = _op_squeeze

    def _op_slice(self, eqn):
        starts = [int(s) for s in eqn.params["start_indices"]]
        ends = [int(s) for s in eqn.params["limit_indices"]]
        strides = eqn.params.get("strides")
        steps = ([int(s) for s in strides] if strides is not None
                 else [1] * len(starts))
        axes = list(range(len(starts)))
        shape = [int(d) for d in eqn.invars[0].aval.shape]
        # starts/ends via shape_const (no value-dedup): limit_indices carry
        # the batch size on full-span axes and must stay structurally
        # aligned across the dynamic-batch two-trace diff
        ends_nm = self.g.shape_const(ends)
        # record which entries are FULL-SPAN slices of their axis — the
        # only entries the dynamic-batch rewrite may soundly replace with
        # INT64_MAX (a partial-span batch-tracking end has no faithful
        # symbolic form; the rewrite raises rather than rely on the
        # optional validator to catch the corruption)
        self.g.ends_full_span[ends_nm] = tuple(
            s == 0 and e == d and st == 1
            for s, e, d, st in zip(starts, ends, shape, steps))
        ins = [self.g.name_of(eqn.invars[0]),
               self.g.shape_const(starts),
               ends_nm,
               self.g.shape_const(axes),
               self.g.shape_const(steps)]
        self.g.add("Slice", ins,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_dynamic_slice(self, eqn):
        """dynamic_slice -> Slice with per-axis start/end parts. Full-span
        axes (size == dim — e.g. the batch axis of x[:, -1]) get the
        static pair (0, INT64_MAX), which is batch-size-independent by
        construction, so this lowering needs no dynamic-batch rewrite at
        all. Partial axes reproduce jax's start clamping
        (start <- clip(start, 0, dim - size)) with Cast+Clip on the traced
        start scalar, then end = start + size."""
        sizes = [int(s) for s in eqn.params["slice_sizes"]]
        shape = [int(d) for d in eqn.invars[0].aval.shape]
        i64max = np.iinfo(np.int64).max
        one_shape = self.g.shape_const([1])
        start_parts, end_parts = [], []
        for a, z, d in zip(eqn.invars[1:], sizes, shape):
            if z == d:                      # full span: static, batch-free
                start_parts.append(self.g.const(np.zeros(1, np.int64)))
                end_parts.append(self.g.const(
                    np.asarray([i64max], np.int64)))
                continue
            s64 = self.g.add("Cast", [self.g.name_of(a)],
                             attrs={"to": proto.NP_TO_ONNX["int64"]})
            clipped = self.g.add("Clip", [
                s64, self.g.const(np.asarray(0, np.int64)),
                self.g.const(np.asarray(d - z, np.int64))])
            s_vec = self.g.add("Reshape", [clipped, one_shape])
            start_parts.append(s_vec)
            end_parts.append(self.g.add(
                "Add", [s_vec, self.g.const(np.asarray([z], np.int64))]))
        ndim = len(shape)
        starts_t = start_parts[0] if ndim == 1 else \
            self.g.add("Concat", start_parts, attrs={"axis": 0})
        ends_t = end_parts[0] if ndim == 1 else \
            self.g.add("Concat", end_parts, attrs={"axis": 0})
        ins = [self.g.name_of(eqn.invars[0]), starts_t, ends_t,
               self.g.shape_const(list(range(ndim))),
               self.g.shape_const([1] * ndim)]
        self.g.add("Slice", ins,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_rev(self, eqn):
        # reverse along dims == Slice with step -1 on those axes
        dims = [int(d) for d in eqn.params["dimensions"]]
        n = len(dims)
        ins = [self.g.name_of(eqn.invars[0]),
               self.g.const(np.asarray([-1] * n, np.int64)),
               self.g.const(np.asarray([np.iinfo(np.int64).min] * n,
                                       np.int64)),
               self.g.const(np.asarray(dims, np.int64)),
               self.g.const(np.asarray([-1] * n, np.int64))]
        self.g.add("Slice", ins,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_pad(self, eqn):
        x, pad_val = eqn.invars
        cfg = eqn.params["padding_config"]
        if any(int(i) != 0 for _, _, i in cfg):
            raise UnsupportedOpError("pad with interior (dilation) padding")
        if any(int(lo) < 0 or int(hi) < 0 for lo, hi, _ in cfg):
            raise UnsupportedOpError("negative (cropping) pad")
        pads = ([int(lo) for lo, _, _ in cfg]
                + [int(hi) for _, hi, _ in cfg])
        ins = [self.g.name_of(x),
               self.g.const(np.asarray(pads, np.int64)),
               self.g.name_of(pad_val)]
        self.g.add("Pad", ins, attrs={"mode": b"constant"},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_iota(self, eqn):
        # static shapes: an iota is a compile-time constant — bake it
        shape = tuple(int(d) for d in eqn.params["shape"])
        dim = int(eqn.params["dimension"])
        dt = np.dtype(eqn.params["dtype"])
        ar = np.arange(shape[dim], dtype=dt)
        ar = np.broadcast_to(
            ar.reshape([-1 if i == dim else 1 for i in range(len(shape))]),
            shape)
        self.g.var_names[eqn.outvars[0]] = self.g.const(np.ascontiguousarray(ar))

    def _op_select_n(self, eqn):
        pred, *cases = eqn.invars
        if len(cases) != 2:
            raise UnsupportedOpError("select_n with >2 cases")
        if str(np.dtype(pred.aval.dtype)) != "bool":
            raise UnsupportedOpError("select_n with integer predicate")
        # select_n: False -> cases[0]; Where: cond True -> first branch
        self.g.add("Where", [self.g.name_of(pred),
                             self.g.name_of(cases[1]),
                             self.g.name_of(cases[0])],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_clamp(self, eqn):
        lo, x, hi = eqn.invars
        m = self.g.add("Max", [self.g.name_of(x), self.g.name_of(lo)])
        self.g.add("Min", [m, self.g.name_of(hi)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    # -- math that needs decomposition ---------------------------------------
    def _op_square(self, eqn):
        nm = self.g.name_of(eqn.invars[0])
        self.g.add("Mul", [nm, nm],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_rsqrt(self, eqn):
        s = self.g.add("Sqrt", [self.g.name_of(eqn.invars[0])])
        self.g.add("Reciprocal", [s],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_erfc(self, eqn):
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        e = self.g.add("Erf", [self.g.name_of(eqn.invars[0])])
        one = self.g.const(np.asarray(1, dt))
        self.g.add("Sub", [one, e],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_integer_pow(self, eqn):
        y = int(eqn.params["y"])
        nm = self.g.name_of(eqn.invars[0])
        out = self.g.name_of(eqn.outvars[0])
        if y == 2:
            self.g.add("Mul", [nm, nm], out_names=[out])
        else:
            dt = np.dtype(eqn.invars[0].aval.dtype)
            self.g.add("Pow", [nm, self.g.const(np.asarray(y, dt))],
                       out_names=[out])

    def _op_ne(self, eqn):
        ins = [self.g.name_of(v) for v in eqn.invars]
        e = self.g.add("Equal", ins)
        self.g.add("Not", [e], out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reduce_sum(self, eqn):
        if not len(eqn.params["axes"]):  # identity; an empty axes INPUT
            # means reduce-all in ONNX (noop_with_empty_axes defaults to 0)
            self.g.add("Identity", [self.g.name_of(eqn.invars[0])],
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        axes = self.g.const(
            np.asarray([int(a) for a in eqn.params["axes"]], np.int64))
        self.g.add("ReduceSum", [self.g.name_of(eqn.invars[0]), axes],
                   attrs={"keepdims": 0},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_argmax(self, eqn):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise UnsupportedOpError("argmax over multiple axes")
        out_dt = proto.NP_TO_ONNX[str(np.dtype(eqn.params["index_dtype"]))]
        a = self.g.add("ArgMax", [self.g.name_of(eqn.invars[0])],
                       attrs={"axis": int(axes[0]), "keepdims": 0})
        self.g.add("Cast", [a], attrs={"to": out_dt},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_argmin(self, eqn):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise UnsupportedOpError("argmin over multiple axes")
        out_dt = proto.NP_TO_ONNX[str(np.dtype(eqn.params["index_dtype"]))]
        a = self.g.add("ArgMin", [self.g.name_of(eqn.invars[0])],
                       attrs={"axis": int(axes[0]), "keepdims": 0})
        self.g.add("Cast", [a], attrs={"to": out_dt},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_cumsum(self, eqn):
        ax = self.g.const(np.asarray(int(eqn.params["axis"]), np.int64))
        self.g.add("CumSum", [self.g.name_of(eqn.invars[0]), ax],
                   attrs={"reverse": 1 if eqn.params.get("reverse") else 0},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    # -- the big three: dot_general / conv / reduce_window -------------------
    def _op_dot_general(self, eqn):
        lhs, rhs = eqn.invars
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc, lb, rb = (list(map(int, d)) for d in (lc, rc, lb, rb))
        lshape, rshape = list(lhs.aval.shape), list(rhs.aval.shape)
        lf = [d for d in range(len(lshape)) if d not in lc + lb]
        rf = [d for d in range(len(rshape)) if d not in rc + rb]

        lnm, rnm = self.g.name_of(lhs), self.g.name_of(rhs)
        # fast path: plain 2D matmul already in [M,K] x [K,N] layout
        if (not lb and len(lshape) == 2 and len(rshape) == 2
                and lc == [1] and rc == [0]):
            self.g.add("MatMul", [lnm, rnm],
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return

        def prod(dims, shape):
            out = 1
            for d in dims:
                out *= shape[d]
            return out

        bdims = [lshape[d] for d in lb]
        m, k = prod(lf, lshape), prod(lc, lshape)
        n = prod(rf, rshape)
        # lhs -> [B..., M, K]
        perm_l = lb + lf + lc
        if perm_l != list(range(len(lshape))):
            lnm = self.g.add("Transpose", [lnm], attrs={"perm": perm_l})
        lnm = self.g.add("Reshape", [lnm, self.g.shape_const(bdims + [m, k])])
        # rhs -> [B..., K, N]
        perm_r = rb + rc + rf
        if perm_r != list(range(len(rshape))):
            rnm = self.g.add("Transpose", [rnm], attrs={"perm": perm_r})
        rnm = self.g.add("Reshape", [rnm, self.g.shape_const(bdims + [k, n])])
        mm = self.g.add("MatMul", [lnm, rnm])
        out_shape = (bdims + [lshape[d] for d in lf]
                     + [rshape[d] for d in rf])
        self.g.add("Reshape", [mm, self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_conv_general_dilated(self, eqn):
        x, w = eqn.invars
        p = eqn.params
        dn = p["dimension_numbers"]
        nd = len(x.aval.shape)
        spatial = list(range(2, nd))
        if (tuple(dn.lhs_spec) != tuple([0, 1] + spatial)
                or tuple(dn.rhs_spec) != tuple([0, 1] + spatial)
                or tuple(dn.out_spec) != tuple([0, 1] + spatial)):
            raise UnsupportedOpError(
                "conv with non-NCHW/OIHW dimension numbers")
        if any(int(d) != 1 for d in p["lhs_dilation"]):
            raise UnsupportedOpError("transposed conv (lhs_dilation != 1)")
        if int(p.get("batch_group_count", 1)) != 1:
            raise UnsupportedOpError("batch_group_count != 1")
        pads = ([int(lo) for lo, _ in p["padding"]]
                + [int(hi) for _, hi in p["padding"]])
        attrs = {
            "strides": [int(s) for s in p["window_strides"]],
            "pads": pads,
            "dilations": [int(d) for d in p["rhs_dilation"]],
            "group": int(p["feature_group_count"]),
            "kernel_shape": [int(w.aval.shape[d]) for d in spatial],
        }
        self.g.add("Conv", [self.g.name_of(x), self.g.name_of(w)],
                   attrs=attrs,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _pool_common(self, eqn):
        p = eqn.params
        win = [int(d) for d in p["window_dimensions"]]
        strides = [int(d) for d in p["window_strides"]]
        padding = [(int(lo), int(hi)) for lo, hi in p["padding"]]
        if win[0] != 1 or win[1] != 1:
            raise UnsupportedOpError(
                "reduce_window over batch/channel dims (not NCHW pooling)")
        if strides[:2] != [1, 1] or padding[:2] != [(0, 0), (0, 0)]:
            raise UnsupportedOpError(
                "reduce_window with stride/pad on batch or channel dims")
        if any(int(d) != 1 for d in p.get("base_dilation", [1] * len(win))):
            raise UnsupportedOpError("reduce_window with base dilation")
        if any(int(d) != 1 for d in p.get("window_dilation", [1] * len(win))):
            raise UnsupportedOpError("reduce_window with window dilation")
        pads = ([lo for lo, _ in padding[2:]] + [hi for _, hi in padding[2:]])
        attrs = {"kernel_shape": win[2:], "strides": strides[2:],
                 "pads": pads}
        return attrs, win

    def _op_reduce_window_max(self, eqn):
        attrs, _ = self._pool_common(eqn)
        self.g.add("MaxPool", [self.g.name_of(eqn.invars[0])], attrs=attrs,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reduce_window_sum(self, eqn):
        attrs, win = self._pool_common(eqn)
        attrs["count_include_pad"] = 1
        ap = self.g.add("AveragePool", [self.g.name_of(eqn.invars[0])],
                        attrs=attrs)
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        k = self.g.const(np.asarray(float(np.prod(win)), dt))
        self.g.add("Mul", [ap, k],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_gather(self, eqn):
        operand, indices = eqn.invars
        dn = eqn.params["dimension_numbers"]
        slice_sizes = [int(s) for s in eqn.params["slice_sizes"]]
        oshape = list(operand.aval.shape)
        ishape = list(indices.aval.shape)
        # the jnp.take(x, ids, axis=a) lowering: one collapsed slice dim at
        # axis a, start_index_map == (a,), full slices elsewhere, index
        # vector as the trailing dim of `indices`
        if (len(dn.start_index_map) != 1
                or list(dn.collapsed_slice_dims) != list(dn.start_index_map)
                or getattr(dn, "operand_batching_dims", ())):
            raise UnsupportedOpError(
                f"gather with general dimension_numbers {dn}")
        axis = int(dn.start_index_map[0])
        expect = list(oshape)
        expect[axis] = 1
        if slice_sizes != expect:
            raise UnsupportedOpError(
                f"gather with partial slice sizes {slice_sizes}")
        if ishape[-1] != 1:
            raise UnsupportedOpError("gather with multi-dim index vectors")
        # offset dims must be the trailing dims (take semantics)
        n_batch = len(ishape) - 1
        out_rank = n_batch + len(oshape) - 1
        if list(dn.offset_dims) != list(range(n_batch, out_rank)):
            raise UnsupportedOpError("gather with interleaved offset dims")
        if axis != 0:
            raise UnsupportedOpError("gather along non-leading axis")
        idx = self.g.add("Reshape", [self.g.name_of(indices),
                                     self.g.shape_const(ishape[:-1])])
        self.g.add("Gather", [self.g.name_of(operand), idx],
                   attrs={"axis": axis},
                   out_names=[self.g.name_of(eqn.outvars[0])])


def _convert_once(pure_fn, params_flat_named, arrs, names):
    """One trace+convert pass; returns (conv, out_vars, out_names)."""
    import jax

    closed = jax.make_jaxpr(
        lambda ps, *xs: pure_fn(ps, *xs))(
            [v for _, v in params_flat_named], *arrs)

    conv = Converter()
    jaxpr = closed.jaxpr
    n_params = len(params_flat_named)
    for var, (pname, pval) in zip(jaxpr.invars[:n_params],
                                  params_flat_named):
        conv.g.var_names[var] = conv.g.const(np.asarray(pval), name=pname)
    graph_in_vars, out_vars, out_names = conv.convert_jaxpr(closed, names)

    # a graph output must be a unique node-produced name: passthrough
    # outputs (an input, an initializer, or a repeated var) get an Identity
    seen = set()
    for i, nm in enumerate(out_names):
        if nm not in conv.g.produced or nm in seen:
            out_names[i] = conv.g.add("Identity", [nm])
        seen.add(out_names[i])
    return conv, out_vars, out_names


def _batch_polymorphic_rewrite(conv, conv2):
    """Make the traced graph batch-size-polymorphic by DIFFING two traces
    (batch B vs B+1): structurally identical graphs whose only differences
    are batch-carrying shape constants get those constants rewritten to
    ONNX's symbolic forms — Reshape targets to 0 (copy input dim) or a
    single -1 (infer, covers flattened B*k dims), Expand shapes to 1
    (two-way broadcast keeps the input's dim). Anything else that differs
    means the model genuinely computes with the batch size; raise rather
    than emit a graph that would be silently wrong at other batches. The
    export validator re-executes at BOTH batch sizes afterwards, so a
    rewrite this diff got wrong cannot ship."""
    g1, g2 = conv.g, conv2.g
    if len(g1.node_specs) != len(g2.node_specs) or \
            len(g1.initializers) != len(g2.initializers):
        raise UnsupportedOpError(
            "dynamic batch: traced graph structure depends on the batch "
            "size (node/initializer counts differ between batch traces)")
    if g1.node_specs != g2.node_specs:
        raise UnsupportedOpError(
            "dynamic batch: node wiring depends on the batch size")
    by_index = {idx: (nm, arr) for nm, (idx, arr) in g1.init_arrays.items()}
    for nm, (idx, a2) in g2.init_arrays.items():
        nm1, a1 = by_index[idx]
        if nm1 != nm:
            raise UnsupportedOpError(
                "dynamic batch: initializer naming depends on batch size")
        same_meta = a1.shape == a2.shape and a1.dtype == a2.dtype
        eq_nan = np.issubdtype(a1.dtype, np.floating)  # NaN consts (masks)
        if same_meta and np.array_equal(a1, a2, equal_nan=eq_nan):
            continue
        cons = g1.consumers.get(nm, set())
        # rewritable ONLY as the SHAPE operand (position 1) of Reshape/
        # Expand or the ENDS operand (position 2) of Slice — the same
        # values as a DATA operand anywhere would be silently corrupted
        ok_shape = (a1.dtype == np.int64 and a1.ndim == 1
                    and a1.shape == a2.shape)
        ops = {op for op, _ in cons}
        positions_ok = cons and all(
            (op in ("Reshape", "Expand") and pos == 1)
            or (op == "Slice" and pos == 2)
            for op, pos in cons)
        if not ok_shape or not positions_ok or len(ops) != 1:
            raise UnsupportedOpError(
                f"dynamic batch: constant {nm} (consumed by {sorted(cons)})"
                " differs between batch traces and is not a rewritable "
                "shape vector — the model is not batch-polymorphic")
        diff = [i for i in range(a1.size) if a1[i] != a2[i]]
        new = a1.copy()
        if ops == {"Reshape"}:
            if len(diff) == 1:
                new[diff[0]] = -1          # infer: covers B and B*k dims
            else:
                for i in diff:
                    new[i] = 0             # copy input dim at that index
        elif ops == {"Expand"}:
            for i in diff:
                new[i] = 1                 # two-way broadcast keeps input
        else:  # Slice ends: INT64_MAX ("through the end") is sound ONLY
            # for entries _op_slice recorded as FULL-SPAN in both traces —
            # a partial-span batch-tracking end (x[:-1]) has no faithful
            # symbolic form and must raise even under validate=False
            fs1 = g1.ends_full_span.get(nm, ())
            fs2 = g2.ends_full_span.get(nm, ())
            if not all(i < len(fs1) and fs1[i] and i < len(fs2) and fs2[i]
                       for i in diff):
                raise UnsupportedOpError(
                    f"dynamic batch: Slice end constant {nm} tracks the "
                    "batch size through a PARTIAL-span slice — not "
                    "batch-polymorphic")
            for i in diff:
                new[i] = np.iinfo(np.int64).max
        conv.g.replace_const(nm, new)


def _dedup_initializers(g):
    """Merge byte-identical const_* initializers and remap node inputs.
    Runs AFTER the dynamic-batch rewrite (shape_const skips value-dedup at
    creation so the two-trace diff stays structurally aligned; the
    unrolled-scan path would otherwise ship one identical shape vector
    per timestep). Named weights are never merged."""
    canon, rename = {}, {}
    new_inits, new_arrays = [], {}
    ordered = sorted(g.init_arrays.items(), key=lambda kv: kv[1][0])
    for nm, (_, arr) in ordered:
        if nm.startswith("const_"):
            key = (str(arr.dtype), arr.shape, arr.tobytes())
            if key in canon:
                rename[nm] = canon[key]
                continue
            canon[key] = nm
        new_arrays[nm] = (len(new_inits), arr)
        new_inits.append(proto.tensor_proto(nm, arr))
    g.initializers = new_inits
    g.init_arrays = new_arrays
    if rename:
        for spec in g.node_specs:
            spec[1] = [rename.get(nm, nm) for nm in spec[1]]


def convert(pure_fn, params_flat_named, example_args, input_names=None,
            model_name="model", dynamic_batch_axes=None):
    """Trace pure_fn(params_list, *args) and convert to ONNX model bytes.

    params_flat_named: list of (name, np.ndarray) weights — become graph
    initializers. example_args: example input arrays (fix the traced
    shapes). dynamic_batch_axes: list of bool per input — True marks the
    input's axis 0 as the symbolic batch dimension 'N' (the reference
    delegates dynamic axes to paddle2onnx; here a second trace at batch+1
    proves the graph is batch-polymorphic and batch-carrying shape
    constants are rewritten to symbolic forms — see
    _batch_polymorphic_rewrite).
    """
    arrs = [np.asarray(a) for a in example_args]
    names = list(input_names or [f"input_{i}" for i in range(len(arrs))])
    dyn = list(dynamic_batch_axes or [])
    conv, out_vars, out_names = _convert_once(
        pure_fn, params_flat_named, arrs, names)

    # out_dyn_syms[i]: axis -> symbolic name. 'N' ONLY when the axis IS the
    # batch dimension (size B in one trace, B+1 in the other); other
    # batch-dependent sizes (a flattened B*T, say) get their own distinct
    # symbol so downstream shape inference can't unify contradictions.
    out_dyn_syms = [dict() for _ in out_vars]
    if any(dyn):
        b1 = next(a.shape[0] for a, d in zip(arrs, dyn) if d)
        arrs2 = [np.concatenate([a, a[:1]], axis=0) if d else a
                 for a, d in zip(arrs, dyn)]
        conv2, out_vars2, _ = _convert_once(
            pure_fn, params_flat_named, arrs2, names)
        _batch_polymorphic_rewrite(conv, conv2)
        for i, (ov, ov2) in enumerate(zip(out_vars, out_vars2)):
            s1, s2 = tuple(ov.aval.shape), tuple(ov2.aval.shape)
            if len(s1) != len(s2):
                raise UnsupportedOpError(
                    "dynamic batch: output rank depends on batch size")
            for a in range(len(s1)):
                if s1[a] != s2[a]:
                    sym = "N" if (s1[a], s2[a]) == (b1, b1 + 1) \
                        else f"dyn_{i}_{a}"
                    out_dyn_syms[i][a] = sym
    _dedup_initializers(conv.g)

    def _dims(shape, syms):
        return [syms.get(a, int(d)) for a, d in enumerate(shape)]

    in_infos = []
    for i, (nm, a) in enumerate(zip(names, arrs)):
        syms = {0: "N"} if (i < len(dyn) and dyn[i]) else {}
        in_infos.append(proto.value_info(
            nm, proto.NP_TO_ONNX[str(a.dtype)], _dims(a.shape, syms)))
    out_infos = []
    for ov, nm, syms in zip(out_vars, out_names, out_dyn_syms):
        out_infos.append(proto.value_info(
            nm, proto.NP_TO_ONNX[str(np.dtype(ov.aval.dtype))],
            _dims(tuple(int(d) for d in ov.aval.shape), syms)))
    graph = proto.graph_proto(model_name, conv.g.build_nodes(),
                              conv.g.initializers, in_infos, out_infos)
    return proto.model_proto(graph)
