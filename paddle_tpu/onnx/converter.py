"""jaxpr → ONNX graph converter.

TPU-native design: instead of an op-by-op layer converter (paddle2onnx's
approach over the reference's Program protobuf), the layer is traced ONCE to
a jaxpr — the same functional trace jit/export use — and each jax primitive
is lowered to standard ONNX ops (opset 13). Composite layers therefore export
as their mathematical decomposition (LayerNorm → ReduceMean/Sub/Div chain,
softmax → max/exp/sum/div), which any ONNX runtime executes without custom
domains. Reference parity target: python/paddle/onnx/export.py:21.

Supported primitive set covers the traced graphs of LeNet, ResNet, and the
GPT block family (Conv/MatMul/Relu-as-Max/Gelu-as-Erf/softmax chain/
LayerNorm chain/MaxPool/Reshape/Transpose/Add/Gather...). Unsupported
primitives raise UnsupportedOpError naming the primitive.
"""
import numpy as np

from . import proto


class UnsupportedOpError(RuntimeError):
    pass


def _np_dtype(aval):
    return str(np.dtype(aval.dtype))


class _Graph:
    """Accumulates ONNX nodes/initializers with SSA naming."""

    def __init__(self):
        self.nodes = []
        self.initializers = []
        self._init_names = set()
        self.var_names = {}     # jax Var -> onnx value name
        self.produced = set()   # names produced by a node (not init/input)
        self._value_cache = {}  # (dtype, shape, bytes) -> initializer name
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, atom):
        """ONNX value name for a jaxpr atom (Var or Literal)."""
        from jax._src.core import Literal

        if isinstance(atom, Literal):
            return self.const(np.asarray(atom.val))
        if atom not in self.var_names:
            self.var_names[atom] = self.fresh("v")
        return self.var_names[atom]

    def const(self, array, name=None):
        arr = np.asarray(array)
        if name is None:
            # dedup small constants by value: jaxpr Literals repeat the
            # same scalars (1.0, 0.5, sqrt(2)...) once per layer
            if arr.size <= 64:
                key = (str(arr.dtype), arr.shape, arr.tobytes())
                cached = self._value_cache.get(key)
                if cached is not None:
                    return cached
                name = self.fresh("const")
                self._value_cache[key] = name
            else:
                name = self.fresh("const")
        if name not in self._init_names:
            self._init_names.add(name)
            self.initializers.append(proto.tensor_proto(name, arr))
        return name

    def shape_const(self, dims):
        return self.const(np.asarray(dims, np.int64))

    def add(self, op_type, inputs, n_out=1, attrs=None, out_names=None):
        outs = out_names or [self.fresh(op_type.lower())
                             for _ in range(n_out)]
        self.nodes.append(proto.node_proto(
            op_type, inputs, outs, name=self.fresh(f"n_{op_type}"),
            attrs=attrs))
        self.produced.update(outs)
        return outs if n_out != 1 or out_names else outs[0]


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "max": "Max",
    "min": "Min", "pow": "Pow", "neg": "Neg", "exp": "Exp", "log": "Log",
    "sqrt": "Sqrt", "tanh": "Tanh", "logistic": "Sigmoid", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "erf": "Erf",
    "sin": "Sin", "cos": "Cos", "not": "Not", "and": "And", "or": "Or",
}
_COMPARE = {"lt": "Less", "le": "LessOrEqual", "gt": "Greater",
            "ge": "GreaterOrEqual", "eq": "Equal"}
_REDUCE_ATTR_AXES = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                     "reduce_prod": "ReduceProd"}


class Converter:
    def __init__(self):
        self.g = _Graph()

    # -- entry ---------------------------------------------------------------
    def convert_jaxpr(self, closed_jaxpr, input_names):
        """closed_jaxpr: jax ClosedJaxpr whose first invars are weights
        (callers pass them via env pre-binding), remaining are graph inputs.
        input_names: names for the GRAPH inputs (last len(input_names)
        invars). Weights invars must already be bound in self.g.var_names
        (as initializers)."""
        jaxpr = closed_jaxpr.jaxpr
        for var, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
            self.g.var_names[var] = self.g.const(np.asarray(cval))
        n_in = len(input_names)
        graph_inputs = jaxpr.invars[len(jaxpr.invars) - n_in:]
        for var, nm in zip(graph_inputs, input_names):
            self.g.var_names[var] = nm
        self._eqns(jaxpr.eqns)
        out_names = []
        for ov in jaxpr.outvars:
            nm = self.g.name_of(ov)
            out_names.append(nm)
        return graph_inputs, jaxpr.outvars, out_names

    def _eqns(self, eqns):
        for eqn in eqns:
            self._eqn(eqn)

    # -- dispatch ------------------------------------------------------------
    def _eqn(self, eqn):
        p = eqn.primitive.name
        handler = getattr(self, f"_op_{p}", None)
        if handler is not None:
            return handler(eqn)
        if p in _ELEMENTWISE:
            ins = [self.g.name_of(v) for v in eqn.invars]
            self.g.add(_ELEMENTWISE[p], ins,
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        if p in _COMPARE:
            ins = [self.g.name_of(v) for v in eqn.invars]
            self.g.add(_COMPARE[p], ins,
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        if p in _REDUCE_ATTR_AXES:
            (x,) = eqn.invars
            axes = [int(a) for a in eqn.params["axes"]]
            if not axes:  # reduce over no axes is the identity (ONNX's
                # empty-axes attr means reduce-ALL with noop_with_empty_axes
                # unset, so it cannot express this case directly)
                self.g.add("Identity", [self.g.name_of(x)],
                           out_names=[self.g.name_of(eqn.outvars[0])])
                return
            self.g.add(_REDUCE_ATTR_AXES[p], [self.g.name_of(x)],
                       attrs={"axes": axes, "keepdims": 0},
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        raise UnsupportedOpError(
            f"paddle_tpu.onnx: no ONNX lowering for jax primitive '{p}' "
            f"(eqn: {eqn})")

    # -- call-like primitives: inline ---------------------------------------
    def _inline(self, eqn, inner_jaxpr, consts):
        """Inline a sub-jaxpr with PROPER SCOPING: jax caches and SHARES
        jaxpr objects (two relu eqns carry the identical call_jaxpr with
        the same Var objects), so the inner vars' name bindings must be
        saved/cleared per inline and restored after — otherwise the second
        inline of a shared jaxpr silently reuses the first one's SSA names
        and two nodes write the same output."""
        from jax._src.core import Literal

        owned = list(inner_jaxpr.invars) + list(inner_jaxpr.constvars)
        for e in inner_jaxpr.eqns:
            owned.extend(e.outvars)   # nested sub-jaxprs scope themselves
        saved = {v: self.g.var_names[v] for v in owned
                 if v in self.g.var_names}
        for v in owned:
            self.g.var_names.pop(v, None)

        for var, cval in zip(inner_jaxpr.constvars, consts):
            self.g.var_names[var] = self.g.const(np.asarray(cval))
        for inner_v, outer_atom in zip(inner_jaxpr.invars, eqn.invars):
            self.g.var_names[inner_v] = self.g.name_of(outer_atom)
        self._eqns(inner_jaxpr.eqns)
        out_names = []
        for inner_v in inner_jaxpr.outvars:
            if isinstance(inner_v, Literal):
                out_names.append(self.g.const(np.asarray(inner_v.val)))
            else:
                out_names.append(self.g.name_of(inner_v))

        for v in owned:
            self.g.var_names.pop(v, None)
        self.g.var_names.update(saved)
        for outer_v, nm in zip(eqn.outvars, out_names):
            self.g.var_names[outer_v] = nm

    def _op_pjit(self, eqn):
        closed = eqn.params["jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    _op_jit = _op_pjit
    _op_closed_call = _op_pjit

    def _op_custom_jvp_call(self, eqn):
        closed = eqn.params["call_jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    def _op_custom_vjp_call(self, eqn):
        closed = eqn.params["call_jaxpr"]
        self._inline(eqn, closed.jaxpr, closed.consts)

    def _op_remat2(self, eqn):
        self._inline(eqn, eqn.params["jaxpr"], ())

    _op_checkpoint = _op_remat2

    # -- structural ----------------------------------------------------------
    def _op_copy(self, eqn):
        self.g.add("Identity", [self.g.name_of(eqn.invars[0])],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    _op_stop_gradient = _op_copy
    _op_copy_p = _op_copy

    def _op_convert_element_type(self, eqn):
        to = proto.NP_TO_ONNX[str(np.dtype(eqn.params["new_dtype"]))]
        self.g.add("Cast", [self.g.name_of(eqn.invars[0])],
                   attrs={"to": to},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reshape(self, eqn):
        if eqn.params.get("dimensions") is not None:
            raise UnsupportedOpError("reshape with dimension permutation")
        shape = self.g.shape_const(eqn.params["new_sizes"])
        self.g.add("Reshape", [self.g.name_of(eqn.invars[0]), shape],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_transpose(self, eqn):
        perm = [int(d) for d in eqn.params["permutation"]]
        self.g.add("Transpose", [self.g.name_of(eqn.invars[0])],
                   attrs={"perm": perm},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_broadcast_in_dim(self, eqn):
        (x,) = eqn.invars
        out_shape = [int(d) for d in eqn.params["shape"]]
        bdims = [int(d) for d in eqn.params["broadcast_dimensions"]]
        in_shape = list(x.aval.shape)
        # place each input dim at its broadcast position, 1 elsewhere
        mid = [1] * len(out_shape)
        for src, dst in enumerate(bdims):
            mid[dst] = in_shape[src]
        nm = self.g.name_of(x)
        if mid != in_shape or len(mid) != len(in_shape):
            nm = self.g.add("Reshape", [nm, self.g.shape_const(mid)])
        self.g.add("Expand", [nm, self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_concatenate(self, eqn):
        ins = [self.g.name_of(v) for v in eqn.invars]
        self.g.add("Concat", ins,
                   attrs={"axis": int(eqn.params["dimension"])},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_split(self, eqn):
        sizes = [int(s) for s in eqn.params["sizes"]]
        split = self.g.const(np.asarray(sizes, np.int64))
        self.g.add("Split", [self.g.name_of(eqn.invars[0]), split],
                   n_out=len(eqn.outvars),
                   attrs={"axis": int(eqn.params["axis"])},
                   out_names=[self.g.name_of(v) for v in eqn.outvars])

    def _op_squeeze(self, eqn):
        out_shape = list(eqn.outvars[0].aval.shape)
        self.g.add("Reshape", [self.g.name_of(eqn.invars[0]),
                               self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    _op_expand_dims = _op_squeeze

    def _op_slice(self, eqn):
        starts = [int(s) for s in eqn.params["start_indices"]]
        ends = [int(s) for s in eqn.params["limit_indices"]]
        strides = eqn.params.get("strides")
        steps = ([int(s) for s in strides] if strides is not None
                 else [1] * len(starts))
        axes = list(range(len(starts)))
        ins = [self.g.name_of(eqn.invars[0]),
               self.g.const(np.asarray(starts, np.int64)),
               self.g.const(np.asarray(ends, np.int64)),
               self.g.const(np.asarray(axes, np.int64)),
               self.g.const(np.asarray(steps, np.int64))]
        self.g.add("Slice", ins,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_rev(self, eqn):
        # reverse along dims == Slice with step -1 on those axes
        dims = [int(d) for d in eqn.params["dimensions"]]
        n = len(dims)
        ins = [self.g.name_of(eqn.invars[0]),
               self.g.const(np.asarray([-1] * n, np.int64)),
               self.g.const(np.asarray([np.iinfo(np.int64).min] * n,
                                       np.int64)),
               self.g.const(np.asarray(dims, np.int64)),
               self.g.const(np.asarray([-1] * n, np.int64))]
        self.g.add("Slice", ins,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_pad(self, eqn):
        x, pad_val = eqn.invars
        cfg = eqn.params["padding_config"]
        if any(int(i) != 0 for _, _, i in cfg):
            raise UnsupportedOpError("pad with interior (dilation) padding")
        if any(int(lo) < 0 or int(hi) < 0 for lo, hi, _ in cfg):
            raise UnsupportedOpError("negative (cropping) pad")
        pads = ([int(lo) for lo, _, _ in cfg]
                + [int(hi) for _, hi, _ in cfg])
        ins = [self.g.name_of(x),
               self.g.const(np.asarray(pads, np.int64)),
               self.g.name_of(pad_val)]
        self.g.add("Pad", ins, attrs={"mode": b"constant"},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_iota(self, eqn):
        # static shapes: an iota is a compile-time constant — bake it
        shape = tuple(int(d) for d in eqn.params["shape"])
        dim = int(eqn.params["dimension"])
        dt = np.dtype(eqn.params["dtype"])
        ar = np.arange(shape[dim], dtype=dt)
        ar = np.broadcast_to(
            ar.reshape([-1 if i == dim else 1 for i in range(len(shape))]),
            shape)
        self.g.var_names[eqn.outvars[0]] = self.g.const(np.ascontiguousarray(ar))

    def _op_select_n(self, eqn):
        pred, *cases = eqn.invars
        if len(cases) != 2:
            raise UnsupportedOpError("select_n with >2 cases")
        if str(np.dtype(pred.aval.dtype)) != "bool":
            raise UnsupportedOpError("select_n with integer predicate")
        # select_n: False -> cases[0]; Where: cond True -> first branch
        self.g.add("Where", [self.g.name_of(pred),
                             self.g.name_of(cases[1]),
                             self.g.name_of(cases[0])],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_clamp(self, eqn):
        lo, x, hi = eqn.invars
        m = self.g.add("Max", [self.g.name_of(x), self.g.name_of(lo)])
        self.g.add("Min", [m, self.g.name_of(hi)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    # -- math that needs decomposition ---------------------------------------
    def _op_square(self, eqn):
        nm = self.g.name_of(eqn.invars[0])
        self.g.add("Mul", [nm, nm],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_rsqrt(self, eqn):
        s = self.g.add("Sqrt", [self.g.name_of(eqn.invars[0])])
        self.g.add("Reciprocal", [s],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_erfc(self, eqn):
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        e = self.g.add("Erf", [self.g.name_of(eqn.invars[0])])
        one = self.g.const(np.asarray(1, dt))
        self.g.add("Sub", [one, e],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_integer_pow(self, eqn):
        y = int(eqn.params["y"])
        nm = self.g.name_of(eqn.invars[0])
        out = self.g.name_of(eqn.outvars[0])
        if y == 2:
            self.g.add("Mul", [nm, nm], out_names=[out])
        else:
            dt = np.dtype(eqn.invars[0].aval.dtype)
            self.g.add("Pow", [nm, self.g.const(np.asarray(y, dt))],
                       out_names=[out])

    def _op_ne(self, eqn):
        ins = [self.g.name_of(v) for v in eqn.invars]
        e = self.g.add("Equal", ins)
        self.g.add("Not", [e], out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reduce_sum(self, eqn):
        if not len(eqn.params["axes"]):  # identity; an empty axes INPUT
            # means reduce-all in ONNX (noop_with_empty_axes defaults to 0)
            self.g.add("Identity", [self.g.name_of(eqn.invars[0])],
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return
        axes = self.g.const(
            np.asarray([int(a) for a in eqn.params["axes"]], np.int64))
        self.g.add("ReduceSum", [self.g.name_of(eqn.invars[0]), axes],
                   attrs={"keepdims": 0},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_argmax(self, eqn):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise UnsupportedOpError("argmax over multiple axes")
        out_dt = proto.NP_TO_ONNX[str(np.dtype(eqn.params["index_dtype"]))]
        a = self.g.add("ArgMax", [self.g.name_of(eqn.invars[0])],
                       attrs={"axis": int(axes[0]), "keepdims": 0})
        self.g.add("Cast", [a], attrs={"to": out_dt},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_argmin(self, eqn):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise UnsupportedOpError("argmin over multiple axes")
        out_dt = proto.NP_TO_ONNX[str(np.dtype(eqn.params["index_dtype"]))]
        a = self.g.add("ArgMin", [self.g.name_of(eqn.invars[0])],
                       attrs={"axis": int(axes[0]), "keepdims": 0})
        self.g.add("Cast", [a], attrs={"to": out_dt},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_cumsum(self, eqn):
        ax = self.g.const(np.asarray(int(eqn.params["axis"]), np.int64))
        self.g.add("CumSum", [self.g.name_of(eqn.invars[0]), ax],
                   attrs={"reverse": 1 if eqn.params.get("reverse") else 0},
                   out_names=[self.g.name_of(eqn.outvars[0])])

    # -- the big three: dot_general / conv / reduce_window -------------------
    def _op_dot_general(self, eqn):
        lhs, rhs = eqn.invars
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc, lb, rb = (list(map(int, d)) for d in (lc, rc, lb, rb))
        lshape, rshape = list(lhs.aval.shape), list(rhs.aval.shape)
        lf = [d for d in range(len(lshape)) if d not in lc + lb]
        rf = [d for d in range(len(rshape)) if d not in rc + rb]

        lnm, rnm = self.g.name_of(lhs), self.g.name_of(rhs)
        # fast path: plain 2D matmul already in [M,K] x [K,N] layout
        if (not lb and len(lshape) == 2 and len(rshape) == 2
                and lc == [1] and rc == [0]):
            self.g.add("MatMul", [lnm, rnm],
                       out_names=[self.g.name_of(eqn.outvars[0])])
            return

        def prod(dims, shape):
            out = 1
            for d in dims:
                out *= shape[d]
            return out

        bdims = [lshape[d] for d in lb]
        m, k = prod(lf, lshape), prod(lc, lshape)
        n = prod(rf, rshape)
        # lhs -> [B..., M, K]
        perm_l = lb + lf + lc
        if perm_l != list(range(len(lshape))):
            lnm = self.g.add("Transpose", [lnm], attrs={"perm": perm_l})
        lnm = self.g.add("Reshape", [lnm, self.g.shape_const(bdims + [m, k])])
        # rhs -> [B..., K, N]
        perm_r = rb + rc + rf
        if perm_r != list(range(len(rshape))):
            rnm = self.g.add("Transpose", [rnm], attrs={"perm": perm_r})
        rnm = self.g.add("Reshape", [rnm, self.g.shape_const(bdims + [k, n])])
        mm = self.g.add("MatMul", [lnm, rnm])
        out_shape = (bdims + [lshape[d] for d in lf]
                     + [rshape[d] for d in rf])
        self.g.add("Reshape", [mm, self.g.shape_const(out_shape)],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_conv_general_dilated(self, eqn):
        x, w = eqn.invars
        p = eqn.params
        dn = p["dimension_numbers"]
        nd = len(x.aval.shape)
        spatial = list(range(2, nd))
        if (tuple(dn.lhs_spec) != tuple([0, 1] + spatial)
                or tuple(dn.rhs_spec) != tuple([0, 1] + spatial)
                or tuple(dn.out_spec) != tuple([0, 1] + spatial)):
            raise UnsupportedOpError(
                "conv with non-NCHW/OIHW dimension numbers")
        if any(int(d) != 1 for d in p["lhs_dilation"]):
            raise UnsupportedOpError("transposed conv (lhs_dilation != 1)")
        if int(p.get("batch_group_count", 1)) != 1:
            raise UnsupportedOpError("batch_group_count != 1")
        pads = ([int(lo) for lo, _ in p["padding"]]
                + [int(hi) for _, hi in p["padding"]])
        attrs = {
            "strides": [int(s) for s in p["window_strides"]],
            "pads": pads,
            "dilations": [int(d) for d in p["rhs_dilation"]],
            "group": int(p["feature_group_count"]),
            "kernel_shape": [int(w.aval.shape[d]) for d in spatial],
        }
        self.g.add("Conv", [self.g.name_of(x), self.g.name_of(w)],
                   attrs=attrs,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _pool_common(self, eqn):
        p = eqn.params
        win = [int(d) for d in p["window_dimensions"]]
        strides = [int(d) for d in p["window_strides"]]
        padding = [(int(lo), int(hi)) for lo, hi in p["padding"]]
        if win[0] != 1 or win[1] != 1:
            raise UnsupportedOpError(
                "reduce_window over batch/channel dims (not NCHW pooling)")
        if strides[:2] != [1, 1] or padding[:2] != [(0, 0), (0, 0)]:
            raise UnsupportedOpError(
                "reduce_window with stride/pad on batch or channel dims")
        if any(int(d) != 1 for d in p.get("base_dilation", [1] * len(win))):
            raise UnsupportedOpError("reduce_window with base dilation")
        if any(int(d) != 1 for d in p.get("window_dilation", [1] * len(win))):
            raise UnsupportedOpError("reduce_window with window dilation")
        pads = ([lo for lo, _ in padding[2:]] + [hi for _, hi in padding[2:]])
        attrs = {"kernel_shape": win[2:], "strides": strides[2:],
                 "pads": pads}
        return attrs, win

    def _op_reduce_window_max(self, eqn):
        attrs, _ = self._pool_common(eqn)
        self.g.add("MaxPool", [self.g.name_of(eqn.invars[0])], attrs=attrs,
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_reduce_window_sum(self, eqn):
        attrs, win = self._pool_common(eqn)
        attrs["count_include_pad"] = 1
        ap = self.g.add("AveragePool", [self.g.name_of(eqn.invars[0])],
                        attrs=attrs)
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        k = self.g.const(np.asarray(float(np.prod(win)), dt))
        self.g.add("Mul", [ap, k],
                   out_names=[self.g.name_of(eqn.outvars[0])])

    def _op_gather(self, eqn):
        operand, indices = eqn.invars
        dn = eqn.params["dimension_numbers"]
        slice_sizes = [int(s) for s in eqn.params["slice_sizes"]]
        oshape = list(operand.aval.shape)
        ishape = list(indices.aval.shape)
        # the jnp.take(x, ids, axis=a) lowering: one collapsed slice dim at
        # axis a, start_index_map == (a,), full slices elsewhere, index
        # vector as the trailing dim of `indices`
        if (len(dn.start_index_map) != 1
                or list(dn.collapsed_slice_dims) != list(dn.start_index_map)
                or getattr(dn, "operand_batching_dims", ())):
            raise UnsupportedOpError(
                f"gather with general dimension_numbers {dn}")
        axis = int(dn.start_index_map[0])
        expect = list(oshape)
        expect[axis] = 1
        if slice_sizes != expect:
            raise UnsupportedOpError(
                f"gather with partial slice sizes {slice_sizes}")
        if ishape[-1] != 1:
            raise UnsupportedOpError("gather with multi-dim index vectors")
        # offset dims must be the trailing dims (take semantics)
        n_batch = len(ishape) - 1
        out_rank = n_batch + len(oshape) - 1
        if list(dn.offset_dims) != list(range(n_batch, out_rank)):
            raise UnsupportedOpError("gather with interleaved offset dims")
        if axis != 0:
            raise UnsupportedOpError("gather along non-leading axis")
        idx = self.g.add("Reshape", [self.g.name_of(indices),
                                     self.g.shape_const(ishape[:-1])])
        self.g.add("Gather", [self.g.name_of(operand), idx],
                   attrs={"axis": axis},
                   out_names=[self.g.name_of(eqn.outvars[0])])


def convert(pure_fn, params_flat_named, example_args, input_names=None,
            model_name="model"):
    """Trace pure_fn(params_list, *args) and convert to ONNX model bytes.

    params_flat_named: list of (name, np.ndarray) weights — become graph
    initializers. example_args: example input arrays (fix the traced
    shapes; ONNX export is static-shape by design here, matching the
    reference's fixed-shape .onnx outputs).
    """
    import jax

    arrs = [np.asarray(a) for a in example_args]
    names = list(input_names or [f"input_{i}" for i in range(len(arrs))])
    closed = jax.make_jaxpr(
        lambda ps, *xs: pure_fn(ps, *xs))(
            [v for _, v in params_flat_named], *arrs)

    conv = Converter()
    jaxpr = closed.jaxpr
    n_params = len(params_flat_named)
    for var, (pname, pval) in zip(jaxpr.invars[:n_params],
                                  params_flat_named):
        conv.g.var_names[var] = conv.g.const(np.asarray(pval), name=pname)
    graph_in_vars, out_vars, out_names = conv.convert_jaxpr(closed, names)

    # a graph output must be a unique node-produced name: passthrough
    # outputs (an input, an initializer, or a repeated var) get an Identity
    seen = set()
    for i, nm in enumerate(out_names):
        if nm not in conv.g.produced or nm in seen:
            out_names[i] = conv.g.add("Identity", [nm])
        seen.add(out_names[i])

    in_infos = [proto.value_info(
        nm, proto.NP_TO_ONNX[str(a.dtype)], a.shape)
        for nm, a in zip(names, arrs)]
    out_infos = []
    for ov, nm in zip(out_vars, out_names):
        out_infos.append(proto.value_info(
            nm, proto.NP_TO_ONNX[str(np.dtype(ov.aval.dtype))],
            [int(d) for d in ov.aval.shape]))
    graph = proto.graph_proto(model_name, conv.g.nodes,
                              conv.g.initializers, in_infos, out_infos)
    return proto.model_proto(graph)
