"""Numpy re-executor for the ONNX op subset the converter emits.

This is the validation half of `paddle.onnx.export`: every exported file is
parsed back (proto.parse_model) and re-executed here, in pure numpy with no
jax involvement, and the result is compared against the layer's own output.
A model that round-trips through serialized-protobuf → parse → numpy and
matches to tolerance is structurally valid and numerically faithful.

Covers exactly the opset-13 node set converter.py can produce. Kept
independent of the converter's internals on purpose — it consumes only the
parsed file, like an external runtime would.
"""
import math

import numpy as np

from . import proto


def _erf(x):
    return np.vectorize(math.erf, otypes=[x.dtype])(x) \
        if x.size else x.copy()


def _pool_views(x, kernel, strides, pads, pad_value):
    """Yield (window_view_stack, axis) for NCHW pooling via explicit pad +
    strided window extraction (loops over the small kernel only)."""
    kh, kw = kernel
    sh, sw = strides
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=pad_value)
    b, c, H, W = xp.shape
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    stack = np.empty((kh * kw, x.shape[0], x.shape[1], oh, ow), x.dtype)
    i = 0
    for dy in range(kh):
        for dx in range(kw):
            stack[i] = xp[:, :, dy:dy + sh * oh:sh, dx:dx + sw * ow:sw]
            i += 1
    return stack


def _conv(x, w, attrs):
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("pads", [0, 0, 0, 0])
    dil = attrs.get("dilations", [1, 1])
    group = attrs.get("group", 1)
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    b, cin, H, W = xp.shape
    cout, cin_g, kh, kw = w.shape
    ekh, ekw = (kh - 1) * dil[0] + 1, (kw - 1) * dil[1] + 1
    oh = (H - ekh) // strides[0] + 1
    ow = (W - ekw) // strides[1] + 1
    out = np.zeros((b, cout, oh, ow), np.result_type(x, w))
    og = cout // group
    for g in range(group):
        xg = xp[:, g * cin_g:(g + 1) * cin_g]
        wg = w[g * og:(g + 1) * og]          # [og, cin_g, kh, kw]
        # im2col over the kernel footprint
        acc = np.zeros((b, og, oh, ow), out.dtype)
        for dy in range(kh):
            for dx in range(kw):
                patch = xg[:, :, dy * dil[0]:dy * dil[0] + strides[0] * oh:strides[0],
                           dx * dil[1]:dx * dil[1] + strides[1] * ow:strides[1]]
                # [b,cin_g,oh,ow] x [og,cin_g] -> [b,og,oh,ow]
                acc += np.einsum("bchw,oc->bohw", patch, wg[:, :, dy, dx])
        out[:, g * og:(g + 1) * og] = acc
    return out


def _slice(x, starts, ends, axes, steps):
    sl = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        # ONNX clamps out-of-range starts/ends (INT64_MIN end + step -1
        # means "through the first element")
        if st > 0:
            sl[a] = slice(int(s), int(min(e, np.iinfo(np.int64).max)),
                          int(st))
        else:
            e = int(e)
            sl[a] = slice(int(s), None if e <= -x.shape[a] - 1 else e,
                          int(st))
    return x[tuple(sl)]


def run(model_bytes, inputs):
    """Execute a serialized ONNX model on numpy inputs.

    inputs: dict name -> array, or list matching graph input order.
    Returns list of output arrays (graph output order).
    """
    model = proto.parse_model(model_bytes)
    g = model["graph"]
    env = dict(g["initializers"])
    if isinstance(inputs, dict):
        env.update({k: np.asarray(v) for k, v in inputs.items()})
    else:
        for vi, arr in zip(g["inputs"], inputs):
            env[vi["name"]] = np.asarray(arr)

    for node in g["nodes"]:
        op = node["op_type"]
        ins = [env[n] for n in node["inputs"]]
        at = node["attrs"]
        if op == "Add":
            out = ins[0] + ins[1]
        elif op == "Sub":
            out = ins[0] - ins[1]
        elif op == "Mul":
            out = ins[0] * ins[1]
        elif op == "Div":
            if np.issubdtype(ins[0].dtype, np.floating):
                out = ins[0] / ins[1]
            else:  # ONNX (and lax.div) integer division truncates toward 0
                out = np.trunc(ins[0] / ins[1]).astype(ins[0].dtype)
        elif op == "Max":
            out = np.maximum(ins[0], ins[1])
        elif op == "Min":
            out = np.minimum(ins[0], ins[1])
        elif op == "Pow":
            out = np.power(ins[0], ins[1]).astype(ins[0].dtype)
        elif op == "Neg":
            out = -ins[0]
        elif op == "Exp":
            out = np.exp(ins[0])
        elif op == "Log":
            out = np.log(ins[0])
        elif op == "Sqrt":
            out = np.sqrt(ins[0])
        elif op == "Reciprocal":
            out = 1.0 / ins[0]
        elif op == "Tanh":
            out = np.tanh(ins[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-ins[0]))
        elif op == "Erf":
            out = _erf(ins[0])
        elif op == "Abs":
            out = np.abs(ins[0])
        elif op == "Sign":
            out = np.sign(ins[0])
        elif op == "Floor":
            out = np.floor(ins[0])
        elif op == "Ceil":
            out = np.ceil(ins[0])
        elif op == "Sin":
            out = np.sin(ins[0])
        elif op == "Cos":
            out = np.cos(ins[0])
        elif op == "Not":
            out = ~ins[0]
        elif op == "And":
            out = ins[0] & ins[1]
        elif op == "Or":
            out = ins[0] | ins[1]
        elif op == "Less":
            out = ins[0] < ins[1]
        elif op == "LessOrEqual":
            out = ins[0] <= ins[1]
        elif op == "Greater":
            out = ins[0] > ins[1]
        elif op == "GreaterOrEqual":
            out = ins[0] >= ins[1]
        elif op == "Equal":
            out = ins[0] == ins[1]
        elif op == "Where":
            out = np.where(ins[0], ins[1], ins[2])
        elif op == "Cast":
            out = ins[0].astype(proto.ONNX_TO_NP[at["to"]])
        elif op == "Identity":
            out = ins[0]
        elif op == "Reshape":
            # ONNX semantics: 0 copies the input dim at that index (with
            # allowzero=0, the default), -1 infers — both are what the
            # dynamic-batch export emits for batch-carrying shape consts
            tgt = [int(d) for d in ins[1]]
            tgt = [ins[0].shape[i] if d == 0 else d
                   for i, d in enumerate(tgt)]
            out = ins[0].reshape(tgt)
        elif op == "Transpose":
            out = np.transpose(ins[0], at["perm"])
        elif op == "Expand":
            # ONNX Expand is TWO-WAY broadcast: output dim = max(input,
            # shape) per numpy rules (a 1 in `shape` keeps the input dim)
            tgt = np.broadcast_shapes(ins[0].shape,
                                      tuple(int(d) for d in ins[1]))
            out = np.broadcast_to(ins[0], tgt)
        elif op == "Concat":
            out = np.concatenate(ins, axis=at["axis"])
        elif op == "Split":
            sizes = [int(s) for s in ins[1]]
            outs = np.split(ins[0], np.cumsum(sizes)[:-1], axis=at["axis"])
            for nm, o in zip(node["outputs"], outs):
                env[nm] = o
            continue
        elif op == "Slice":
            out = _slice(ins[0], ins[1], ins[2], ins[3], ins[4])
        elif op == "Pad":
            pads = [int(p) for p in ins[1]]
            n = len(pads) // 2
            out = np.pad(ins[0], list(zip(pads[:n], pads[n:])),
                         constant_values=ins[2])
        elif op == "ReduceSum":
            # ONNX noop_with_empty_axes=0 (the default): empty axes input
            # means reduce over ALL axes, unlike numpy's sum(axis=()).
            axes = tuple(int(a) for a in ins[1]) if len(ins[1]) else None
            out = ins[0].sum(axis=axes, keepdims=bool(at.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd", "ReduceMean"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod, "ReduceMean": np.mean}[op]
            out = fn(ins[0], axis=tuple(at["axes"]),
                     keepdims=bool(at.get("keepdims", 1)))
        elif op == "ArgMax":
            out = np.argmax(ins[0], axis=at["axis"]).astype(np.int64)
            if at.get("keepdims", 1):
                out = np.expand_dims(out, at["axis"])
        elif op == "ArgMin":
            out = np.argmin(ins[0], axis=at["axis"]).astype(np.int64)
            if at.get("keepdims", 1):
                out = np.expand_dims(out, at["axis"])
        elif op == "CumSum":
            out = np.cumsum(ins[0], axis=int(ins[1]))
            if at.get("reverse"):
                raise NotImplementedError("CumSum reverse")
        elif op == "MatMul":
            out = np.matmul(ins[0], ins[1])
        elif op == "Conv":
            out = _conv(ins[0], ins[1], at)
            if len(ins) > 2:
                out = out + ins[2].reshape(1, -1, 1, 1)
        elif op == "MaxPool":
            stack = _pool_views(ins[0], at["kernel_shape"],
                                at.get("strides", [1, 1]),
                                at.get("pads", [0, 0, 0, 0]),
                                -np.inf)
            out = stack.max(axis=0)
        elif op == "AveragePool":
            if not at.get("count_include_pad"):
                raise NotImplementedError(
                    "AveragePool without count_include_pad")
            stack = _pool_views(ins[0], at["kernel_shape"],
                                at.get("strides", [1, 1]),
                                at.get("pads", [0, 0, 0, 0]), 0.0)
            out = stack.mean(axis=0)
        elif op == "Gather":
            out = np.take(ins[0], ins[1].astype(np.int64),
                          axis=at.get("axis", 0))
        elif op == "Clip":
            out = np.clip(ins[0], ins[1] if len(ins) > 1 else None,
                          ins[2] if len(ins) > 2 else None)
        else:
            raise NotImplementedError(f"onnx.runtime: op {op}")
        env[node["outputs"][0]] = np.asarray(out)

    return [env[vi["name"]] for vi in g["outputs"]]
