"""VOC2012 segmentation dataset (python/paddle/vision/datasets/voc2012.py parity)
with synthetic fallback for zero-egress environments.

Accepts either the paddle tarball or a local `VOCdevkit`-layout directory; real
samples are decoded lazily in __getitem__ (the train split is ~3 GB decoded — only
the id list is read up front). Without local data a deterministic synthetic set
keeps pipelines runnable offline.
"""
import os
import tarfile

import numpy as np

from ...io.dataset import Dataset

_HOME = os.path.expanduser("~/.cache/paddle/dataset/voc2012")
_MODES = ("train", "valid", "test")
_SPLIT_FILES = {"train": "train.txt", "valid": "val.txt", "test": "val.txt"}


def _synthetic(n, seed, hw=64):
    """Blobby images with matching segmentation masks (21 VOC classes)."""
    rng = np.random.RandomState(seed)
    images = np.zeros((n, 3, hw, hw), np.uint8)
    labels = np.zeros((n, hw, hw), np.uint8)
    yy, xx = np.mgrid[0:hw, 0:hw]
    for i in range(n):
        k = rng.randint(1, 4)  # objects per image
        img = rng.rand(3, hw, hw) * 40
        for _ in range(k):
            cls = rng.randint(1, 21)
            cy, cx = rng.randint(8, hw - 8, 2)
            r = rng.randint(5, 14)
            mask = (yy - cy) ** 2 + (xx - cx) ** 2 < r * r
            labels[i][mask] = cls
            color = rng.rand(3, 1) * 200 + 55
            img[:, mask] = color
        images[i] = np.clip(img, 0, 255).astype(np.uint8)
    return images, labels


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("reading real VOC data needs Pillow") from e
    return Image


class VOC2012(Dataset):
    """mode: 'train' | 'valid'/'val' | 'test'. Yields (image CHW uint8,
    label HW int64) like the reference (image, segmentation label)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = {"val": "valid"}.get(mode.lower(), mode.lower())
        if self.mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.transform = transform
        self._tar_path = None
        self._tar = None
        self._root = None
        self._ids = None
        data_file = data_file or os.path.join(_HOME, "VOCtrainval_11-May-2012.tar")
        if os.path.isdir(data_file):
            self._init_dir(data_file)
        elif os.path.exists(data_file):
            self._init_tar(data_file)
        else:
            n = 200 if self.mode == "train" else 50
            seed = {"train": 11, "valid": 13, "test": 17}[self.mode]
            self.images, self.labels = _synthetic(n, seed)

    # -- real-data backends (lazy decode) -------------------------------------
    def _init_dir(self, root):
        """VOCdevkit layout: root(/VOCdevkit)/VOC2012/{ImageSets,JPEGImages,...}"""
        for cand in (root, os.path.join(root, "VOC2012"),
                     os.path.join(root, "VOCdevkit", "VOC2012")):
            if os.path.isdir(os.path.join(cand, "ImageSets", "Segmentation")):
                self._root = cand
                break
        else:
            raise ValueError(f"{root} is not a VOCdevkit/VOC2012 layout")
        split = os.path.join(self._root, "ImageSets", "Segmentation",
                             _SPLIT_FILES[self.mode])
        with open(split) as f:
            self._ids = f.read().split()

    def _init_tar(self, path):
        self._tar_path = path
        with tarfile.open(path) as tf:
            names = tf.getnames()
            seg_dir = next(n for n in names
                           if n.endswith("ImageSets/Segmentation"))
            self._root = seg_dir.rsplit("/ImageSets", 1)[0]
            ids = tf.extractfile(
                f"{seg_dir}/{_SPLIT_FILES[self.mode]}").read().split()
            self._ids = [s.decode() for s in ids]

    def _open_tar(self):
        if self._tar is None:
            self._tar = tarfile.open(self._tar_path)
        return self._tar

    def _read_pair(self, sid):
        Image = _pil()
        if self._tar_path is not None:
            tf = self._open_tar()
            img = Image.open(tf.extractfile(
                f"{self._root}/JPEGImages/{sid}.jpg")).convert("RGB")
            lab = Image.open(tf.extractfile(
                f"{self._root}/SegmentationClass/{sid}.png"))
        else:
            img = Image.open(os.path.join(
                self._root, "JPEGImages", f"{sid}.jpg")).convert("RGB")
            lab = Image.open(os.path.join(
                self._root, "SegmentationClass", f"{sid}.png"))
        return (np.moveaxis(np.asarray(img, np.uint8), -1, 0),
                np.asarray(lab, np.uint8))

    # -- Dataset API -----------------------------------------------------------
    def __len__(self):
        return len(self._ids) if self._ids is not None else len(self.images)

    def __getitem__(self, idx):
        if self._ids is not None:
            img, lab = self._read_pair(self._ids[idx])
        else:
            img, lab = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, lab.astype(np.int64)
