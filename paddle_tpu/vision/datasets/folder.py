"""DatasetFolder / ImageFolder (python/paddle/vision/datasets/folder.py parity).
Loads .npy/.png/.jpg files; image decoding uses numpy (npy) or defers to an installed
imaging library when available."""
import os

import numpy as np

from ...io.dataset import Dataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image  # optional

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError:
        raise RuntimeError(f"cannot load {path}: install Pillow or use .npy files")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.extensions = extensions or IMG_EXTENSIONS
        self.transform = transform
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(tuple(self.extensions)):
                    self.samples.append((os.path.join(d, fname), self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.asarray([target], dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.extensions = extensions or IMG_EXTENSIONS
        self.transform = transform
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for fname in sorted(files):
                if fname.lower().endswith(tuple(self.extensions)):
                    self.samples.append(os.path.join(dirpath, fname))

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
