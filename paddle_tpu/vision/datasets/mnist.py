"""MNIST dataset (python/paddle/vision/datasets/mnist.py parity).

Reads the standard idx-ubyte files when present (image_path/label_path or
~/.cache/paddle/dataset/mnist); otherwise generates a deterministic synthetic set so
training flows run in zero-egress environments (class-conditional gaussian blobs —
learnable, converges like a toy MNIST).
"""
import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

_HOME = os.path.expanduser("~/.cache/paddle/dataset/mnist")


def _load_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _load_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data


def _synthetic_mnist(n, seed):
    """Deterministic class-conditional digit-blob images."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    images = np.zeros((n, 28, 28), dtype=np.uint8)
    centers = [(7 + 2 * (d % 5), 7 + 3 * (d // 5)) for d in range(10)]
    yy, xx = np.mgrid[0:28, 0:28]
    for i in range(n):
        cy, cx = centers[labels[i]]
        blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / (2.0 * (2.0 + labels[i] * 0.3) ** 2)))
        noise = rng.rand(28, 28) * 0.15
        images[i] = np.clip((blob + noise) * 255, 0, 255).astype(np.uint8)
    return images, labels


class MNIST(Dataset):
    NAME = "mnist"
    N_TRAIN = 60000
    N_TEST = 10000

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        self.backend = backend or "cv2"
        images, labels = self._load(image_path, label_path)
        self.images = images
        self.labels = labels

    def _load(self, image_path, label_path):
        prefix = "train" if self.mode == "train" else "t10k"
        candidates = [
            (image_path, label_path),
            (os.path.join(_HOME, f"{prefix}-images-idx3-ubyte.gz"),
             os.path.join(_HOME, f"{prefix}-labels-idx1-ubyte.gz")),
            (os.path.join(_HOME, f"{prefix}-images-idx3-ubyte"),
             os.path.join(_HOME, f"{prefix}-labels-idx1-ubyte")),
        ]
        for ip, lp in candidates:
            if ip and lp and os.path.exists(ip) and os.path.exists(lp):
                return _load_idx_images(ip), _load_idx_labels(lp).astype(np.int64)
        n = 6000 if self.mode == "train" else 1000  # synthetic fallback (smaller)
        return _synthetic_mnist(n, seed=42 if self.mode == "train" else 7)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)
        from ...core.tensor import Tensor

        if isinstance(img, Tensor):
            img = np.asarray(img._data)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
