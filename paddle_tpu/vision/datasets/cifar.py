"""Cifar10/100 (python/paddle/vision/datasets/cifar.py parity) with synthetic
fallback for zero-egress environments."""
import os
import pickle
import tarfile

import numpy as np

from ...io.dataset import Dataset

_HOME = os.path.expanduser("~/.cache/paddle/dataset/cifar")


def _synthetic(n, n_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int64)
    base = rng.rand(n_classes, 3, 8, 8).astype(np.float32)
    images = np.zeros((n, 3, 32, 32), dtype=np.uint8)
    for i in range(n):
        pat = np.kron(base[labels[i]], np.ones((4, 4), dtype=np.float32))
        noise = rng.rand(3, 32, 32) * 0.2
        images[i] = np.clip((pat + noise) * 200, 0, 255).astype(np.uint8)
    return images, labels


class Cifar10(Dataset):
    N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        data_file = data_file or os.path.join(_HOME, "cifar-10-python.tar.gz")
        if os.path.exists(data_file):
            self.images, self.labels = self._load_tar(data_file)
        else:
            n = 5000 if self.mode == "train" else 1000
            self.images, self.labels = _synthetic(n, self.N_CLASSES, 3 if self.mode == "train" else 5)

    def _load_tar(self, path):
        images, labels = [], []
        want = "data_batch" if self.mode == "train" else "test_batch"
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if want in m.name:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(d[b"data"].reshape(-1, 3, 32, 32))
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        return np.concatenate(images), np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
            from ...core.tensor import Tensor

            if isinstance(img, Tensor):
                img = np.asarray(img._data)
        else:
            img = img.astype(np.float32)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    N_CLASSES = 100
