"""Flowers dataset (python/paddle/vision/datasets/flowers.py parity) — synthetic
fallback in zero-egress environments."""
import numpy as np

from ...io.dataset import Dataset
from .cifar import _synthetic


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 1000 if mode == "train" else 200
        imgs, labels = _synthetic(n, 102, 11 if mode == "train" else 13)
        # upscale 32->64 to be vaguely flower-sized
        self.images = np.repeat(np.repeat(imgs, 2, axis=2), 2, axis=3)
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
            from ...core.tensor import Tensor

            if isinstance(img, Tensor):
                img = np.asarray(img._data)
        else:
            img = img.astype(np.float32)
        return img, label

    def __len__(self):
        return len(self.images)
