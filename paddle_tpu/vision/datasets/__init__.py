"""paddle.vision.datasets parity (MNIST, FashionMNIST, Cifar10/100, Flowers, VOC2012,
ImageFolder/DatasetFolder). Zero-egress environments: every dataset accepts
`backend='synthetic'` or falls back to deterministic synthetic data when files are
absent and download is impossible (download URLs retained for parity)."""
from .mnist import MNIST, FashionMNIST  # noqa: F401
from .cifar import Cifar10, Cifar100  # noqa: F401
from .folder import DatasetFolder, ImageFolder  # noqa: F401
from .flowers import Flowers  # noqa: F401
from .voc2012 import VOC2012  # noqa: F401
