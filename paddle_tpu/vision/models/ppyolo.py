"""PP-YOLOE-style anchor-free detector (BASELINE.json config #5: mixed conv +
NMS custom ops via Pallas).

The reference tree ships the detection *operators* (paddle/fluid/operators/detection/:
yolo_box_op.cc, multiclass_nms_op.cc, prior_box, roi_align …) but no detection model —
model zoos live in PaddleDetection. This is the framework's own compact PP-YOLOE-class
model exercising those ops end-to-end on TPU: CSP backbone (conv+BN+SiLU), PAN-lite
neck, decoupled anchor-free head with per-level objectness/class/box branches, decode +
multiclass NMS (vision/ops.py, Pallas greedy kernel on TPU) postprocessing, and a
trainable varifocal+GIoU-style loss.

Layout is NCHW to match the reference detection ops' convention.
"""
import numpy as np

from ... import nn
from ...nn import functional as F


class ConvBNLayer(nn.Layer):
    def __init__(self, ch_in, ch_out, k=3, stride=1, groups=1, act="silu"):
        super().__init__()
        self.conv = nn.Conv2D(ch_in, ch_out, k, stride=stride, padding=k // 2,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(ch_out)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return getattr(F, self.act)(x) if self.act else x


class CSPBlock(nn.Layer):
    """Cross-stage-partial block: split, residual bottlenecks, merge."""

    def __init__(self, ch, n_bottlenecks=1):
        super().__init__()
        mid = ch // 2
        self.left = ConvBNLayer(ch, mid, k=1)
        self.right = ConvBNLayer(ch, mid, k=1)
        self.blocks = nn.LayerList([
            nn.Sequential(ConvBNLayer(mid, mid, k=1), ConvBNLayer(mid, mid, k=3))
            for _ in range(n_bottlenecks)
        ])
        self.merge = ConvBNLayer(2 * mid, ch, k=1)

    def forward(self, x):
        left = self.left(x)
        y = self.right(x)
        for blk in self.blocks:
            y = y + blk(y)
        from ...tensor.manipulation import concat

        return self.merge(concat([left, y], axis=1))


class CSPBackbone(nn.Layer):
    """Stages at strides 8/16/32 -> feature pyramid [C3, C4, C5]."""

    def __init__(self, width=32, depth=1):
        super().__init__()
        w = width
        self.stem = nn.Sequential(
            ConvBNLayer(3, w, k=3, stride=2),
            ConvBNLayer(w, w, k=3, stride=2),
        )
        self.stages = nn.LayerList()
        chs = [w, 2 * w, 4 * w, 8 * w]
        for i in range(3):
            self.stages.append(nn.Sequential(
                ConvBNLayer(chs[i], chs[i + 1], k=3, stride=2),
                CSPBlock(chs[i + 1], depth),
            ))
        self.out_channels = chs[1:]

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats  # strides 8, 16, 32


class PANNeck(nn.Layer):
    """Top-down feature fusion (PAN-lite: upsample + lateral 1x1 + CSP merge)."""

    def __init__(self, in_channels):
        super().__init__()
        c3, c4, c5 = in_channels
        self.lat5 = ConvBNLayer(c5, c4, k=1)
        self.merge4 = CSPBlock(c4)
        self.lat4 = ConvBNLayer(c4, c3, k=1)
        self.merge3 = CSPBlock(c3)
        self.out_channels = [c3, c4, c5]

    def forward(self, feats):
        c3, c4, c5 = feats
        p5 = c5
        up5 = F.interpolate(self.lat5(p5), scale_factor=2, mode="nearest",
                            data_format="NCHW")
        p4 = self.merge4(c4 + up5)
        up4 = F.interpolate(self.lat4(p4), scale_factor=2, mode="nearest",
                            data_format="NCHW")
        p3 = self.merge3(c3 + up4)
        return [p3, p4, p5]


class PPYOLOEHead(nn.Layer):
    """Decoupled anchor-free head: per level, cls logits [B,C,H,W] and box
    ltrb distances [B,4,H,W] (distance-from-point regression, PP-YOLOE style)."""

    def __init__(self, in_channels, num_classes=80):
        super().__init__()
        self.num_classes = num_classes
        self.cls_convs = nn.LayerList()
        self.reg_convs = nn.LayerList()
        self.cls_preds = nn.LayerList()
        self.reg_preds = nn.LayerList()
        for ch in in_channels:
            self.cls_convs.append(ConvBNLayer(ch, ch, k=3))
            self.reg_convs.append(ConvBNLayer(ch, ch, k=3))
            self.cls_preds.append(nn.Conv2D(ch, num_classes, 1))
            self.reg_preds.append(nn.Conv2D(ch, 4, 1))

    def forward(self, feats):
        outs = []
        for i, x in enumerate(feats):
            cls = self.cls_preds[i](self.cls_convs[i](x))
            reg = self.reg_preds[i](self.reg_convs[i](x))
            outs.append((cls, reg))
        return outs


class PPYOLOE(nn.Layer):
    """Compact PP-YOLOE-class detector. strides (8, 16, 32)."""

    def __init__(self, num_classes=80, width=32, depth=1):
        super().__init__()
        self.backbone = CSPBackbone(width, depth)
        self.neck = PANNeck(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes)
        self.num_classes = num_classes
        self.strides = (8, 16, 32)

    def forward(self, images):
        h, w = images.shape[-2], images.shape[-1]
        if h % self.strides[-1] or w % self.strides[-1]:
            raise ValueError(
                f"PPYOLOE input H/W must be multiples of {self.strides[-1]}, "
                f"got {h}x{w} (pad or resize the batch first)")
        return self.head(self.neck(self.backbone(images)))

    # ---- decode / postprocess ------------------------------------------------
    def decode(self, head_outs):
        """-> (boxes [B, A, 4] xyxy in pixels, scores [B, num_classes, A])."""
        from ...tensor.manipulation import concat

        all_boxes, all_scores = [], []
        import jax
        import jax.numpy as jnp

        from ...core.dispatch import apply

        for (cls, reg), stride in zip(head_outs, self.strides):
            b, c, h, w = cls.shape

            def fn(cls_v, reg_v, _stride=stride, _h=h, _w=w):
                ys = (jnp.arange(_h, dtype=jnp.float32) + 0.5) * _stride
                xs = (jnp.arange(_w, dtype=jnp.float32) + 0.5) * _stride
                cy, cx = jnp.meshgrid(ys, xs, indexing="ij")
                # ltrb distances are kept positive via softplus
                l, t, r, btm = [reg_v[:, i] * _stride for i in range(4)]
                x1 = cx[None] - jax.nn.softplus(l)
                y1 = cy[None] - jax.nn.softplus(t)
                x2 = cx[None] + jax.nn.softplus(r)
                y2 = cy[None] + jax.nn.softplus(btm)
                boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(
                    cls_v.shape[0], _h * _w, 4)
                scores = jax.nn.sigmoid(cls_v).reshape(
                    cls_v.shape[0], cls_v.shape[1], _h * _w)
                return boxes, scores

            boxes, scores = apply(fn, cls, reg, n_outputs=2)
            all_boxes.append(boxes)
            all_scores.append(scores)
        return concat(all_boxes, axis=1), concat(all_scores, axis=2)

    def postprocess(self, head_outs, score_threshold=0.05, nms_threshold=0.5,
                    keep_top_k=100):
        """Full inference tail: decode + per-class NMS (Pallas kernel on TPU)."""
        from ..ops import multiclass_nms

        boxes, scores = self.decode(head_outs)
        # anchor-free sigmoid scores: every class is foreground (no background
        # column), so disable multiclass_nms's background skip
        return multiclass_nms(boxes, scores, score_threshold=score_threshold,
                              nms_threshold=nms_threshold, keep_top_k=keep_top_k,
                              background_label=-1)


class PPYOLOELoss(nn.Layer):
    """Simplified PP-YOLOE training loss over decoded predictions.

    targets: (gt_boxes [B, A, 4] per-anchor assigned boxes, gt_labels [B, A]
    with num_classes = background). Classification = focal BCE on assigned
    anchors; regression = GIoU-style IoU loss on positive anchors. A full
    TOOD/ATSS assigner belongs in a detection library; the per-anchor-target
    interface matches what such an assigner emits.
    """

    def __init__(self, num_classes=80, cls_weight=1.0, iou_weight=2.5):
        super().__init__()
        self.num_classes = num_classes
        self.cls_weight = cls_weight
        self.iou_weight = iou_weight

    def forward(self, decoded, targets):
        import jax
        import jax.numpy as jnp

        from ...core.dispatch import apply

        boxes, scores = decoded
        gt_boxes, gt_labels = targets
        C = self.num_classes

        def fn(boxes_v, scores_v, gt_b, gt_l):
            pos = (gt_l < C)  # [B, A]
            onehot = jax.nn.one_hot(gt_l, C + 1)[..., :C]  # bg -> all-zero
            logits = jnp.moveaxis(scores_v, 1, 2)  # [B, A, C], already sigmoided
            p = jnp.clip(logits, 1e-6, 1 - 1e-6)
            focal = -(onehot * (1 - p) ** 2 * jnp.log(p)
                      + (1 - onehot) * p ** 2 * jnp.log(1 - p))
            cls_loss = focal.sum() / jnp.maximum(pos.sum(), 1)

            # IoU loss on positives
            ix1 = jnp.maximum(boxes_v[..., 0], gt_b[..., 0])
            iy1 = jnp.maximum(boxes_v[..., 1], gt_b[..., 1])
            ix2 = jnp.minimum(boxes_v[..., 2], gt_b[..., 2])
            iy2 = jnp.minimum(boxes_v[..., 3], gt_b[..., 3])
            inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
            area_p = jnp.maximum(boxes_v[..., 2] - boxes_v[..., 0], 0) * \
                jnp.maximum(boxes_v[..., 3] - boxes_v[..., 1], 0)
            area_g = jnp.maximum(gt_b[..., 2] - gt_b[..., 0], 0) * \
                jnp.maximum(gt_b[..., 3] - gt_b[..., 1], 0)
            iou = inter / jnp.maximum(area_p + area_g - inter, 1e-9)
            iou_loss = ((1 - iou) * pos).sum() / jnp.maximum(pos.sum(), 1)
            return self.cls_weight * cls_loss + self.iou_weight * iou_loss

        return apply(fn, boxes, scores, gt_boxes, gt_labels)


def ppyoloe_tiny(num_classes=80):
    return PPYOLOE(num_classes=num_classes, width=16, depth=1)
