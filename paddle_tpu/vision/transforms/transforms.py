"""Vision transforms on numpy HWC uint8/float images
(python/paddle/vision/transforms/transforms.py parity; PIL-free — pure numpy,
cv2-style semantics)."""
import numbers
import random

import numpy as np

from ...core.tensor import Tensor


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


def resize(img, size, interpolation="bilinear"):
    img = _as_np(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if (nh, nw) == (h, w):
        return img
    # numpy bilinear/nearest resize
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    if interpolation == "nearest":
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        return img[yi][:, xi]
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def to_tensor(pic, data_format="CHW"):
    img = _as_np(pic)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    if img.ndim == 2:
        img = img[:, :, None]
    if data_format == "CHW":
        img = np.transpose(img, (2, 0, 1))
    return Tensor(img.astype(np.float32))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _as_np(img).astype(np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    if isinstance(img, Tensor):
        return Tensor(out)
    return out


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size

    def _apply_image(self, img):
        arr = _as_np(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i : i + th, j : j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_np(img)
        if self.padding:
            p = self.padding if not isinstance(self.padding, int) else (self.padding,) * 4
            pad_width = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad_width)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return arr[i : i + th, j : j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _as_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(arr[i : i + ch, j : j + cw], self.size, self.interpolation)
        return resize(CenterCrop(min(h, w))._apply_image(arr), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_np(img)[:, ::-1].copy()
        return _as_np(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_np(img)[::-1].copy()
        return _as_np(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        # rotation in steps of 90 for numpy-only implementation; small angles approx. identity
        angle = random.uniform(*self.degrees)
        arr = _as_np(img)
        k = int(round(angle / 90.0)) % 4
        return np.rot90(arr, k=k, axes=(0, 1)).copy() if k else arr


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(padding, int):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        arr = _as_np(img)
        l, t, r, b = self.padding
        pad_width = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad_width, constant_values=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _as_np(img).astype(np.float32)
        if arr.ndim == 3 and arr.shape[2] == 3:
            g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
        else:
            g = arr.squeeze()
        g = g[..., None]
        if self.num_output_channels == 3:
            g = np.repeat(g, 3, axis=2)
        return g.astype(_as_np(img).dtype)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _as_np(img)
        arr = _as_np(img).astype(np.float32)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1 else 1.0).astype(_as_np(img).dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _as_np(img)
        arr = _as_np(img).astype(np.float32)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255 if arr.max() > 1 else 1.0).astype(_as_np(img).dtype)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _as_np(img)
        arr = _as_np(img).astype(np.float32)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = Grayscale(3)._apply_image(arr).astype(np.float32)
        return np.clip(arr * factor + gray * (1 - factor), 0, 255 if arr.max() > 1 else 1.0).astype(_as_np(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return _as_np(img)  # hue shift approximated as identity in numpy-only build


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [
            BrightnessTransform(brightness),
            ContrastTransform(contrast),
            SaturationTransform(saturation),
            HueTransform(hue),
        ]

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t._apply_image(img)
        return img
