"""paddle.vision.transforms.functional parity: stateless transform fns the
class transforms delegate to (python/paddle/vision/transforms/functional.py).
Backed by the same numpy/PIL-free implementations as transforms.py."""
import numpy as np


def _chw(img):
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    from .transforms import ToTensor

    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from .transforms import Normalize

    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    from .transforms import Resize

    return Resize(size, interpolation)(img)


def center_crop(img, output_size):
    from .transforms import CenterCrop

    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    a = np.asarray(img)
    if a.ndim == 3 and a.shape[0] in (1, 3):  # CHW
        return a[:, top: top + height, left: left + width]
    return a[top: top + height, left: left + width]


def hflip(img):
    a = np.asarray(img)
    return a[:, :, ::-1] if (a.ndim == 3 and a.shape[0] in (1, 3)) else a[:, ::-1]


def vflip(img):
    a = np.asarray(img)
    return a[:, ::-1, :] if (a.ndim == 3 and a.shape[0] in (1, 3)) else a[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    from .transforms import Pad

    return Pad(padding, fill, padding_mode)(img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from .transforms import RandomRotation

    r = RandomRotation((angle, angle), interpolation, expand, center, fill)
    return r(img)


def to_grayscale(img, num_output_channels=1):
    from .transforms import Grayscale

    return Grayscale(num_output_channels)(img)
