"""paddle.vision.transforms parity (python/paddle/vision/transforms/transforms.py —
19 transforms on numpy HWC images)."""
from .transforms import (  # noqa: F401
    BaseTransform,
    BrightnessTransform,
    CenterCrop,
    ColorJitter,
    Compose,
    ContrastTransform,
    Grayscale,
    HueTransform,
    Normalize,
    Pad,
    RandomCrop,
    RandomHorizontalFlip,
    RandomResizedCrop,
    RandomRotation,
    RandomVerticalFlip,
    Resize,
    SaturationTransform,
    ToTensor,
    Transpose,
    to_tensor,
    normalize,
    resize,
)

from . import functional  # noqa: E402,F401
