"""Detection ops (paddle.vision.ops parity).

Reference parity: paddle/fluid/operators/detection/ — multiclass_nms_op.cc,
yolo_box_op.cc, roi_align_op.cc, prior_box_op.cc, box_coder_op.cc (18k LoC of CUDA/C++
post-processing). TPU-native design: static-shape implementations (XLA requirement):
NMS returns a fixed `max_out` set with a validity mask and -1 padding instead of
dynamic LoD outputs; the O(n^2) IoU matrix is MXU/VPU-friendly.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _iou_matrix(boxes):
    # boxes [n,4] xyxy
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def nms_mask(boxes, scores, iou_threshold=3e-1, score_threshold=None, top_k=None,
             use_pallas=None):
    """Pure static-shape NMS: returns keep mask [n].

    On TPU the greedy sweep runs as a single-VMEM Pallas kernel
    (ops/nms_pallas.py); elsewhere (or when `use_pallas=False`) it is a
    lax.scan over the precomputed IoU matrix."""
    from ..ops import nms_pallas as _np_kernel

    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    if use_pallas is None:
        use_pallas = _np_kernel.supported(n)
    if use_pallas:
        try:
            keep_sorted_full = _np_kernel.nms_keep_mask_pallas(
                boxes[order], iou_threshold)
            keep = jnp.zeros(n, dtype=bool).at[order].set(keep_sorted_full)
            return _nms_mask_filters(keep, scores, score_threshold, top_k,
                                     order, n)
        except Exception:  # Mosaic lowering/compile failure -> scan fallback
            _np_kernel.mark_unsupported()
    iou = _iou_matrix(boxes)
    iou_sorted = iou[order][:, order]

    def body(keep, i):
        # suppressed if any earlier kept box overlaps > threshold
        sup = jnp.any(keep & (jnp.arange(n) < i) & (iou_sorted[i] > iou_threshold))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep0 = jnp.zeros(n, dtype=bool).at[0].set(True)
    keep_sorted, _ = jax.lax.scan(body, keep0, jnp.arange(1, n))
    keep = jnp.zeros(n, dtype=bool).at[order].set(keep_sorted)
    return _nms_mask_filters(keep, scores, score_threshold, top_k, order, n)


def _nms_mask_filters(keep, scores, score_threshold, top_k, order, n):
    if score_threshold is not None:
        keep = keep & (scores > score_threshold)
    if top_k is not None:
        rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
        keep = keep & (rank < top_k)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """paddle.vision.ops.nms parity: returns kept indices sorted by score.

    Eager op (dynamic output count — uses host filtering like the reference's CPU
    kernel); inside jit use `nms_mask` for the static-shape variant.
    """
    b = _t(boxes)._data
    s = _t(scores)._data if scores is not None else jnp.ones(b.shape[0])
    if category_idxs is not None:
        # category-aware: offset boxes per class so cross-class boxes never overlap
        c = _t(category_idxs)._data.astype(b.dtype)
        offset = c[:, None] * (jnp.max(b) + 1.0)
        mask = nms_mask(b + offset, s, iou_threshold)
    else:
        mask = nms_mask(b, s, iou_threshold)
    mask_np = np.asarray(mask)
    s_np = np.asarray(s)
    idxs = np.nonzero(mask_np)[0]
    idxs = idxs[np.argsort(-s_np[idxs])]
    if top_k is not None:
        idxs = idxs[:top_k]
    return Tensor(jnp.asarray(idxs.astype(np.int64)))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400, keep_top_k=100,
                   nms_threshold=0.3, normalized=True, background_label=0, name=None):
    """multiclass_nms_op.cc parity (static-shape): bboxes [N,M,4], scores [N,C,M].

    Returns (out [N, keep_top_k, 6] (label, score, x1,y1,x2,y2; -1 padded),
             valid counts [N]).
    """
    bv = _t(bboxes)._data
    sv = _t(scores)._data

    def per_image(boxes, score):
        C, M = score.shape
        all_entries = []
        for c in range(C):
            if c == background_label:
                continue
            sc = score[c]
            k = min(nms_top_k, M)
            top_s, top_i = jax.lax.top_k(sc, k)
            bx = boxes[top_i]
            keep = nms_mask(bx, top_s, nms_threshold, score_threshold)
            entry = jnp.concatenate(
                [jnp.full((k, 1), c, boxes.dtype), top_s[:, None], bx], axis=1
            )
            entry = jnp.where(keep[:, None], entry, jnp.full_like(entry, -1.0))
            all_entries.append(entry)
        cat = jnp.concatenate(all_entries, axis=0)
        # rank by score, take keep_top_k
        k2 = min(keep_top_k, cat.shape[0])
        _, order = jax.lax.top_k(cat[:, 1], k2)
        out = cat[order]
        valid = jnp.sum(out[:, 1] > 0).astype(jnp.int32)
        if k2 < keep_top_k:
            out = jnp.concatenate([out, jnp.full((keep_top_k - k2, 6), -1.0, out.dtype)], axis=0)
        return out, valid

    outs, valids = jax.vmap(per_image)(bv, sv)
    return Tensor(outs), Tensor(valids)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01, downsample_ratio=32,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5, name=None):
    """yolo_box_op.cc parity: decode YOLO head [N, an*(5+C), H, W] -> boxes+scores."""
    xv = _t(x)._data
    img = _t(img_size)._data

    an = len(anchors) // 2
    anchors_wh = jnp.asarray(np.array(anchors, np.float32).reshape(an, 2))

    def fn(v, imsz):
        N, _, H, W = v.shape
        v = v.reshape(N, an, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=v.dtype).reshape(1, 1, 1, W)
        gy = jnp.arange(H, dtype=v.dtype).reshape(1, 1, H, 1)
        sig = jax.nn.sigmoid
        bx = (sig(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / W
        by = (sig(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / H
        bw = jnp.exp(v[:, :, 2]) * anchors_wh[:, 0].reshape(1, an, 1, 1) / (downsample_ratio * W)
        bh = jnp.exp(v[:, :, 3]) * anchors_wh[:, 1].reshape(1, an, 1, 1) / (downsample_ratio * H)
        conf = sig(v[:, :, 4])
        cls = sig(v[:, :, 5:])
        scores = conf[:, :, None] * cls  # [N, an, C, H, W]
        imh = imsz[:, 0].reshape(N, 1, 1, 1).astype(v.dtype)
        imw = imsz[:, 1].reshape(N, 1, 1, 1).astype(v.dtype)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, an * H * W, 4)
        mask = (conf > conf_thresh).reshape(N, an, 1, H, W)
        scores = (scores * mask).transpose(0, 1, 3, 4, 2).reshape(N, an * H * W, class_num)
        return boxes, scores

    boxes, scores = fn(xv, img)
    return Tensor(boxes), Tensor(scores)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True, name=None):
    """roi_align_op.cc parity via bilinear grid sampling."""
    xv = _t(x)
    bv = _t(boxes).detach()
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(feat, rois):
        # rois: [R, 4] xyxy in input scale; all on image 0 unless boxes_num used
        R = rois.shape[0]
        C, H, W = feat.shape[1], feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        # sample centers
        ys = y1[:, None] + (jnp.arange(ph) + 0.5)[None, :] * (rh[:, None] / ph)  # [R, ph]
        xs = x1[:, None] + (jnp.arange(pw) + 0.5)[None, :] * (rw[:, None] / pw)  # [R, pw]

        def bilinear(img, yy, xx):
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            y0c = jnp.clip(y0, 0, H - 1)
            x0c = jnp.clip(x0, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            v00 = img[:, y0c][:, :, x0c]
            v01 = img[:, y0c][:, :, x1i]
            v10 = img[:, y1i][:, :, x0c]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(r):
            return bilinear(feat[0], ys[r], xs[r])  # [C, ph, pw]

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(lambda f, r: fn(f, r), xv, bv)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """box_coder_op.cc parity (encode/decode center-size)."""

    def fn(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tx - px) / pw / pbv[:, 0],
                (ty - py) / ph / pbv[:, 1],
                jnp.log(tw / pw) / pbv[:, 2],
                jnp.log(th / ph) / pbv[:, 3],
            ], axis=1)
        else:  # decode
            dx, dy, dw, dh = tb[:, 0], tb[:, 1], tb[:, 2], tb[:, 3]
            cx = dx * pbv[:, 0] * pw + px
            cy = dy * pbv[:, 1] * ph + py
            w = jnp.exp(dw * pbv[:, 2]) * pw
            h = jnp.exp(dh * pbv[:, 3]) * ph
            out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
        return out

    pbv = _t(prior_box_var) if prior_box_var is not None else Tensor(np.ones((1, 4), np.float32))
    return apply(fn, _t(prior_box).detach(), pbv.detach(), _t(target_box))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False, steps=(0.0, 0.0),
              offset=0.5, name=None):
    """prior_box_op.cc parity (SSD anchors)."""
    H, W = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_h = steps[1] or img_h / H
    step_w = steps[0] or img_w / W
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in ars if a != 1.0]
    boxes = []
    for h in range(H):
        for w in range(W):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            for k, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) / 2
                    bh = ms / np.sqrt(ar) / 2
                    boxes.append([(cx - bw) / img_w, (cy - bh) / img_h,
                                  (cx + bw) / img_w, (cy + bh) / img_h])
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k]) / 2
                    boxes.append([(cx - s) / img_w, (cy - s) / img_h,
                                  (cx + s) / img_w, (cy + s) / img_h])
    b = np.asarray(boxes, np.float32).reshape(H, W, -1, 4)
    if clip:
        b = b.clip(0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), b.shape).copy()
    return Tensor(jnp.asarray(b)), Tensor(jnp.asarray(var))


class DeformConv2D:  # registered for inventory completeness; XLA path pending
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D: deferred (gather-based impl, round 2)")
