"""Detection ops (paddle.vision.ops parity).

Reference parity: paddle/fluid/operators/detection/ — multiclass_nms_op.cc,
yolo_box_op.cc, roi_align_op.cc, prior_box_op.cc, box_coder_op.cc (18k LoC of CUDA/C++
post-processing). TPU-native design: static-shape implementations (XLA requirement):
NMS returns a fixed `max_out` set with a validity mask and -1 padding instead of
dynamic LoD outputs; the O(n^2) IoU matrix is MXU/VPU-friendly.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _iou_matrix(boxes):
    # boxes [n,4] xyxy
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def nms_mask(boxes, scores, iou_threshold=3e-1, score_threshold=None, top_k=None,
             use_pallas=None):
    """Pure static-shape NMS: returns keep mask [n].

    On TPU the greedy sweep runs as a single-VMEM Pallas kernel
    (ops/nms_pallas.py); elsewhere (or when `use_pallas=False`) it is a
    lax.scan over the precomputed IoU matrix."""
    from ..ops import nms_pallas as _np_kernel

    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    if use_pallas is None:
        use_pallas = _np_kernel.supported(n)
    if use_pallas:
        try:
            keep_sorted_full = _np_kernel.nms_keep_mask_pallas(
                boxes[order], iou_threshold)
            keep = jnp.zeros(n, dtype=bool).at[order].set(keep_sorted_full)
            return _nms_mask_filters(keep, scores, score_threshold, top_k,
                                     order, n)
        except Exception:  # Mosaic lowering/compile failure -> scan fallback
            _np_kernel.mark_unsupported()
    iou = _iou_matrix(boxes)
    iou_sorted = iou[order][:, order]

    def body(keep, i):
        # suppressed if any earlier kept box overlaps > threshold
        sup = jnp.any(keep & (jnp.arange(n) < i) & (iou_sorted[i] > iou_threshold))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep0 = jnp.zeros(n, dtype=bool).at[0].set(True)
    keep_sorted, _ = jax.lax.scan(body, keep0, jnp.arange(1, n))
    keep = jnp.zeros(n, dtype=bool).at[order].set(keep_sorted)
    return _nms_mask_filters(keep, scores, score_threshold, top_k, order, n)


def _nms_mask_filters(keep, scores, score_threshold, top_k, order, n):
    if score_threshold is not None:
        keep = keep & (scores > score_threshold)
    if top_k is not None:
        rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
        keep = keep & (rank < top_k)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """paddle.vision.ops.nms parity: returns kept indices sorted by score.

    Eager op (dynamic output count — uses host filtering like the reference's CPU
    kernel); inside jit use `nms_mask` for the static-shape variant.
    """
    b = _t(boxes)._data
    s = _t(scores)._data if scores is not None else jnp.ones(b.shape[0])
    if category_idxs is not None:
        # category-aware: offset boxes per class so cross-class boxes never overlap
        c = _t(category_idxs)._data.astype(b.dtype)
        offset = c[:, None] * (jnp.max(b) + 1.0)
        mask = nms_mask(b + offset, s, iou_threshold)
    else:
        mask = nms_mask(b, s, iou_threshold)
    mask_np = np.asarray(mask)
    s_np = np.asarray(s)
    idxs = np.nonzero(mask_np)[0]
    idxs = idxs[np.argsort(-s_np[idxs])]
    if top_k is not None:
        idxs = idxs[:top_k]
    return Tensor(jnp.asarray(idxs.astype(np.int64)))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400, keep_top_k=100,
                   nms_threshold=0.3, normalized=True, background_label=0, name=None):
    """multiclass_nms_op.cc parity (static-shape): bboxes [N,M,4], scores [N,C,M].

    Returns (out [N, keep_top_k, 6] (label, score, x1,y1,x2,y2; -1 padded),
             valid counts [N]).
    """
    bv = _t(bboxes)._data
    sv = _t(scores)._data

    def per_image(boxes, score):
        C, M = score.shape
        all_entries = []
        for c in range(C):
            if c == background_label:
                continue
            sc = score[c]
            k = min(nms_top_k, M)
            top_s, top_i = jax.lax.top_k(sc, k)
            bx = boxes[top_i]
            keep = nms_mask(bx, top_s, nms_threshold, score_threshold)
            entry = jnp.concatenate(
                [jnp.full((k, 1), c, boxes.dtype), top_s[:, None], bx], axis=1
            )
            entry = jnp.where(keep[:, None], entry, jnp.full_like(entry, -1.0))
            all_entries.append(entry)
        if not all_entries:
            # every class is background (C==1 with background_label=0):
            # the reference emits an empty LoD result; here all-(-1) padding
            return (jnp.full((keep_top_k, 6), -1.0, boxes.dtype),
                    jnp.zeros((), jnp.int32))
        cat = jnp.concatenate(all_entries, axis=0)
        # rank by score, take keep_top_k
        k2 = min(keep_top_k, cat.shape[0])
        _, order = jax.lax.top_k(cat[:, 1], k2)
        out = cat[order]
        valid = jnp.sum(out[:, 1] > 0).astype(jnp.int32)
        if k2 < keep_top_k:
            out = jnp.concatenate([out, jnp.full((keep_top_k - k2, 6), -1.0, out.dtype)], axis=0)
        return out, valid

    outs, valids = jax.vmap(per_image)(bv, sv)
    return Tensor(outs), Tensor(valids)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01, downsample_ratio=32,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5, name=None):
    """yolo_box_op.cc parity: decode YOLO head [N, an*(5+C), H, W] -> boxes+scores."""
    xv = _t(x)._data
    img = _t(img_size)._data

    an = len(anchors) // 2
    anchors_wh = jnp.asarray(np.array(anchors, np.float32).reshape(an, 2))

    def fn(v, imsz):
        N, _, H, W = v.shape
        v = v.reshape(N, an, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=v.dtype).reshape(1, 1, 1, W)
        gy = jnp.arange(H, dtype=v.dtype).reshape(1, 1, H, 1)
        sig = jax.nn.sigmoid
        bx = (sig(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / W
        by = (sig(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / H
        bw = jnp.exp(v[:, :, 2]) * anchors_wh[:, 0].reshape(1, an, 1, 1) / (downsample_ratio * W)
        bh = jnp.exp(v[:, :, 3]) * anchors_wh[:, 1].reshape(1, an, 1, 1) / (downsample_ratio * H)
        conf = sig(v[:, :, 4])
        cls = sig(v[:, :, 5:])
        scores = conf[:, :, None] * cls  # [N, an, C, H, W]
        imh = imsz[:, 0].reshape(N, 1, 1, 1).astype(v.dtype)
        imw = imsz[:, 1].reshape(N, 1, 1, 1).astype(v.dtype)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, an * H * W, 4)
        mask = (conf > conf_thresh).reshape(N, an, 1, H, W)
        scores = (scores * mask).transpose(0, 1, 3, 4, 2).reshape(N, an * H * W, class_num)
        return boxes, scores

    boxes, scores = fn(xv, img)
    return Tensor(boxes), Tensor(scores)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True, name=None):
    """roi_align_op.cc parity via bilinear grid sampling."""
    xv = _t(x)
    bv = _t(boxes).detach()
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(feat, rois):
        # rois: [R, 4] xyxy in input scale; all on image 0 unless boxes_num used
        R = rois.shape[0]
        C, H, W = feat.shape[1], feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        # sample centers
        ys = y1[:, None] + (jnp.arange(ph) + 0.5)[None, :] * (rh[:, None] / ph)  # [R, ph]
        xs = x1[:, None] + (jnp.arange(pw) + 0.5)[None, :] * (rw[:, None] / pw)  # [R, pw]

        def bilinear(img, yy, xx):
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            y0c = jnp.clip(y0, 0, H - 1)
            x0c = jnp.clip(x0, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            v00 = img[:, y0c][:, :, x0c]
            v01 = img[:, y0c][:, :, x1i]
            v10 = img[:, y1i][:, :, x0c]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def per_roi(r):
            return bilinear(feat[0], ys[r], xs[r])  # [C, ph, pw]

        return jax.vmap(per_roi)(jnp.arange(R))

    return apply(lambda f, r: fn(f, r), xv, bv)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """box_coder_op.cc parity (encode/decode center-size)."""

    def fn(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tx - px) / pw / pbv[:, 0],
                (ty - py) / ph / pbv[:, 1],
                jnp.log(tw / pw) / pbv[:, 2],
                jnp.log(th / ph) / pbv[:, 3],
            ], axis=1)
        else:  # decode
            dx, dy, dw, dh = tb[:, 0], tb[:, 1], tb[:, 2], tb[:, 3]
            cx = dx * pbv[:, 0] * pw + px
            cy = dy * pbv[:, 1] * ph + py
            w = jnp.exp(dw * pbv[:, 2]) * pw
            h = jnp.exp(dh * pbv[:, 3]) * ph
            out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
        return out

    pbv = _t(prior_box_var) if prior_box_var is not None else Tensor(np.ones((1, 4), np.float32))
    return apply(fn, _t(prior_box).detach(), pbv.detach(), _t(target_box))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False, steps=(0.0, 0.0),
              offset=0.5, name=None):
    """prior_box_op.cc parity (SSD anchors)."""
    H, W = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_h = steps[1] or img_h / H
    step_w = steps[0] or img_w / W
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in ars if a != 1.0]
    boxes = []
    for h in range(H):
        for w in range(W):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            for k, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) / 2
                    bh = ms / np.sqrt(ar) / 2
                    boxes.append([(cx - bw) / img_w, (cy - bh) / img_h,
                                  (cx + bw) / img_w, (cy + bh) / img_h])
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k]) / 2
                    boxes.append([(cx - s) / img_w, (cy - s) / img_h,
                                  (cx + s) / img_w, (cy + s) / img_h])
    b = np.asarray(boxes, np.float32).reshape(H, W, -1, 4)
    if clip:
        b = b.clip(0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), b.shape).copy()
    return Tensor(jnp.asarray(b)), Tensor(jnp.asarray(var))


from ..nn.layer.layers import Layer as _Layer


class DeformConv2D(_Layer):
    """Deformable conv v1/v2 Layer (reference python/paddle/vision/ops.py:598).

    Thin stateful wrapper over the functional `deform_conv2d` below: holds
    weight [out, in/groups, kh, kw] (Normal(0, sqrt(2/fan_in)) like the
    reference's default initializer) and optional bias; v2 (modulated) when
    `mask` is passed to forward."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if in_channels % groups != 0:
            raise ValueError("in_channels must be divisible by groups.")

        def _pair(v):
            return [v, v] if isinstance(v, int) else list(v)

        from ..nn import initializer as I

        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size)
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._deformable_groups = deformable_groups
        self._groups = groups
        filter_shape = ([out_channels, in_channels // groups]
                        + self._kernel_size)
        std = (2.0 / (int(np.prod(self._kernel_size)) * in_channels)) ** 0.5
        self.weight = self.create_parameter(
            shape=filter_shape, attr=weight_attr,
            default_initializer=None
            if (weight_attr and getattr(weight_attr, "initializer", None))
            else I.Normal(0.0, std))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups,
            mask=mask)


def iou_similarity(x, y, box_normalized=True, name=None):
    """detection/iou_similarity_op.cc parity: pairwise IoU of x [N,4] vs y [M,4]
    (xyxy). box_normalized=False adds +1 to widths/heights like the reference."""
    def fn(a, b):
        off = 0.0 if box_normalized else 1.0
        area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * jnp.maximum(
            a[:, 3] - a[:, 1] + off, 0)
        area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * jnp.maximum(
            b[:, 3] - b[:, 1] + off, 0)
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.maximum(rb - lt + off, 0)
        inter = wh[..., 0] * wh[..., 1]
        union = area_a[:, None] + area_b[None, :] - inter
        return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)

    return apply(fn, _t(x), _t(y))


def bipartite_match(dist_matrix, match_type="bipartite", overlap_threshold=0.5,
                    name=None):
    """detection/bipartite_match_op.cc parity: greedy global-max bipartite
    matching on dist [R, C]. Returns (match_indices [C] int32 — matched row or
    -1, match_dist [C]). match_type='per_prediction' then assigns every still-
    unmatched column its argmax row when that overlap >= overlap_threshold.

    TPU design: lax.scan of min(R, C) greedy steps, each picking the global
    argmax of the live sub-matrix — no python loops over entries.
    """
    def fn(dist):
        R, C = dist.shape
        eps = 1e-6

        def step(carry, _):
            live, col_row, col_dist = carry  # live [R, C] mask
            masked = jnp.where(live, dist, -jnp.inf)
            flat = jnp.argmax(masked)
            i, j = flat // C, flat % C
            best = masked[i, j]
            ok = best > eps
            col_row = jnp.where(ok, col_row.at[j].set(i.astype(jnp.int32)), col_row)
            col_dist = jnp.where(ok, col_dist.at[j].set(best), col_dist)
            live = jnp.where(ok, live & (jnp.arange(R)[:, None] != i)
                             & (jnp.arange(C)[None, :] != j), live)
            return (live, col_row, col_dist), None

        init = (jnp.ones((R, C), bool), jnp.full((C,), -1, jnp.int32),
                jnp.zeros((C,), dist.dtype))
        (live, col_row, col_dist), _ = jax.lax.scan(
            step, init, None, length=min(R, C))
        if match_type == "per_prediction":
            best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
            best_val = jnp.max(dist, axis=0)
            fill = (col_row == -1) & (best_val >= overlap_threshold)
            col_row = jnp.where(fill, best_row, col_row)
            col_dist = jnp.where(fill, best_val, col_dist)
        return col_row, col_dist

    idx, d = apply(fn, _t(dist_matrix).detach())
    idx.stop_gradient = True
    d.stop_gradient = True
    return idx, d


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """detection/matrix_nms_op.cc parity (SOLOv2 Matrix NMS): scores decay by
    min_j f(iou_ij, max_iou_j) instead of hard suppression — one IoU matrix,
    no sequential sweep: ideal for the MXU. bboxes [N, M, 4], scores [N, C, M].

    Returns (out [N, keep_top_k, 6] (-1 padded rows), rois_num [N][, index]).
    """
    bv = _t(bboxes)._data
    sv = _t(scores)._data

    def per_image(boxes, score):
        C, M = score.shape
        off = 0.0 if normalized else 1.0
        outs = []
        for c in range(C):
            if c == background_label:
                continue
            sc = score[c]
            k = min(nms_top_k, M)
            top_s, top_i = jax.lax.top_k(sc, k)
            bsel = boxes[top_i]
            area = jnp.maximum(bsel[:, 2] - bsel[:, 0] + off, 0) * jnp.maximum(
                bsel[:, 3] - bsel[:, 1] + off, 0)
            lt = jnp.maximum(bsel[:, None, :2], bsel[None, :, :2])
            rb = jnp.minimum(bsel[:, None, 2:], bsel[None, :, 2:])
            wh = jnp.maximum(rb - lt + off, 0)
            inter = wh[..., 0] * wh[..., 1]
            union = area[:, None] + area[None, :] - inter
            iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)
            upper = jnp.tril(iou, k=-1)      # iou[i, j] for j < i lives at [i, :i]
            max_iou = jnp.max(upper, axis=1)  # per box: max IoU vs higher-scored
            if use_gaussian:
                decay = jnp.exp((max_iou[None, :] ** 2 - upper ** 2)
                                * gaussian_sigma)
            else:
                decay = (1.0 - upper) / jnp.maximum(1.0 - max_iou[None, :], 1e-10)
            # min over j < i (mask j >= i to 1)
            jj = jnp.arange(k)
            mask_lower = jj[None, :] < jj[:, None]
            decay = jnp.where(mask_lower, decay, 1.0)
            decayed = top_s * jnp.min(decay, axis=1)
            valid = top_s > score_threshold
            if post_threshold > 0:
                valid = valid & (decayed > post_threshold)
            entry = jnp.concatenate(
                [jnp.full((k, 1), float(c)), decayed[:, None], bsel], axis=1)
            entry = jnp.where(valid[:, None], entry, -1.0)
            outs.append((entry, jnp.where(valid, decayed, -jnp.inf), top_i))
        if not outs:  # every class was the background label
            kk = min(keep_top_k, M)
            return (jnp.full((kk, 6), -1.0), jnp.zeros((), jnp.int32),
                    jnp.zeros((kk,), jnp.int32))
        all_e = jnp.concatenate([e for e, _, _ in outs], axis=0)
        all_s = jnp.concatenate([s for _, s, _ in outs], axis=0)
        all_i = jnp.concatenate([i for _, _, i in outs], axis=0)
        kk = min(keep_top_k, all_e.shape[0])
        sel_s, sel = jax.lax.top_k(all_s, kk)
        out = jnp.where((sel_s > -jnp.inf)[:, None], all_e[sel], -1.0)
        n_valid = jnp.sum(sel_s > -jnp.inf)
        return out, n_valid, all_i[sel]

    outs, nums, idxs = [], [], []
    for n in range(bv.shape[0]):
        o, nv, ix = per_image(bv[n], sv[n])
        outs.append(o)
        nums.append(nv)
        idxs.append(ix)
    out = Tensor(jnp.stack(outs))
    nums_t = Tensor(jnp.stack(nums).astype(jnp.int32))
    if return_index:
        return (out, nums_t, Tensor(jnp.stack(idxs))) if return_rois_num else (out, Tensor(jnp.stack(idxs)))
    return (out, nums_t) if return_rois_num else out


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """roi_pool_op.cc parity: max pooling per bin with the reference's rounded
    integer-grid bin layout. x [N,C,H,W]; boxes [R,4] xyxy; boxes_num [N]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph_n, pw_n = output_size

    xv = _t(x)
    bv = _t(boxes).detach()
    bn = np.asarray(_t(boxes_num)._data).astype(np.int64)
    img_of_roi = np.repeat(np.arange(len(bn)), bn)

    def fn(feat, rois):
        N, C, H, W = feat.shape
        img_idx = jnp.asarray(img_of_roi, jnp.int32)

        def one(roi, im):
            x1 = jnp.round(roi[0] * spatial_scale)
            y1 = jnp.round(roi[1] * spatial_scale)
            x2 = jnp.round(roi[2] * spatial_scale)
            y2 = jnp.round(roi[3] * spatial_scale)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            fmap = feat[im]                      # [C, H, W]
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)

            def bin_val(phw):
                ph, pw = phw // pw_n, phw % pw_n
                hs = jnp.floor(ph * rh / ph_n) + y1
                he = jnp.ceil((ph + 1) * rh / ph_n) + y1
                ws = jnp.floor(pw * rw / pw_n) + x1
                we = jnp.ceil((pw + 1) * rw / pw_n) + x1
                hs, he = jnp.clip(hs, 0, H), jnp.clip(he, 0, H)
                ws, we = jnp.clip(ws, 0, W), jnp.clip(we, 0, W)
                m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                     & (xs[None, :] >= ws) & (xs[None, :] < we))
                empty = (he <= hs) | (we <= ws)
                v = jnp.max(jnp.where(m[None], fmap, -jnp.inf), axis=(1, 2))
                return jnp.where(empty, 0.0, v)

            vals = jax.vmap(bin_val)(jnp.arange(ph_n * pw_n))  # [ph*pw, C]
            return vals.T.reshape(C, ph_n, pw_n)

        return jax.vmap(one)(rois, img_idx)

    return apply(fn, xv, bv)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """psroi_pool_op.cc parity: position-sensitive average pooling — output
    channel c at bin (ph, pw) averages input channel (c*ph_n + ph)*pw_n + pw."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph_n, pw_n = output_size

    xv = _t(x)
    bv = _t(boxes).detach()
    bn = np.asarray(_t(boxes_num)._data).astype(np.int64)
    img_of_roi = np.repeat(np.arange(len(bn)), bn)

    def fn(feat, rois):
        N, C, H, W = feat.shape
        c_out = C // (ph_n * pw_n)
        img_idx = jnp.asarray(img_of_roi, jnp.int32)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)

        def one(roi, im):
            x1 = jnp.round(roi[0]) * spatial_scale
            y1 = jnp.round(roi[1]) * spatial_scale
            x2 = jnp.round(roi[2] + 1.0) * spatial_scale
            y2 = jnp.round(roi[3] + 1.0) * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1)
            rw = jnp.maximum(x2 - x1, 0.1)
            bin_h, bin_w = rh / ph_n, rw / pw_n
            fmap = feat[im]

            def bin_val(phw):
                ph, pw = phw // pw_n, phw % pw_n
                hs = jnp.floor(y1 + ph * bin_h)
                he = jnp.ceil(y1 + (ph + 1) * bin_h)
                ws = jnp.floor(x1 + pw * bin_w)
                we = jnp.ceil(x1 + (pw + 1) * bin_w)
                hs, he = jnp.clip(hs, 0, H), jnp.clip(he, 0, H)
                ws, we = jnp.clip(ws, 0, W), jnp.clip(we, 0, W)
                m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                     & (xs[None, :] >= ws) & (xs[None, :] < we))
                cnt = jnp.maximum(jnp.sum(m), 1)
                ch = (jnp.arange(c_out) * ph_n + ph) * pw_n + pw  # [c_out]
                v = jnp.sum(jnp.where(m[None], fmap[ch], 0.0), axis=(1, 2))
                empty = (he <= hs) | (we <= ws)
                return jnp.where(empty, 0.0, v / cnt)

            vals = jax.vmap(bin_val)(jnp.arange(ph_n * pw_n))  # [ph*pw, c_out]
            return vals.T.reshape(c_out, ph_n, pw_n)

        return jax.vmap(one)(rois, img_idx)

    return apply(fn, xv, bv)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """distribute_fpn_proposals_op.cc parity: route each RoI to its FPN level
    by sqrt(area): level = floor(refer_level + log2(sqrt(wh)/refer_scale)).
    Eager op (dynamic per-level counts, like the reference's CPU kernel).
    Returns (multi_rois list, restore_index [R, 1][, multi_level_rois_num])."""
    rv = np.asarray(_t(fpn_rois)._data)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rv[:, 2] - rv[:, 0] + off, 0)
    h = np.maximum(rv[:, 3] - rv[:, 1] + off, 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        multi.append(Tensor(jnp.asarray(rv[idx])))
        nums.append(len(idx))
        order.extend(idx.tolist())
    restore = np.zeros((len(rv), 1), np.int32)
    restore[np.asarray(order, np.int64), 0] = np.arange(len(rv), dtype=np.int32)
    out = (multi, Tensor(jnp.asarray(restore)))
    if rois_num is not None:
        out = out + (Tensor(jnp.asarray(np.asarray(nums, np.int32))),)
    return out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """detection/generate_proposals_v2_op.cc parity (RPN proposal stage),
    static-shape: decode deltas on anchors, clip to image, drop boxes smaller
    than min_size, keep top pre_nms_top_n, greedy-NMS, emit post_nms_top_n
    rows (zero-padded) + per-image valid count. scores [N, A, H, W],
    bbox_deltas [N, 4A, H, W], anchors [H, W, A, 4] or [H*W*A, 4]."""
    sv = _t(scores).detach()._data
    dv = _t(bbox_deltas).detach()._data
    iv = np.asarray(_t(img_size)._data, np.float32)
    av = _t(anchors)._data.reshape(-1, 4)
    vv = _t(variances)._data.reshape(-1, 4)
    off = 1.0 if pixel_offset else 0.0

    def per_image(sc, dl, im_hw):
        A = av.shape[0] // (sc.shape[1] * sc.shape[2])
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)             # [H*W*A]
        d = jnp.transpose(dl, (1, 2, 0)).reshape(-1, 4)          # [H*W*A, 4]
        aw = av[:, 2] - av[:, 0] + off
        ah = av[:, 3] - av[:, 1] + off
        acx = av[:, 0] + 0.5 * aw
        acy = av[:, 1] + 0.5 * ah
        cx = vv[:, 0] * d[:, 0] * aw + acx
        cy = vv[:, 1] * d[:, 1] * ah + acy
        bw = aw * jnp.exp(jnp.minimum(vv[:, 2] * d[:, 2], np.log(1000.0 / 16)))
        bh = ah * jnp.exp(jnp.minimum(vv[:, 3] * d[:, 3], np.log(1000.0 / 16)))
        x1 = cx - 0.5 * bw
        y1 = cy - 0.5 * bh
        x2 = cx + 0.5 * bw - off
        y2 = cy + 0.5 * bh - off
        H_img, W_img = im_hw[0], im_hw[1]
        x1 = jnp.clip(x1, 0, W_img - off)
        x2 = jnp.clip(x2, 0, W_img - off)
        y1 = jnp.clip(y1, 0, H_img - off)
        y2 = jnp.clip(y2, 0, H_img - off)
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)
        keep = ((x2 - x1 + off) >= min_size) & ((y2 - y1 + off) >= min_size)
        s = jnp.where(keep, s, -jnp.inf)
        k = min(pre_nms_top_n, s.shape[0])
        top_s, top_i = jax.lax.top_k(s, k)
        bsel = boxes[top_i]
        mask = nms_mask(bsel, top_s, nms_thresh) & (top_s > -jnp.inf)
        # order kept boxes by score (they already are), compact to post_nms_top_n
        rank = jnp.cumsum(mask) - 1
        kk = post_nms_top_n
        sel = jnp.where(mask & (rank < kk), rank, kk)  # kk = dump slot
        out_rois = jnp.zeros((kk + 1, 4), boxes.dtype).at[sel].set(bsel)[:kk]
        out_sc = jnp.zeros((kk + 1,), s.dtype).at[sel].set(top_s)[:kk]
        n_valid = jnp.minimum(jnp.sum(mask), kk)
        return out_rois, out_sc, n_valid

    rois, rsc, nums = [], [], []
    for n in range(sv.shape[0]):
        r, scs, nv = per_image(sv[n], dv[n], iv[n])
        rois.append(r)
        rsc.append(scs)
        nums.append(nv)
    rois_t = Tensor(jnp.stack(rois))
    sc_t = Tensor(jnp.stack(rsc))
    if return_rois_num:
        return rois_t, sc_t, Tensor(jnp.stack(nums).astype(jnp.int32))
    return rois_t, sc_t


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """deformable_conv_op.cu parity (v1; v2/modulated when `mask` given).

    TPU design: for each kernel tap (i, j) the whole feature map is bilinearly
    resampled at (base_grid + learned offset) in one gather — kh*kw vectorized
    samples instead of the reference's per-output im2col loop — then the
    conv collapses to an einsum over (tap, in-channel).
    x [N,Cin,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo]; mask [N, dg*kh*kw, Ho, Wo];
    weight [Cout, Cin/groups, kh, kw].
    """
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)

    args = [_t(x), _t(offset), _t(weight)]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))

    def fn(xv, ov, wv, *rest):
        rest = list(rest)
        mv = rest.pop(0) if mask is not None else None
        bvv = rest.pop(0) if bias is not None else None
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = wv.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        dg = deformable_groups
        ov = ov.reshape(N, dg, kh * kw, 2, Ho, Wo)  # reference layout: (..., [y, x], ...)
        base_y = jnp.arange(Ho) * sh - ph
        base_x = jnp.arange(Wo) * sw - pw

        def sample(fmap, py, px):
            # fmap [C', H, W]; py/px [Ho, Wo] absolute float positions
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0

            def at(yy, xx):
                inb = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
                yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                v = fmap[:, yc, xc]                      # [C', Ho, Wo]
                return jnp.where(inb[None], v, 0.0)

            return (at(y0, x0) * (1 - wy) * (1 - wx)
                    + at(y0, x0 + 1) * (1 - wy) * wx
                    + at(y0 + 1, x0) * wy * (1 - wx)
                    + at(y0 + 1, x0 + 1) * wy * wx)

        cin_per_dg = Cin // dg

        def one_image(xi, oi, mi):
            taps = []
            for i in range(kh):
                for j in range(kw):
                    t = i * kw + j
                    per_dg = []
                    for g in range(dg):
                        py = base_y[:, None] + i * dh + oi[g, t, 0]
                        px = base_x[None, :] + j * dw + oi[g, t, 1]
                        sm = sample(xi[g * cin_per_dg:(g + 1) * cin_per_dg],
                                    py, px)
                        if mi is not None:
                            sm = sm * mi[g, t][None]
                        per_dg.append(sm)
                    taps.append(jnp.concatenate(per_dg, axis=0))  # [Cin, Ho, Wo]
            return jnp.stack(taps)                                # [kh*kw, Cin, Ho, Wo]

        if mv is not None:
            mi_all = mv.reshape(N, dg, kh * kw, Ho, Wo)
            cols = jax.vmap(one_image)(xv, ov, mi_all)
        else:
            cols = jax.vmap(lambda a, b: one_image(a, b, None))(xv, ov)
        # grouped conv reduce: weight [Cout, Cin/groups, kh, kw]
        outs = []
        cout_g = Cout // groups
        cin_pg = Cin // groups
        for g in range(groups):
            wg = wv[g * cout_g:(g + 1) * cout_g]                 # [cout_g, cin_pg, kh, kw]
            cg = cols[:, :, g * cin_pg:(g + 1) * cin_pg]          # [N, khkw, cin_pg, Ho, Wo]
            wgf = wg.reshape(cout_g, cin_pg, kh * kw)
            outs.append(jnp.einsum("ock,nkchw->nohw", wgf, cg))
        out = jnp.concatenate(outs, axis=1)
        if bvv is not None:
            out = out + bvv.reshape(1, -1, 1, 1)
        return out

    return apply(fn, *args)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """detection/anchor_generator_op.h parity: per-cell anchors over the
    feature map. input [N, C, H, W] (only H, W used). Returns
    (anchors [H, W, A, 4], variances [H, W, A, 4]); anchor order is
    aspect_ratio-major, size-minor like the reference (:62-64)."""
    H, W = int(input.shape[2]), int(input.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for ar in aspect_ratios:
        area = sw * sh
        base_w = np.round(np.sqrt(area / ar))
        base_h = np.round(base_w * ar)
        for s in anchor_sizes:
            whs.append((s / sw * base_w, s / sh * base_h))
    whs = jnp.asarray(np.asarray(whs, np.float32))          # [A, 2]
    x_ctr = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
    y_ctr = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
    xc = jnp.broadcast_to(x_ctr[None, :, None], (H, W, whs.shape[0]))
    yc = jnp.broadcast_to(y_ctr[:, None, None], (H, W, whs.shape[0]))
    aw = whs[None, None, :, 0]
    ah = whs[None, None, :, 1]
    anchors = jnp.stack([xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                         xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    a = Tensor(anchors)
    v = Tensor(var)
    a.stop_gradient = True
    v.stop_gradient = True
    return a, v


def box_clip(input, im_info, name=None):
    """detection/box_clip_op.h parity: clip [N, M, 4] (or [M, 4]) boxes to
    the image: [0, round(h/scale) - 1] x [0, round(w/scale) - 1];
    im_info rows are (height, width, scale)."""
    def fn(b, info):
        batched = b.ndim == 3
        if not batched:
            b = b[None]
            info = info.reshape(1, -1)
        im_h = jnp.round(info[:, 0] / info[:, 2]).reshape(-1, 1)
        im_w = jnp.round(info[:, 1] / info[:, 2]).reshape(-1, 1)
        x1 = jnp.clip(b[..., 0], 0, im_w - 1)
        y1 = jnp.clip(b[..., 1], 0, im_h - 1)
        x2 = jnp.clip(b[..., 2], 0, im_w - 1)
        y2 = jnp.clip(b[..., 3], 0, im_h - 1)
        out = jnp.stack([x1, y1, x2, y2], axis=-1)
        return out if batched else out[0]

    return apply(fn, _t(input), _t(im_info).detach())


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """detection/target_assign_op.h parity: out[b, p] = input[b, match[b, p]]
    (mismatch rows filled with mismatch_value, weight 0; negative_indices
    entries get mismatch_value with weight 1 — SSD negative mining)."""
    args = [_t(input).detach(), _t(matched_indices).detach()]
    if negative_indices is not None:
        args.append(_t(negative_indices).detach())

    def fn(x, mi, *neg):
        B, P = mi.shape
        mi = mi.astype(jnp.int32)
        matched = mi >= 0
        safe = jnp.where(matched, mi, 0)
        out = jnp.take_along_axis(
            x, safe[:, :, None] if x.ndim == 3 else safe, axis=1)
        fill = jnp.asarray(mismatch_value, x.dtype)
        out = jnp.where(matched[:, :, None] if x.ndim == 3 else matched,
                        out, fill)
        wt = matched.astype(jnp.float32)
        if neg:
            ni = neg[0].astype(jnp.int32)                    # [B, Q]
            bidx = jnp.broadcast_to(jnp.arange(B)[:, None], ni.shape)
            valid = ni >= 0
            dump = jnp.where(valid, ni, P)
            wt = jnp.concatenate([wt, jnp.zeros((B, 1), wt.dtype)], axis=1)
            wt = wt.at[bidx.reshape(-1), dump.reshape(-1)].set(1.0)[:, :P]
            if x.ndim == 3:
                out = jnp.concatenate(
                    [out, jnp.zeros((B, 1, out.shape[2]), out.dtype)], axis=1
                ).at[bidx.reshape(-1), dump.reshape(-1)].set(fill)[:, :P]
            else:
                out = jnp.concatenate(
                    [out, jnp.zeros((B, 1), out.dtype)], axis=1
                ).at[bidx.reshape(-1), dump.reshape(-1)].set(fill)[:, :P]
        return out, (wt[:, :, None] if x.ndim == 3 else wt)

    out, wt = apply(fn, *args)
    out.stop_gradient = True
    wt.stop_gradient = True
    return out, wt


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None):
    """detection/yolov3_loss_op.h parity (vectorized; loss per image [N]).

    x [N, mask_num*(5+C), H, W]; gt_box [N, B, 4] normalized (cx, cy, w, h);
    gt_label [N, B]; anchors = flat [a0w, a0h, ...]; anchor_mask = this
    level's anchor indices. Per-gt best-anchor matching scatters positives;
    objectness cells whose predicted box IoUs any gt above ignore_thresh are
    excluded from the negative term (obj target semantics of :384-397). The
    whole thing is differentiable through XLA (no hand-written grad kernel).
    """
    mask_num = len(anchor_mask)
    an_np = np.asarray(anchors, np.float32).reshape(-1, 2)   # [an_num, 2]
    an_masked = an_np[list(anchor_mask)]                     # [mask_num, 2]
    scale, bias = scale_x_y, -0.5 * (scale_x_y - 1.0)

    args = [_t(x), _t(gt_box).detach(), _t(gt_label).detach()]
    if gt_score is not None:
        args.append(_t(gt_score).detach())

    smooth = min(1.0 / class_num, 1.0 / 40) if use_label_smooth else 0.0
    pos_lab, neg_lab = 1.0 - smooth, smooth

    def sce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    def fn(xv, gb, gl, *gs):
        N, _, H, W = xv.shape
        input_size = downsample_ratio * H
        xv = xv.reshape(N, mask_num, 5 + class_num, H, W)
        score = (gs[0] if gs else jnp.ones(gb.shape[:2], xv.dtype))
        gl = gl.astype(jnp.int32)
        valid = (gb[..., 2] > 0) & (gb[..., 3] > 0)          # [N, B]

        amw = jnp.asarray(an_masked[:, 0])
        amh = jnp.asarray(an_masked[:, 1])
        # predicted boxes (for the ignore mask)
        gx = (jnp.arange(W)[None, :] + jax.nn.sigmoid(xv[:, :, 0]) * scale
              + bias) / W
        gy = (jnp.arange(H)[:, None] + jax.nn.sigmoid(xv[:, :, 1]) * scale
              + bias) / H
        gw = jnp.exp(xv[:, :, 2]) * amw[None, :, None, None] / input_size
        gh = jnp.exp(xv[:, :, 3]) * amh[None, :, None, None] / input_size

        def iou_cwh(ax, ay, aw_, ah_, bx, by, bw, bh):
            ax1, ay1 = ax - aw_ / 2, ay - ah_ / 2
            ax2, ay2 = ax + aw_ / 2, ay + ah_ / 2
            bx1, by1 = bx - bw / 2, by - bh / 2
            bx2, by2 = bx + bw / 2, by + bh / 2
            iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0)
            ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0)
            inter = iw * ih
            return inter / jnp.maximum(aw_ * ah_ + bw * bh - inter, 1e-10)

        # best IoU of each predicted box vs any valid gt: [N, mask, H, W]
        ious = iou_cwh(
            gx[:, :, :, :, None], gy[:, :, :, :, None],
            gw[:, :, :, :, None], gh[:, :, :, :, None],
            gb[:, None, None, None, :, 0], gb[:, None, None, None, :, 1],
            gb[:, None, None, None, :, 2], gb[:, None, None, None, :, 3])
        ious = jnp.where(valid[:, None, None, None, :], ious, 0.0)
        ignore = jnp.max(ious, axis=-1) > ignore_thresh      # [N, mask, H, W]

        # per-gt best anchor over ALL anchors (wh IoU at origin)
        all_aw = jnp.asarray(an_np[:, 0]) / input_size
        all_ah = jnp.asarray(an_np[:, 1]) / input_size
        inter = (jnp.minimum(gb[..., 2:3], all_aw[None, None, :])
                 * jnp.minimum(gb[..., 3:4], all_ah[None, None, :]))
        union = (gb[..., 2:3] * gb[..., 3:4]
                 + all_aw[None, None, :] * all_ah[None, None, :] - inter)
        best_n = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N, B]
        # map to this level's mask slot (-1 if not ours)
        mask_arr = jnp.asarray(np.asarray(anchor_mask, np.int64))
        mask_idx = jnp.argmax(mask_arr[None, None, :] == best_n[..., None],
                              axis=-1)
        ours = jnp.any(mask_arr[None, None, :] == best_n[..., None], axis=-1)
        take = valid & ours                                   # [N, B]

        gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)

        # gather predictions at each gt's cell: [N, B, 5+C]
        flat = xv.reshape(N, mask_num, 5 + class_num, H * W)
        cell = gj * W + gi                                    # [N, B]
        midx = jnp.where(take, mask_idx, 0).astype(jnp.int32)
        pred = jnp.take_along_axis(
            jnp.take_along_axis(
                flat, midx[:, :, None, None] *
                jnp.ones((1, 1, 5 + class_num, H * W), jnp.int32), axis=1),
            cell[:, :, None, None] *
            jnp.ones((1, 1, 5 + class_num, 1), jnp.int32), axis=3)[:, :, :, 0]

        tx = gb[..., 0] * W - gi
        ty = gb[..., 1] * H - gj
        aw_t = jnp.take(jnp.asarray(an_np[:, 0]), best_n)
        ah_t = jnp.take(jnp.asarray(an_np[:, 1]), best_n)
        tw = jnp.log(jnp.maximum(gb[..., 2] * input_size / aw_t, 1e-9))
        th = jnp.log(jnp.maximum(gb[..., 3] * input_size / ah_t, 1e-9))
        loc_scale = (2.0 - gb[..., 2] * gb[..., 3]) * score
        loc = (sce(pred[..., 0], tx) + sce(pred[..., 1], ty)
               + jnp.abs(pred[..., 2] - tw) + jnp.abs(pred[..., 3] - th)
               ) * loc_scale
        cls_t = jax.nn.one_hot(gl, class_num) * (pos_lab - neg_lab) + neg_lab
        cls = jnp.sum(sce(pred[..., 5:], cls_t), axis=-1) * score
        per_gt = jnp.where(take, loc + cls, 0.0)              # [N, B]

        # objectness target map: later gts win on cell collisions (reference
        # loop order). JAX scatter-set with duplicate indices is unordered, so
        # pick the winner deterministically: scatter-max each gt's (t+1) into
        # the cell, then only the gt matching that rank contributes its score.
        Bn = gb.shape[1]
        dest = jnp.where(take, midx * H * W + cell, mask_num * H * W)
        bidx = jnp.broadcast_to(jnp.arange(N)[:, None], dest.shape)
        ranks = jnp.broadcast_to(jnp.arange(1, Bn + 1)[None, :], dest.shape)
        order = jnp.zeros((N, mask_num * H * W + 1), jnp.int32).at[
            bidx.reshape(-1), dest.reshape(-1)].max(
                jnp.where(take, ranks, 0).reshape(-1))
        winner = take & (jnp.take_along_axis(order, dest, axis=1) == ranks)
        obj_t = jnp.zeros((N, mask_num * H * W + 1), xv.dtype).at[
            bidx.reshape(-1), dest.reshape(-1)].add(
                jnp.where(winner, score, 0.0).reshape(-1))
        obj_t = obj_t[:, :mask_num * H * W].reshape(N, mask_num, H, W)
        conf = xv[:, :, 4]
        pos = obj_t > 1e-5
        obj_loss = jnp.where(pos, sce(conf, 1.0) * obj_t,
                             jnp.where(ignore, 0.0, sce(conf, 0.0)))
        return jnp.sum(per_gt, axis=1) + jnp.sum(obj_loss, axis=(1, 2, 3))

    return apply(fn, *args)


_DENSITY_PRIOR_CACHE = {}


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variances, clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """detection/density_prior_box_op.h parity: per-cell density-sampled SSD
    priors. input [N, C, H, W] feature map, image [N, C, Hi, Wi]. Returns
    (boxes [H, W, P, 4] normalized (or [H*W*P, 4] when flatten_to_2d),
    variances same shape)."""
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    key = (H, W, img_h, img_w, tuple(densities), tuple(fixed_sizes),
           tuple(fixed_ratios), tuple(np.ravel(variances)), bool(clip),
           tuple(steps), float(offset))
    cached = _DENSITY_PRIOR_CACHE.get(key)
    if cached is None:
        step_w = steps[0] if steps[0] > 0 else img_w / W
        step_h = steps[1] if steps[1] > 0 else img_h / H
        step_avg = int(0.5 * (step_w + step_h))

        # vectorized over the grid: per-cell prior geometry is identical, so
        # build the per-cell offsets once and broadcast-add the cell centers
        cxs = (np.arange(W) + offset) * step_w                  # [W]
        cys = (np.arange(H) + offset) * step_h                  # [H]
        rel = []                                                # per-prior (dx, dy, bw, bh)
        for fs, density in zip(fixed_sizes, densities):
            shift = step_avg // density
            base = -step_avg / 2.0 + shift / 2.0
            for fr in fixed_ratios:
                bw = fs * np.sqrt(fr)
                bh = fs / np.sqrt(fr)
                for di in range(density):
                    for dj in range(density):
                        rel.append((base + dj * shift, base + di * shift,
                                    bw, bh))
        rel = np.asarray(rel, np.float32)                       # [P, 4]
        P = rel.shape[0]
        cxt = cxs[None, :, None] + rel[None, None, :, 0]        # [1, W, P]
        cyt = cys[:, None, None] + rel[None, None, :, 1]        # [H, 1, P]
        cxt = np.broadcast_to(cxt, (H, W, P))
        cyt = np.broadcast_to(cyt, (H, W, P))
        bw = rel[None, None, :, 2]
        bh = rel[None, None, :, 3]
        arr = np.stack([
            np.maximum((cxt - bw / 2.0) / img_w, 0.0),
            np.maximum((cyt - bh / 2.0) / img_h, 0.0),
            np.minimum((cxt + bw / 2.0) / img_w, 1.0),
            np.minimum((cyt + bh / 2.0) / img_h, 1.0),
        ], axis=-1).astype(np.float32)
        if clip:
            arr = np.clip(arr, 0.0, 1.0)
        var = np.broadcast_to(np.asarray(variances, np.float32),
                              arr.shape).copy()
        cached = (arr, var)
        _DENSITY_PRIOR_CACHE[key] = cached
    arr, var = cached
    if flatten_to_2d:
        arr = arr.reshape(-1, 4)
        var = var.reshape(-1, 4)
    b = Tensor(jnp.asarray(arr))
    v = Tensor(jnp.asarray(var))
    b.stop_gradient = True
    v.stop_gradient = True
    return b, v


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    """detection/collect_fpn_proposals_op.h parity: merge per-level RoIs,
    keep the global top post_nms_top_n by score (inverse of
    distribute_fpn_proposals). Eager, single-image LoD-free form."""
    rois = np.concatenate([np.asarray(_t(r)._data).reshape(-1, 4)
                           for r in multi_rois], axis=0)
    scores = np.concatenate([np.asarray(_t(s)._data).reshape(-1)
                             for s in multi_scores], axis=0)
    k = min(post_nms_top_n, len(scores))
    order = np.argsort(-scores, kind="stable")[:k]
    out = Tensor(jnp.asarray(rois[order]))
    out.stop_gradient = True
    if rois_num_per_level is not None:
        return out, Tensor(jnp.asarray(np.asarray([k], np.int32)))
    return out


def polygon_box_transform(input, name=None):
    """detection/polygon_box_transform_op.cc parity (EAST-style geometry →
    quad coordinates): even channels out = 4*w_idx - in, odd channels
    out = 4*h_idx - in."""
    def fn(v):
        N, C, H, W = v.shape
        wk = 4.0 * jnp.arange(W, dtype=v.dtype)[None, None, None, :]
        hk = 4.0 * jnp.arange(H, dtype=v.dtype)[None, None, :, None]
        even = jnp.arange(C) % 2 == 0
        return jnp.where(even[None, :, None, None], wk - v, hk - v)

    return apply(fn, _t(input))


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative", name=None):
    """detection/mine_hard_examples_op.cc parity (SSD negative mining).

    cls_loss/match_dist [B, P]; match_indices [B, P] (-1 = unmatched).
    max_negative: eligible = unmatched priors with dist < neg_dist_threshold,
    keep the top num_pos*neg_pos_ratio by cls_loss. hard_example: every prior
    is eligible, loss = cls+loc, keep sample_size, and positives that are not
    selected get their match index erased. Returns (neg_indices list of [k_b]
    arrays, updated_match_indices [B, P])."""
    cl = np.asarray(_t(cls_loss)._data)
    mi = np.asarray(_t(match_indices)._data).astype(np.int64)
    md = np.asarray(_t(match_dist)._data)
    ll = np.asarray(_t(loc_loss)._data) if loc_loss is not None else None
    B, P = mi.shape
    neg_out, updated = [], mi.copy()
    for n in range(B):
        if mining_type == "max_negative":
            elig = (mi[n] == -1) & (md[n] < neg_dist_threshold)
            loss = cl[n]
            num_pos = int((mi[n] != -1).sum())
            cap = int(num_pos * neg_pos_ratio)
        elif mining_type == "hard_example":
            elig = np.ones(P, bool)
            loss = cl[n] + (ll[n] if ll is not None else 0.0)
            cap = sample_size
        else:
            raise ValueError("mining_type must be max_negative or hard_example")
        cand = np.nonzero(elig)[0]
        order = cand[np.argsort(-loss[cand], kind="stable")]
        sel = order[: min(cap, len(order))]
        sel_set = set(int(s) for s in sel)
        if mining_type == "hard_example":
            for m in range(P):
                if mi[n, m] > -1 and m not in sel_set:
                    updated[n, m] = -1
            neg = sorted(s for s in sel_set if mi[n, s] == -1)
        else:
            neg = sorted(sel_set)
        neg_out.append(Tensor(jnp.asarray(np.asarray(neg, np.int32))))
    upd = Tensor(jnp.asarray(updated))
    upd.stop_gradient = True
    return neg_out, upd


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, gt_boxes, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, name=None):
    """detection/rpn_target_assign_op.cc parity (Faster-RCNN RPN sampling,
    Detectron-matching two-direction assignment :190-205).

    Per image: fg = anchors holding any gt's max overlap OR IoU >=
    rpn_positive_overlap, subsampled to fg_fraction*batch_size; bg = anchors
    with max IoU < rpn_negative_overlap, subsampled to the remainder (bg
    sampling may demote sampled fg — the fg_fake/bbox_inside_weight dance at
    :235-250 is reproduced). Eager host op (dynamic output counts, like the
    reference CPU kernel). Returns (loc_index, score_index, tgt_bbox,
    tgt_lbl, bbox_inside_weight) for a single image.
    """
    anchors = np.asarray(_t(anchor_box)._data).reshape(-1, 4)
    gts = np.asarray(_t(gt_boxes)._data).reshape(-1, 4)
    A, G = len(anchors), len(gts)
    rng_ = np.random.RandomState(0)

    # IoU anchor x gt
    ov = np.zeros((A, G), np.float32)
    for j in range(G):
        ix1 = np.maximum(anchors[:, 0], gts[j, 0])
        iy1 = np.maximum(anchors[:, 1], gts[j, 1])
        ix2 = np.minimum(anchors[:, 2], gts[j, 2])
        iy2 = np.minimum(anchors[:, 3], gts[j, 3])
        iw = np.maximum(ix2 - ix1 + 1, 0)
        ih = np.maximum(iy2 - iy1 + 1, 0)
        inter = iw * ih
        aa = (anchors[:, 2] - anchors[:, 0] + 1) * (anchors[:, 3] - anchors[:, 1] + 1)
        ga = (gts[j, 2] - gts[j, 0] + 1) * (gts[j, 3] - gts[j, 1] + 1)
        ov[:, j] = inter / np.maximum(aa + ga - inter, 1e-10)
    a2g_max = ov.max(axis=1) if G else np.zeros(A, np.float32)
    a2g_arg = ov.argmax(axis=1) if G else np.zeros(A, np.int64)
    g2a_max = ov.max(axis=0) if G else np.zeros(0, np.float32)

    def reservoir(cands, k):
        cands = list(cands)
        if k <= 0 or len(cands) <= k:
            return cands
        if not use_random:
            return cands[:k]
        out = cands[:k]
        for i in range(k, len(cands)):
            j = rng_.randint(0, i + 1)
            if j < k:
                out[j] = cands[i]
        return out

    eps = 1e-5
    with_max = (np.abs(ov - g2a_max[None, :]) < eps).any(axis=1) if G else np.zeros(A, bool)
    fg_fake_inds = reservoir(
        np.nonzero(with_max | (a2g_max >= rpn_positive_overlap))[0],
        int(rpn_fg_fraction * rpn_batch_size_per_im))
    label = np.full(A, -1, np.int64)
    label[np.asarray(fg_fake_inds, np.int64)] = 1
    fg_fake_num = len(fg_fake_inds)

    bg_cands = np.nonzero(a2g_max < rpn_negative_overlap)[0]
    bg_sel = reservoir(bg_cands, rpn_batch_size_per_im - fg_fake_num)

    fg_fake, inside_w = [], []
    fake_num = 0
    for b in bg_sel:
        if label[b] == 1:  # demoted fg keeps a zero-weight loc slot
            fake_num += 1
            fg_fake.append(int(fg_fake_inds[0]))
            inside_w.extend([0.0] * 4)
        label[b] = 0
    inside_w.extend([1.0] * 4 * (fg_fake_num - fake_num))

    fg_inds = np.nonzero(label == 1)[0]
    bg_inds = np.nonzero(label == 0)[0]
    fg_fake.extend(int(i) for i in fg_inds)
    loc_index = np.asarray(fg_fake, np.int32)
    score_index = np.concatenate([fg_inds, bg_inds]).astype(np.int32)
    tgt_lbl = np.concatenate([np.ones(len(fg_inds), np.int32),
                              np.zeros(len(bg_inds), np.int32)])

    # box deltas anchor -> matched gt for each loc_index entry
    def deltas(aidx):
        a = anchors[aidx]
        g = gts[a2g_arg[aidx]] if G else a
        aw, ah = a[2] - a[0] + 1, a[3] - a[1] + 1
        acx, acy = a[0] + aw / 2, a[1] + ah / 2
        gw, gh = g[2] - g[0] + 1, g[3] - g[1] + 1
        gcx, gcy = g[0] + gw / 2, g[1] + gh / 2
        return [(gcx - acx) / aw, (gcy - acy) / ah,
                np.log(gw / aw), np.log(gh / ah)]

    tgt_bbox = np.asarray([deltas(i) for i in loc_index], np.float32).reshape(-1, 4)
    iw_arr = np.asarray(inside_w, np.float32).reshape(-1, 4)

    outs = [Tensor(jnp.asarray(loc_index)), Tensor(jnp.asarray(score_index)),
            Tensor(jnp.asarray(tgt_bbox)),
            Tensor(jnp.asarray(tgt_lbl.reshape(-1, 1))),
            Tensor(jnp.asarray(iw_arr))]
    for t in outs:
        t.stop_gradient = True
    return tuple(outs)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes, im_info,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, name=None):
    """detection/generate_proposal_labels_op.cc parity (Fast-RCNN stage-2
    sampler), single image: gt boxes join the candidate pool, fg = RoIs with
    max gt IoU >= fg_thresh (subsampled to fg_fraction*batch), bg = RoIs with
    IoU in [bg_thresh_lo, bg_thresh_hi) (fills the remainder, labeled 0).
    Returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights) — targets one-hot-expanded per class like the
    reference (class-agnostic collapses to a single foreground slot)."""
    rois = np.asarray(_t(rpn_rois)._data).reshape(-1, 4)
    gts = np.asarray(_t(gt_boxes)._data).reshape(-1, 4)
    cls = np.asarray(_t(gt_classes)._data).reshape(-1).astype(np.int64)
    crowd = (np.asarray(_t(is_crowd)._data).reshape(-1).astype(np.int64)
             if is_crowd is not None else np.zeros(len(gts), np.int64))
    rng_ = np.random.RandomState(0)

    # gt boxes participate as candidates (reference appends them)
    pool = np.concatenate([rois, gts], axis=0) if len(gts) else rois
    P, G = len(pool), len(gts)
    ov = np.zeros((P, max(G, 1)), np.float32)
    for j in range(G):
        if crowd[j]:
            continue
        ix1 = np.maximum(pool[:, 0], gts[j, 0])
        iy1 = np.maximum(pool[:, 1], gts[j, 1])
        ix2 = np.minimum(pool[:, 2], gts[j, 2])
        iy2 = np.minimum(pool[:, 3], gts[j, 3])
        iw = np.maximum(ix2 - ix1 + 1, 0)
        ih = np.maximum(iy2 - iy1 + 1, 0)
        inter = iw * ih
        pa = (pool[:, 2] - pool[:, 0] + 1) * (pool[:, 3] - pool[:, 1] + 1)
        ga = (gts[j, 2] - gts[j, 0] + 1) * (gts[j, 3] - gts[j, 1] + 1)
        ov[:, j] = inter / np.maximum(pa + ga - inter, 1e-10)
    mx = ov.max(axis=1)
    arg = ov.argmax(axis=1)

    fg_cand = np.nonzero(mx >= fg_thresh)[0]
    bg_cand = np.nonzero((mx >= bg_thresh_lo) & (mx < bg_thresh_hi))[0]
    fg_per_im = int(np.floor(batch_size_per_im * fg_fraction))
    n_fg = min(fg_per_im, len(fg_cand))
    if use_random and len(fg_cand) > n_fg:
        fg_sel = rng_.choice(fg_cand, n_fg, replace=False)
    else:
        fg_sel = fg_cand[:n_fg]
    n_bg = min(batch_size_per_im - n_fg, len(bg_cand))
    if use_random and len(bg_cand) > n_bg:
        bg_sel = rng_.choice(bg_cand, n_bg, replace=False)
    else:
        bg_sel = bg_cand[:n_bg]

    sel = np.concatenate([fg_sel, bg_sel]).astype(np.int64)
    out_rois = pool[sel]
    labels = np.concatenate([
        cls[arg[fg_sel]] if G else np.zeros(len(fg_sel), np.int64),
        np.zeros(len(bg_sel), np.int64)]).astype(np.int32)

    # box regression targets (fg only), weighted like the reference
    wx, wy, ww, wh = bbox_reg_weights
    n_cls = 2 if is_cls_agnostic else class_nums
    targets = np.zeros((len(sel), 4 * n_cls), np.float32)
    inside = np.zeros_like(targets)
    for k, ridx in enumerate(fg_sel):
        a = pool[ridx]
        g = gts[arg[ridx]] if G else a
        aw, ah = a[2] - a[0] + 1, a[3] - a[1] + 1
        acx, acy = a[0] + aw / 2, a[1] + ah / 2
        gw, gh = g[2] - g[0] + 1, g[3] - g[1] + 1
        gcx, gcy = g[0] + gw / 2, g[1] + gh / 2
        d = [(gcx - acx) / aw / wx, (gcy - acy) / ah / wy,
             np.log(gw / aw) / ww, np.log(gh / ah) / wh]
        c = 1 if is_cls_agnostic else int(labels[k])
        targets[k, 4 * c: 4 * c + 4] = d
        inside[k, 4 * c: 4 * c + 4] = 1.0
    outside = (inside > 0).astype(np.float32)

    outs = [Tensor(jnp.asarray(out_rois.astype(np.float32))),
            Tensor(jnp.asarray(labels.reshape(-1, 1))),
            Tensor(jnp.asarray(targets)),
            Tensor(jnp.asarray(inside)),
            Tensor(jnp.asarray(outside))]
    for t in outs:
        t.stop_gradient = True
    return tuple(outs)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """paddle.vision.ops.yolo_loss 2.x alias of yolov3_loss."""
    return yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                       ignore_thresh, downsample_ratio, gt_score=gt_score,
                       use_label_smooth=use_label_smooth, scale_x_y=scale_x_y)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135, name=None):
    """detection/box_decoder_and_assign_op.h parity (Cascade-RCNN): decode
    per-class deltas against each RoI (+1-width convention, dw/dh clipped to
    box_clip), then assign each RoI the decoded box of its best non-background
    class. Returns (decode_box [R, C*4], output_assign_box [R, 4])."""
    def fn(pb, pv, tb, sc):
        R = pb.shape[0]
        C = sc.shape[1]
        pw = pb[:, 2] - pb[:, 0] + 1
        ph = pb[:, 3] - pb[:, 1] + 1
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        d = tb.reshape(R, C, 4)
        dw = jnp.minimum(pv[2] * d[..., 2], box_clip)
        dh = jnp.minimum(pv[3] * d[..., 3], box_clip)
        cx = pv[0] * d[..., 0] * pw[:, None] + pcx[:, None]
        cy = pv[1] * d[..., 1] * ph[:, None] + pcy[:, None]
        bw = jnp.exp(dw) * pw[:, None]
        bh = jnp.exp(dh) * ph[:, None]
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], axis=-1)
        # best non-background class per roi (class 0 = background)
        masked = jnp.where(jnp.arange(C)[None, :] > 0, sc, -jnp.inf)
        best = jnp.argmax(masked, axis=1)
        assign = jnp.take_along_axis(
            boxes, jnp.broadcast_to(best[:, None, None].astype(jnp.int32),
                                    (boxes.shape[0], 1, 4)), axis=1)[:, 0]
        return boxes.reshape(R, C * 4), assign

    db, ab = apply(fn, _t(prior_box).detach(), _t(prior_box_var).detach(),
                   _t(target_box), _t(box_score).detach())
    return db, ab


def prroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """prroi_pool_op parity (Precise RoI Pooling, Acquisition-of-Localization):
    each bin averages the EXACT integral of the bilinearly-interpolated
    feature over its continuous region — no sampling-point quantization.

    TPU design: the 2-D integral of a bilinear surface is separable, so the
    bin reduces to wx^T F wy / area where wx[i] / wy[j] are the integrals of
    the hat basis at column i / row j over the bin interval — two small
    matvecs per bin instead of the reference's per-pixel accumulation loop.
    Fully differentiable (the reference ships a hand-written grad kernel).
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph_n, pw_n = output_size

    xv = _t(x)
    bv = _t(boxes).detach()
    bn = np.asarray(_t(boxes_num)._data).astype(np.int64)
    img_of_roi = np.repeat(np.arange(len(bn)), bn)

    def fn(feat, rois):
        N, C, H, W = feat.shape
        img_idx = jnp.asarray(img_of_roi, jnp.int32)

        def hat_weights(a, b, n):
            """Integral of each hat basis (center k, support [k-1, k+1]) over
            [a, b], vectorized over k = 0..n-1."""
            k = jnp.arange(n, dtype=jnp.float32)

            def seg(lo, hi, kk, rising):
                lo_c = jnp.maximum(lo, a)
                hi_c = jnp.minimum(hi, b)
                L = jnp.maximum(hi_c - lo_c, 0.0)
                mid = (lo_c + hi_c) / 2.0
                # hat value at midpoint integrates exactly (linear segment)
                val = jnp.where(rising, mid - (kk - 1), (kk + 1) - mid)
                return L * val

            return seg(k - 1, k, k, True) + seg(k, k + 1, k, False)

        def one(roi, im):
            x1 = roi[0] * spatial_scale
            y1 = roi[1] * spatial_scale
            x2 = roi[2] * spatial_scale
            y2 = roi[3] * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.0)
            rw = jnp.maximum(x2 - x1, 0.0)
            bin_h = rh / ph_n
            bin_w = rw / pw_n
            fmap = feat[im]

            def bin_val(phw):
                ph, pw = phw // pw_n, phw % pw_n
                ya = y1 + ph * bin_h
                yb = ya + bin_h
                xa = x1 + pw * bin_w
                xb = xa + bin_w
                wy = hat_weights(ya, yb, H)
                wx = hat_weights(xa, xb, W)
                area = jnp.maximum(bin_h * bin_w, 1e-9)
                return jnp.einsum("h,chw,w->c", wy, fmap, wx) / area

            vals = jax.vmap(bin_val)(jnp.arange(ph_n * pw_n))
            return vals.T.reshape(C, ph_n, pw_n)

        return jax.vmap(one)(rois, img_idx)

    return apply(fn, xv, bv)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                       nms_threshold=0.3, normalized=True,
                       background_label=-1, name=None):
    """detection/locality_aware_nms_op.cc parity (EAST text detection):
    a sequential pass over boxes in input order score-weighted-MERGES runs of
    mutually-overlapping boxes (:102-128), then standard multiclass NMS runs
    on the merged survivors. Eager host op (the merge is order-dependent).
    bboxes [N, M, 4], scores [N, C, M] -> (out [N, keep_top_k, 6], num [N])."""
    bv = np.asarray(_t(bboxes)._data)
    sv = np.asarray(_t(scores)._data)
    off = 0.0 if normalized else 1.0

    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]) + off)
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]) + off)
        inter = ix * iy
        ar_a = max(0, a[2] - a[0] + off) * max(0, a[3] - a[1] + off)
        ar_b = max(0, b[2] - b[0] + off) * max(0, b[3] - b[1] + off)
        u = ar_a + ar_b - inter
        return inter / u if u > 0 else 0.0

    N, C, M = sv.shape
    outs, nums = [], []
    for n in range(N):
        entries = []
        for c in range(C):
            if c == background_label:
                continue
            boxes = bv[n].copy()
            sc = sv[n, c].copy()
            skip = np.ones(M, bool)
            idx = -1
            for i in range(M):
                if idx > -1:
                    if iou(boxes[i], boxes[idx]) > nms_threshold:
                        si, sx = sc[i], sc[idx]
                        boxes[idx] = (boxes[i] * si + boxes[idx] * sx) / (si + sx)
                        sc[idx] += sc[i]
                    else:
                        skip[idx] = False
                        idx = i
                else:
                    idx = i
            if idx > -1:
                skip[idx] = False
            keep = np.nonzero((~skip) & (sc > score_threshold))[0]
            keep = keep[np.argsort(-sc[keep], kind="stable")]
            if nms_top_k > -1:
                keep = keep[:nms_top_k]
            if len(keep):
                kmask = np.asarray(nms_mask(jnp.asarray(boxes[keep]),
                                            jnp.asarray(sc[keep]),
                                            nms_threshold))
                for k in keep[kmask]:
                    entries.append([float(c), sc[k], *boxes[k]])
        entries.sort(key=lambda e: -e[1])
        entries = entries[:keep_top_k]
        nums.append(len(entries))
        pad = [[-1.0] * 6] * (keep_top_k - len(entries))
        outs.append(np.asarray(entries + pad, np.float32).reshape(keep_top_k, 6))
    out_t = Tensor(jnp.asarray(np.stack(outs)))
    num_t = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    out_t.stop_gradient = True
    num_t.stop_gradient = True
    return out_t, num_t


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3, nms_eta=1.0,
                               name=None):
    """detection/retinanet_detection_output_op.cc parity: multi-level (FPN)
    RetinaNet post-processing — per level, threshold the [cells*A, C] sigmoid
    scores (last level thresholds at 0), keep nms_top_k, decode the top
    candidates' anchor deltas (+1 convention, clipped to the rescaled image),
    then per-class NMS over the union and keep_top_k. Single image, eager.
    bboxes/scores/anchors: lists per level ([M_l, 4], [M_l, C], [M_l, 4]);
    im_info (h, w, scale). Returns (out [k, 6], num)."""
    info = np.asarray(_t(im_info)._data).reshape(-1)
    im_h = round(float(info[0]) / float(info[2]))
    im_w = round(float(info[1]) / float(info[2]))
    scale = float(info[2])

    cand = []  # (class, score, box)
    L = len(scores)
    for l in range(L):
        sc = np.asarray(_t(scores[l])._data).reshape(-1)
        bx = np.asarray(_t(bboxes[l])._data).reshape(-1, 4)
        an = np.asarray(_t(anchors[l])._data).reshape(-1, 4)
        C = np.asarray(_t(scores[l])._data).shape[-1]
        thr = score_threshold if l < L - 1 else 0.0
        keep = np.nonzero(sc > thr)[0]
        keep = keep[np.argsort(-sc[keep], kind="stable")][:nms_top_k]
        for idx in keep:
            a, c = idx // C, idx % C
            aw = an[a, 2] - an[a, 0] + 1
            ah = an[a, 3] - an[a, 1] + 1
            acx = an[a, 0] + aw / 2
            acy = an[a, 1] + ah / 2
            cx = bx[a, 0] * aw + acx
            cy = bx[a, 1] * ah + acy
            bw = np.exp(bx[a, 2]) * aw
            bh = np.exp(bx[a, 3]) * ah
            box = np.array([cx - bw / 2, cy - bh / 2,
                            cx + bw / 2 - 1, cy + bh / 2 - 1]) / scale
            box[0::2] = np.clip(box[0::2], 0, im_w - 1)
            box[1::2] = np.clip(box[1::2], 0, im_h - 1)
            cand.append((int(c), float(sc[idx]), box))

    entries = []
    if cand:
        classes = sorted(set(c for c, _, _ in cand))
        for c in classes:
            cl = [(s, b) for cc, s, b in cand if cc == c]
            cl.sort(key=lambda e: -e[0])
            boxes_c = np.stack([b for _, b in cl])
            sc_c = np.asarray([s for s, _ in cl], np.float32)
            kmask = np.asarray(nms_mask(jnp.asarray(boxes_c),
                                        jnp.asarray(sc_c), nms_threshold,
                                        use_pallas=False))
            for k in np.nonzero(kmask)[0]:
                entries.append([float(c), sc_c[k], *boxes_c[k]])
        entries.sort(key=lambda e: -e[1])
        entries = entries[:keep_top_k]
    n = len(entries)
    pad = [[-1.0] * 6] * (keep_top_k - n)
    out = Tensor(jnp.asarray(np.asarray(entries + pad, np.float32)))
    num = Tensor(jnp.asarray(np.asarray([n], np.int32)))
    out.stop_gradient = True
    num.stop_gradient = True
    return out, num


def roi_perspective_transform(x, rois, transformed_height, transformed_width,
                              spatial_scale=1.0, name=None):
    """detection/roi_perspective_transform_op.cc parity (OCR text
    rectification): each RoI is a quadrilateral [x0 y0 .. x3 y3]; the op
    builds the projective map from the output rectangle onto the quad
    (:110-168 — width normalized by the quad's estimated aspect) and
    bilinearly samples the feature map (out-of-bounds reads 0).

    x [N, C, H, W]; rois [R, 8] with every RoI belonging to image 0..N-1 via
    `rois_num`-free single-image usage (reference uses LoD; here all RoIs
    sample image 0 unless rois has a leading batch column). Returns
    (out [R, C, th, tw], mask [R, 1, th, tw], transform_matrix [R, 9])."""
    th, tw = int(transformed_height), int(transformed_width)
    xv = _t(x)
    rv = _t(rois).detach()

    def fn(feat, quads):
        N, C, H, W = feat.shape
        R = quads.shape[0]

        def one(quad):
            qx = quad[0::2] * spatial_scale
            qy = quad[1::2] * spatial_scale
            len1 = jnp.sqrt((qx[0] - qx[1]) ** 2 + (qy[0] - qy[1]) ** 2)
            len2 = jnp.sqrt((qx[1] - qx[2]) ** 2 + (qy[1] - qy[2]) ** 2)
            len3 = jnp.sqrt((qx[2] - qx[3]) ** 2 + (qy[2] - qy[3]) ** 2)
            len4 = jnp.sqrt((qx[3] - qx[0]) ** 2 + (qy[3] - qy[0]) ** 2)
            est_h = (len2 + len4) / 2.0
            est_w = (len1 + len3) / 2.0
            nh = max(2, th)
            nw = jnp.clip(jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-5)
                                    ) + 1, 2, tw)
            dx1, dx2 = qx[1] - qx[2], qx[3] - qx[2]
            dx3 = qx[0] - qx[1] + qx[2] - qx[3]
            dy1, dy2 = qy[1] - qy[2], qy[3] - qy[2]
            dy3 = qy[0] - qy[1] + qy[2] - qy[3]
            den = dx1 * dy2 - dx2 * dy1 + 1e-5
            m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
            m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
            m8 = 1.0
            m3 = (qy[1] - qy[0] + m6 * (nw - 1) * qy[1]) / (nw - 1)
            m4 = (qy[3] - qy[0] + m7 * (nh - 1) * qy[3]) / (nh - 1)
            m5 = qy[0]
            m0 = (qx[1] - qx[0] + m6 * (nw - 1) * qx[1]) / (nw - 1)
            m1 = (qx[3] - qx[0] + m7 * (nh - 1) * qx[3]) / (nh - 1)
            m2 = qx[0]
            mat = jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])

            ww = jnp.arange(tw, dtype=jnp.float32)[None, :]
            hh = jnp.arange(th, dtype=jnp.float32)[:, None]
            u = m0 * ww + m1 * hh + m2
            v = m3 * ww + m4 * hh + m5
            w_ = m6 * ww + m7 * hh + m8
            in_w = u / w_
            in_h = v / w_
            # reference also zeroes output+mask when the source point falls
            # OUTSIDE the quadrilateral (roi_perspective_transform_op.cc:303)
            # — even-odd crossing test against the 4-gon
            inq = jnp.zeros(in_w.shape, bool)
            on_edge = jnp.zeros(in_w.shape, bool)
            for e in range(4):
                xi, yi = qx[e], qy[e]
                xj, yj = qx[(e + 3) % 4], qy[(e + 3) % 4]
                crosses = ((yi > in_h) != (yj > in_h)) & (
                    in_w < (xj - xi) * (in_h - yi) / (yj - yi + 1e-12) + xi)
                inq = inq ^ crosses
                # reference in_quad counts points ON an edge as inside (:46-60)
                cross = (xj - xi) * (in_h - yi) - (yj - yi) * (in_w - xi)
                seg_len = jnp.sqrt((xj - xi) ** 2 + (yj - yi) ** 2) + 1e-12
                near = jnp.abs(cross) / seg_len < 1e-3
                inseg = ((in_w >= jnp.minimum(xi, xj) - 1e-3)
                         & (in_w <= jnp.maximum(xi, xj) + 1e-3)
                         & (in_h >= jnp.minimum(yi, yj) - 1e-3)
                         & (in_h <= jnp.maximum(yi, yj) + 1e-3))
                on_edge = on_edge | (near & inseg)
            inq = inq | on_edge
            inb = (inq & (in_w > -0.5) & (in_w < W - 0.5)
                   & (in_h > -0.5) & (in_h < H - 0.5))

            x0 = jnp.floor(in_w)
            y0 = jnp.floor(in_h)
            wx = in_w - x0
            wy = in_h - y0

            def at(yy, xx):
                ok = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
                yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                return feat[0][:, yc, xc] * ok[None]

            val = (at(y0, x0) * (1 - wy) * (1 - wx)
                   + at(y0, x0 + 1) * (1 - wy) * wx
                   + at(y0 + 1, x0) * wy * (1 - wx)
                   + at(y0 + 1, x0 + 1) * wy * wx)
            out = val * inb[None]
            return out, inb.astype(jnp.int32)[None], mat

        outs, masks, mats = jax.vmap(one)(quads)
        return outs, masks, mats

    o, m, t = apply(fn, xv, rv)
    m.stop_gradient = True
    t.stop_gradient = True
    return o, m, t


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4, name=None):
    """detection/rpn_target_assign_op.cc:875 RetinanetTargetAssign parity:
    the RPN two-direction assignment with NO subsampling (every anchor is
    labeled), fg targets carry the matched gt's CLASS label (not 1), bg = 0.
    Returns (loc_index, score_index, tgt_bbox, tgt_lbl, bbox_inside_weight,
    fg_num) for one image; fg_num = #fg + 1 (the reference's focal-loss
    normalizer convention)."""
    anchors = np.asarray(_t(anchor_box)._data).reshape(-1, 4)
    gts = np.asarray(_t(gt_boxes)._data).reshape(-1, 4)
    labels_np = np.asarray(_t(gt_labels)._data).reshape(-1).astype(np.int64)
    crowd = (np.asarray(_t(is_crowd)._data).reshape(-1).astype(np.int64)
             if is_crowd is not None else np.zeros(len(gts), np.int64))
    # gt boxes arrive in ORIGINAL image coords; anchors live on the resized
    # image — scale gts by im_scale like the reference (:~975)
    if im_info is not None:
        im_scale = float(np.asarray(_t(im_info)._data).reshape(-1)[2])
        gts = gts * im_scale
    keep_gt = crowd == 0
    gts = gts[keep_gt]
    labels_np = labels_np[keep_gt]
    A, G = len(anchors), len(gts)

    ov = np.zeros((A, max(G, 1)), np.float32)
    for j in range(G):
        ix1 = np.maximum(anchors[:, 0], gts[j, 0])
        iy1 = np.maximum(anchors[:, 1], gts[j, 1])
        ix2 = np.minimum(anchors[:, 2], gts[j, 2])
        iy2 = np.minimum(anchors[:, 3], gts[j, 3])
        iw = np.maximum(ix2 - ix1 + 1, 0)
        ih = np.maximum(iy2 - iy1 + 1, 0)
        inter = iw * ih
        aa = (anchors[:, 2] - anchors[:, 0] + 1) * (anchors[:, 3] - anchors[:, 1] + 1)
        ga = (gts[j, 2] - gts[j, 0] + 1) * (gts[j, 3] - gts[j, 1] + 1)
        ov[:, j] = inter / np.maximum(aa + ga - inter, 1e-10)
    a2g_max = ov.max(axis=1) if G else np.zeros(A, np.float32)
    a2g_arg = ov.argmax(axis=1) if G else np.zeros(A, np.int64)
    g2a_max = ov.max(axis=0) if G else np.zeros(0, np.float32)

    eps = 1e-5
    with_max = (np.abs(ov - g2a_max[None, :]) < eps).any(axis=1) if G else np.zeros(A, bool)
    fg_cand = with_max | (a2g_max >= positive_overlap)
    # reference bg loop (rpn_target_assign_op.cc:236-246) demotes fg anchors
    # whose max IoU is below negative_overlap back to background, keeping a
    # zero-weight loc slot (duplicated first fg candidate) for each
    below_neg = a2g_max < negative_overlap
    demoted = fg_cand & below_neg
    fg_mask = fg_cand & ~below_neg
    bg_mask = below_neg                      # includes the demoted anchors
    fg_inds = np.nonzero(fg_mask)[0]
    bg_inds = np.nonzero(bg_mask)[0]
    n_demoted = int(demoted.sum())
    fg_cand_inds = np.nonzero(fg_cand)[0]
    first_fg = int(fg_cand_inds[0]) if len(fg_cand_inds) else 0
    loc_index = np.concatenate([
        np.full(n_demoted, first_fg, np.int64), fg_inds]).astype(np.int64)
    inside_w = np.concatenate([
        np.zeros((n_demoted, 4), np.float32),
        np.ones((len(fg_inds), 4), np.float32)], axis=0)

    def deltas(aidx):
        a = anchors[aidx]
        g = gts[a2g_arg[aidx]] if G else a
        aw, ah = a[2] - a[0] + 1, a[3] - a[1] + 1
        acx, acy = a[0] + aw / 2, a[1] + ah / 2
        gw, gh = g[2] - g[0] + 1, g[3] - g[1] + 1
        gcx, gcy = g[0] + gw / 2, g[1] + gh / 2
        return [(gcx - acx) / aw, (gcy - acy) / ah,
                np.log(gw / aw), np.log(gh / ah)]

    tgt_bbox = np.asarray([deltas(i) for i in loc_index],
                          np.float32).reshape(-1, 4)
    tgt_lbl = np.concatenate([
        labels_np[a2g_arg[fg_inds]] if G else np.zeros(len(fg_inds), np.int64),
        np.zeros(len(bg_inds), np.int64)]).astype(np.int32)
    score_index = np.concatenate([fg_inds, bg_inds]).astype(np.int32)
    outs = [Tensor(jnp.asarray(loc_index.astype(np.int32))),
            Tensor(jnp.asarray(score_index)),
            Tensor(jnp.asarray(tgt_bbox)),
            Tensor(jnp.asarray(tgt_lbl.reshape(-1, 1))),
            Tensor(jnp.asarray(inside_w)),
            Tensor(jnp.asarray(np.asarray([len(loc_index) + 1], np.int32)))]
    for t in outs:
        t.stop_gradient = True
    return tuple(outs)


def deformable_psroi_pooling(input, rois, trans, no_trans=False,
                             spatial_scale=1.0, group_size=(1, 1),
                             pooled_height=1, pooled_width=1,
                             part_size=None, sample_per_part=1,
                             trans_std=0.1, position_sensitive=True,
                             boxes_num=None, name=None):
    """deformable_psroi_pooling_op.cu parity (deformable R-FCN head): each
    bin samples sample_per_part^2 bilinear points, shifted by the learned
    normalized offsets trans[r, 2, part_h, part_w]*trans_std*roi_size; the
    channel is picked position-sensitively via group_size. All RoIs read
    image 0 (single-image eager form). Returns [R, output_dim, ph, pw]."""
    ph_n, pw_n = int(pooled_height), int(pooled_width)
    gh_n, gw_n = (int(group_size[0]), int(group_size[1]))
    if part_size is None:
        part_size = (ph_n, pw_n)
    pth, ptw = int(part_size[0]), int(part_size[1])

    xv = _t(input)
    rv = _t(rois).detach()
    args = [xv, rv]
    if trans is not None and not no_trans:
        args.append(_t(trans))

    def fn(feat, rois_v, *tr):
        N, C, H, W = feat.shape
        out_dim = C // (gh_n * gw_n) if position_sensitive else C
        trans_v = tr[0] if tr else None

        def one(ri):
            roi = rois_v[ri]
            x1 = jnp.round(roi[0]) * spatial_scale - 0.5
            y1 = jnp.round(roi[1]) * spatial_scale - 0.5
            x2 = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
            y2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bh, bw = rh / ph_n, rw / pw_n
            sh, sw = bh / sample_per_part, bw / sample_per_part

            def bin_val(phw):
                ph, pw = phw // pw_n, phw % pw_n
                part_h = (ph * pth) // ph_n
                part_w = (pw * ptw) // pw_n
                tx = (trans_v[ri, 0, part_h, part_w] * trans_std
                      if trans_v is not None else 0.0)
                ty = (trans_v[ri, 1, part_h, part_w] * trans_std
                      if trans_v is not None else 0.0)
                ws = pw * bw + x1 + tx * rw
                hs = ph * bh + y1 + ty * rh
                gw = jnp.clip((pw * gw_n) // pw_n, 0, gw_n - 1)
                gh = jnp.clip((ph * gh_n) // ph_n, 0, gh_n - 1)
                if position_sensitive:
                    ch = (jnp.arange(out_dim) * gh_n + gh) * gw_n + gw
                else:
                    ch = jnp.arange(out_dim)
                fm = feat[0][ch]                       # [out_dim, H, W]

                ihs = jnp.arange(sample_per_part, dtype=jnp.float32)
                iws = jnp.arange(sample_per_part, dtype=jnp.float32)
                hh = hs + ihs[:, None] * sh            # [s, 1]
                wwv = ws + iws[None, :] * sw           # [1, s]
                hh = jnp.broadcast_to(hh, (sample_per_part, sample_per_part))
                wwv = jnp.broadcast_to(wwv, (sample_per_part, sample_per_part))
                inb = ((wwv >= -0.5) & (wwv <= W - 0.5)
                       & (hh >= -0.5) & (hh <= H - 0.5))
                wc = jnp.clip(wwv, 0.0, W - 1.0)
                hc = jnp.clip(hh, 0.0, H - 1.0)
                x0 = jnp.floor(wc)
                y0 = jnp.floor(hc)
                ax = wc - x0
                ay = hc - y0

                def at(yy, xx):
                    yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                    xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                    return fm[:, yi, xi]               # [out_dim, s, s]

                val = (at(y0, x0) * (1 - ay) * (1 - ax)
                       + at(y0, x0 + 1) * (1 - ay) * ax
                       + at(y0 + 1, x0) * ay * (1 - ax)
                       + at(y0 + 1, x0 + 1) * ay * ax)
                cnt = jnp.maximum(jnp.sum(inb), 1)
                return jnp.sum(val * inb[None], axis=(1, 2)) / cnt

            vals = jax.vmap(bin_val)(jnp.arange(ph_n * pw_n))
            return vals.T.reshape(out_dim, ph_n, pw_n)

        return jax.vmap(one)(jnp.arange(rois_v.shape[0]))

    return apply(fn, *args)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution, name=None):
    """detection/generate_mask_labels_op.cc parity (Mask R-CNN mask targets):
    each fg RoI (label > 0) is matched (IoU vs the polygons' bounding boxes,
    in unscaled image coords) to a non-crowd gt; the gt's polygons are
    rasterized within the RoI at resolution^2 (even-odd point-in-polygon on
    the bin-center grid, the Polys2MaskWrtBox recipe) and one-hot-expanded to
    [fg, num_classes*res^2] with -1 outside the class slot. Eager host op.

    gt_segms: list (per gt) of lists of flat polygons [x0, y0, x1, y1, ...].
    Returns (mask_rois [fg, 4], roi_has_mask_int32 [fg, 1], mask_int32)."""
    info = np.asarray(_t(im_info)._data).reshape(-1)
    im_scale = float(info[2])
    cls = np.asarray(_t(gt_classes)._data).reshape(-1).astype(np.int64)
    crowd = np.asarray(_t(is_crowd)._data).reshape(-1).astype(np.int64)
    rois_np = np.asarray(_t(rois)._data).reshape(-1, 4)
    labels = np.asarray(_t(labels_int32)._data).reshape(-1).astype(np.int64)

    keep = [(i, gt_segms[i]) for i in range(len(cls))
            if cls[i] > 0 and crowd[i] == 0]
    gt_polys = [p for _, p in keep]
    gt_ids = [i for i, _ in keep]
    boxes = np.zeros((len(gt_polys), 4), np.float32)
    for k, polys in enumerate(gt_polys):
        pts = np.concatenate([np.asarray(p, np.float32).reshape(-1, 2)
                              for p in polys])
        boxes[k] = [pts[:, 0].min(), pts[:, 1].min(),
                    pts[:, 0].max(), pts[:, 1].max()]

    fg_inds = np.nonzero(labels > 0)[0]
    res = int(resolution)
    M = res * res
    mask_t = -np.ones((max(len(fg_inds), 1), num_classes * M), np.int32)
    out_rois = np.zeros((max(len(fg_inds), 1), 4), np.float32)

    def in_polys(px, py, polys):
        inside = np.zeros(px.shape, bool)
        for poly in polys:
            pts = np.asarray(poly, np.float32).reshape(-1, 2)
            n = len(pts)
            acc = np.zeros(px.shape, bool)
            j = n - 1
            for i in range(n):
                xi, yi = pts[i]
                xj, yj = pts[j]
                crosses = ((yi > py) != (yj > py)) & (
                    px < (xj - xi) * (py - yi) / (yj - yi + 1e-12) + xi)
                acc ^= crosses
                j = i
            inside |= acc
        return inside

    for k, ridx in enumerate(fg_inds):
        roi = rois_np[ridx] / im_scale
        out_rois[k] = rois_np[ridx]
        if len(boxes):
            ix1 = np.maximum(roi[0], boxes[:, 0])
            iy1 = np.maximum(roi[1], boxes[:, 1])
            ix2 = np.minimum(roi[2], boxes[:, 2])
            iy2 = np.minimum(roi[3], boxes[:, 3])
            inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
            ra = (roi[2] - roi[0]) * (roi[3] - roi[1])
            ba = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
            best = int(np.argmax(inter / np.maximum(ra + ba - inter, 1e-10)))
            polys = gt_polys[best]
        else:
            polys = []
        # the mask goes into the RoI's OWN class slot (reference gathers
        # mask_class_labels from labels_int32); the matched gt only supplies
        # the polygon geometry
        c = int(labels[ridx])
        w = max(roi[2] - roi[0], 1e-3)
        h = max(roi[3] - roi[1], 1e-3)
        gx = roi[0] + (np.arange(res) + 0.5) * w / res
        gy = roi[1] + (np.arange(res) + 0.5) * h / res
        px, py = np.meshgrid(gx, gy)
        m = in_polys(px, py, polys).astype(np.int32).reshape(-1)
        c = min(max(c, 0), num_classes - 1)
        mask_t[k, c * M:(c + 1) * M] = m

    n_fg = len(fg_inds)
    outs = (Tensor(jnp.asarray(out_rois[:max(n_fg, 1)])),
            Tensor(jnp.asarray(fg_inds.astype(np.int32).reshape(-1, 1)
                               if n_fg else np.zeros((1, 1), np.int32))),
            Tensor(jnp.asarray(mask_t)))
    for t in outs:
        t.stop_gradient = True
    return outs
