"""Device cost registry: per-executable FLOPs/bytes/HBM accounting.

XLA already knows what every compiled program costs —
``compiled.cost_analysis()`` (flops, bytes accessed) and
``compiled.memory_analysis()`` (argument/output/temp/peak HBM) — but
until now that data only surfaced in ad-hoc scripts
(tools/profile_gpt.py, tools/pipeline_memory.py). This module captures
it ONCE at every compile site — ``Executor._compile``,
``SpmdTrainer._aot_compile``, the ``ServingEngine``/``Predictor``
``CachedJit`` program family, including AOT-cache deserialize hits in
framework/aot.py — into a per-executable table keyed ``(site, sig)``,
and exports it as gauges:

- ``program_flops{site,sig}`` — per-execution FLOPs of the executable;
- ``program_hbm_bytes{site,kind}`` — kind in
  ``peak|argument|output|temp|generated_code`` for the site's most
  recently captured executable (full per-sig detail: :func:`table`);
- ``device_hbm_used_bytes{device}`` — sampled from
  ``device.memory_stats()`` where the backend provides it
  (:func:`sample_device_memory`; TPU yes, CPU no).

Joined with measured step wall time this is the roofline/MFU layer
(Tensor Processing Primitives, arXiv:2104.05755): a step's model FLOPs
over ``wall_time × peak_flops`` — ``SpmdTrainer.stats()["mfu"]`` and
``ServingEngine.stats()["breakdown"]`` read through :func:`get`.

Capture never raises: a backend whose executables lack cost analysis
degrades to an absent entry, not a crashed compile path.
"""
import threading

from .. import flags as _flags
from .. import monitor as _monitor

__all__ = ["record", "record_manual", "get", "table", "reset",
           "sample_device_memory", "peak_flops", "peak_hbm_bandwidth"]

_flags.define_flag(
    "device_peak_flops", 0.0,
    "peak device FLOP/s used as the MFU denominator; 0 = auto from the "
    "device kind table (unknown kinds fall back to a nominal 1e12 so "
    "MFU stays finite — absolute values are only meaningful on known "
    "hardware)")

_LOCK = threading.Lock()
_TABLE = {}   # (site, sig) -> entry dict

_FLOPS_G = None
_HBM_G = None
_DEV_G = None

_HBM_KINDS = ("peak", "argument", "output", "temp", "generated_code")

#: bf16 peak FLOP/s per chip by device-kind substring (TPU datasheet
#: numbers); matched case-insensitively, first hit wins
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_NOMINAL_PEAK = 1e12

#: HBM bytes/s per chip by device-kind substring (approximate datasheet
#: numbers — the bandwidth side of the roofline the plan-search cost
#: model prices against); same matching rules as the FLOPs table
_PEAK_HBM_BW_BY_KIND = (
    ("v6e", 1.6e12),
    ("v5p", 2.8e12),
    ("v5e", 0.8e12),
    ("v5 lite", 0.8e12),
    ("v4", 1.2e12),
    ("v3", 0.9e12),
    ("v2", 0.7e12),
)
_NOMINAL_HBM_BW = 1e11


def _gauges():
    global _FLOPS_G, _HBM_G, _DEV_G
    if _FLOPS_G is None:
        _FLOPS_G = _monitor.gauge(
            "program_flops",
            "per-execution FLOPs of a compiled executable "
            "(XLA cost_analysis)", labelnames=("site", "sig"))
        _HBM_G = _monitor.gauge(
            "program_hbm_bytes",
            "HBM footprint of the site's most recently captured "
            "executable by kind (XLA memory_analysis; per-sig detail in "
            "trace.costs.table())", labelnames=("site", "kind"))
        _DEV_G = _monitor.gauge(
            "device_hbm_used_bytes",
            "live device memory in use (device.memory_stats(), where the "
            "backend provides it)", labelnames=("device",))
    return _FLOPS_G, _HBM_G, _DEV_G


def _cost_dict(compiled):
    """cost_analysis() returns a dict on some backends, a one-element
    list of dicts on others — normalize to one merged dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, dict):
        return ca
    out = {}
    for d in ca or []:
        for k, v in d.items():
            out[k] = out.get(k, 0.0) + float(v)
    return out


def record(site, sig, compiled):
    """Capture one executable's cost+memory analysis under (site, sig).
    `compiled` may be None (bypass paths) — a no-op then. Returns the
    entry dict or None. Never raises."""
    if compiled is None:
        return None
    try:
        cost = _cost_dict(compiled)
        entry = {"site": str(site), "sig": str(sig),
                 "flops": float(cost.get("flops", 0.0)),
                 "bytes_accessed": float(cost.get("bytes accessed", 0.0))}
        try:
            ma = compiled.memory_analysis()
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            out = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            gen = int(getattr(ma, "generated_code_size_in_bytes", 0))
            # donated buffers appear in BOTH argument and output sizes;
            # alias_size is that overlap — subtract it or the serving
            # decode programs (which donate the KV caches, their largest
            # buffers) overstate peak HBM by up to 2x
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            entry.update(argument_bytes=arg, output_bytes=out,
                         temp_bytes=tmp, generated_code_bytes=gen,
                         alias_bytes=alias,
                         peak_bytes=arg + out + tmp + gen - alias)
        except Exception:
            pass
    except Exception:
        return None
    with _LOCK:
        _TABLE[(str(site), str(sig))] = entry
    if _monitor.is_enabled():
        flops_g, hbm_g, _ = _gauges()
        flops_g.labels(site=site, sig=sig).set(entry["flops"])
        for kind in _HBM_KINDS:
            v = entry.get(f"{kind}_bytes")
            if v is not None:
                hbm_g.labels(site=site, kind=kind).set(v)
    return entry


def record_manual(site, sig, flops=0.0, bytes_accessed=0.0):
    """Capture an ANALYTIC cost entry under (site, sig) — for work that
    has no standalone executable to ask, e.g. a Pallas micro-kernel
    living inside a larger jitted program (ops/tpp.py registers each
    op's per-call FLOPs/bytes here under site="tpp"). Repeated calls
    ACCUMULATE (a kernel invoked N times per trace reports N times its
    per-call cost) and bump a ``calls`` field; the same gauges as
    :func:`record` are updated. Never raises."""
    try:
        with _LOCK:
            entry = _TABLE.get((str(site), str(sig)))
            if entry is None:
                entry = {"site": str(site), "sig": str(sig),
                         "flops": 0.0, "bytes_accessed": 0.0, "calls": 0}
                _TABLE[(str(site), str(sig))] = entry
            entry["flops"] += float(flops)
            entry["bytes_accessed"] += float(bytes_accessed)
            entry["calls"] += 1
            snap = dict(entry)
        if _monitor.is_enabled():
            flops_g, _, _ = _gauges()
            flops_g.labels(site=site, sig=sig).set(snap["flops"])
        return snap
    except Exception:
        return None


def get(site, sig):
    """The captured entry for (site, sig), or None."""
    with _LOCK:
        return _TABLE.get((str(site), str(sig)))


def table():
    """Snapshot of every captured entry (list of dicts)."""
    with _LOCK:
        return [dict(v) for v in _TABLE.values()]


def reset():
    with _LOCK:
        _TABLE.clear()


def sample_device_memory():
    """Set device_hbm_used_bytes{device} from device.memory_stats() for
    every device that reports it; returns {device_str: bytes_in_use}.
    CPU backends report nothing — the gauge simply stays absent."""
    import jax

    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        used = stats.get("bytes_in_use")
        if used is None:
            continue
        out[str(d)] = int(used)
        if _monitor.is_enabled():
            _gauges()[2].labels(device=str(d)).set(int(used))
    return out


def peak_flops(device=None):
    """The MFU denominator: FLAGS_device_peak_flops when set, else the
    device-kind table, else a nominal 1e12 (keeps MFU finite on backends
    with no published peak, e.g. the CPU test harness)."""
    override = float(_flags.get_flag("device_peak_flops", 0.0) or 0.0)
    if override > 0:
        return override
    import jax

    d = device or jax.devices()[0]
    kind = str(getattr(d, "device_kind", d.platform)).lower()
    for needle, flops in _PEAK_FLOPS_BY_KIND:
        if needle in kind:
            return flops
    return _NOMINAL_PEAK


def peak_hbm_bandwidth(device=None):
    """Peak HBM bytes/s from the device-kind table, else a nominal
    1e11 — the bandwidth denominator of the roofline
    (analysis/cost_model.py prices ``max(flops/peak, bytes/bw)`` with
    it; like :func:`peak_flops`, absolute values only mean something on
    known hardware)."""
    import jax

    d = device or jax.devices()[0]
    kind = str(getattr(d, "device_kind", d.platform)).lower()
    for needle, bw in _PEAK_HBM_BW_BY_KIND:
        if needle in kind:
            return bw
    return _NOMINAL_HBM_BW
