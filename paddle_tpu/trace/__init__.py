"""Structured tracing: spans with explicit trace/span ids and parent links.

Reference parity: platform/profiler.{h,cc} builds a RecordEvent TREE and
tools/timeline.py converts it into a chrome://tracing timeline. The host
shim in paddle_tpu.profiler kept only the flat event list; this module is
the tree — every span carries a ``trace_id`` (one per logical unit of
work: a serving request, a train step), a ``span_id``, and a
``parent_id``, so a single slow request can be followed across
queue-wait, prefill chunks, and decode steps even when those slices
interleave with other requests inside the same engine step.

Three ways to produce a span:

- ``with span("name", subsystem="serving", **attrs):`` — nests on a
  thread-local stack (parent/trace ids inherited automatically);
- ``s = start_span(...); ...; s.end(**attrs)`` — explicit lifetime for
  work that crosses function/step boundaries (a request's root span
  lives from ``submit()`` to its finish reason);
- ``emit(name, start_ns=..., end_ns=..., ...)`` — retro-record a slice
  whose window was measured with ``time.perf_counter_ns()`` (the serving
  engine emits one per-slot ``decode`` span per batched device step).

Spans land in a bounded thread-safe ring buffer (``FLAGS_trace_buffer``
capacity; oldest dropped) and, when ``FLAGS_trace_log_path`` is set, are
appended as JSONL through the monitor event-log writer. Disabled mode
(``FLAGS_trace`` unset, the default) is ONE boolean check per call —
same discipline as monitor/failpoints, pinned <5µs/call by
tests/test_trace_gate.py.

``export_chrome(path)`` merges three sources into one chrome://tracing
JSON (docs/OBSERVABILITY.md):

- profiler RecordEvent host events (sorted by start time — nesting
  renders from ts/dur ordering);
- trace spans, one chrome *process* per subsystem, with flow events
  linking every multi-span trace_id across threads;
- span-boundary counter samples (``add_counter_sample``) as ph="C"
  counter tracks.

The sibling :mod:`paddle_tpu.trace.costs` is the device cost registry:
per-executable ``cost_analysis()``/``memory_analysis()`` tables captured
at every compile site, joined with step spans for MFU/step-time
breakdowns (``SpmdTrainer.stats()["mfu"]``,
``ServingEngine.stats()["breakdown"]``).
"""
import collections
import contextlib
import itertools
import json
import threading
import time

from .. import flags as _flags

__all__ = [
    "Span", "span", "start_span", "emit", "current_span", "new_trace_id",
    "enable", "disable", "is_enabled", "sync_from_flag", "clear",
    "spans", "open_spans", "set_capacity", "capacity", "summary",
    "top_spans", "add_counter_sample", "counter_samples", "export_chrome",
    "load_spans", "costs",
]

_flags.define_flag(
    "trace", False,
    "structured span tracing on/off (paddle_tpu/trace); off turns every "
    "span call site into one boolean check (tests/test_trace_gate.py "
    "pins <5µs/call and zero metric/behavior drift)")
_flags.define_flag(
    "trace_buffer", 4096,
    "span ring-buffer capacity; the oldest spans are dropped past it so "
    "a long-lived traced server cannot OOM the host on span bookkeeping")
_flags.define_flag(
    "trace_log_path", "",
    "JSONL span log path (one 'span' event per finished span via the "
    "monitor event-log writer); empty = ring buffer only")

_ENABLED = [False]          # the ONE read on the disabled fast path
_LOCK = threading.Lock()
_TLS = threading.local()
_SPAN_IDS = itertools.count(1)
_TRACE_IDS = itertools.count(1)
_BUF = collections.deque(maxlen=int(_flags.get_flag("trace_buffer", 4096)))
_SAMPLES = collections.deque(maxlen=4096)   # (ts_ns, name, value)
_OPEN = {}                  # span_id -> OPEN Span (entered/started, not
_OPEN_CAP = 8192            # yet ended) — the blackbox dump's span tree


def is_enabled():
    return _ENABLED[0]


def enable():
    _ENABLED[0] = True


def disable():
    _ENABLED[0] = False


def sync_from_flag():
    """Re-read FLAGS_trace/FLAGS_trace_buffer (after paddle.set_flags)."""
    _ENABLED[0] = bool(_flags.get_flag("trace", False))
    set_capacity(int(_flags.get_flag("trace_buffer", 4096)))


def new_trace_id():
    """A process-unique trace id (one per logical unit of work)."""
    return f"t{next(_TRACE_IDS):08x}"


def set_capacity(n):
    """Resize the ring buffer (keeps the newest spans)."""
    global _BUF
    n = max(1, int(n))
    if n == _BUF.maxlen:
        return
    with _LOCK:
        _BUF = collections.deque(_BUF, maxlen=n)


def capacity():
    return _BUF.maxlen


def clear():
    with _LOCK:
        _BUF.clear()
        _SAMPLES.clear()
        _OPEN.clear()


def spans():
    """Snapshot of the ring buffer (oldest first)."""
    with _LOCK:
        return list(_BUF)


def open_spans():
    """Every span currently OPEN (entered or started, not yet ended) as
    dicts with end_ns=None — the live span tree a blackbox dump bundle
    captures, so a wedge shows WHICH requests/steps were mid-flight."""
    with _LOCK:
        return [sp.to_dict() for sp in _OPEN.values()]


def _track_open(sp):
    with _LOCK:
        if len(_OPEN) >= _OPEN_CAP:   # leaked never-ended spans must not
            _OPEN.pop(next(iter(_OPEN)))   # grow the table without bound
        _OPEN[sp.span_id] = sp
    # flight-recorder OPEN digest (one boolean check when the recorder
    # is off): a span that never closes is exactly the wedge evidence
    _blackbox.note("span_open", name=sp.name, subsystem=sp.subsystem,
                   trace_id=sp.trace_id)


def counter_samples():
    with _LOCK:
        return list(_SAMPLES)


def add_counter_sample(name, value):
    """Record one (ts, name, value) counter sample — rendered as a ph='C'
    track by export_chrome. Call sites sample at span boundaries (the
    serving step samples batch occupancy, the trainer step latency)."""
    if not _ENABLED[0]:
        return
    with _LOCK:
        _SAMPLES.append((time.perf_counter_ns(), str(name), float(value)))


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_span():
    """The innermost OPEN context-manager span on this thread, or None —
    the attribute-attachment hook: current_span().set(k=v)."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


class _NoopSpan:
    """Returned by span()/start_span() when tracing is off: every method
    is a no-op so call sites need no second flag check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def set(self, **attrs):
        return self

    def end(self, **attrs):
        return self


_NOOP = _NoopSpan()


class Span:
    """One timed slice with identity and a parent link."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "subsystem",
                 "attrs", "start_ns", "end_ns", "tid", "_pushed")

    def __init__(self, name, trace_id=None, parent_id=None, subsystem=None,
                 attrs=None, start_ns=None):
        self.name = str(name)
        self.trace_id = trace_id
        self.span_id = next(_SPAN_IDS)
        self.parent_id = parent_id
        self.subsystem = subsystem
        self.attrs = dict(attrs) if attrs else {}
        self.start_ns = (time.perf_counter_ns() if start_ns is None
                         else int(start_ns))
        self.end_ns = None
        self.tid = threading.get_ident()
        self._pushed = False

    # -- context-manager form (thread-local nesting) ----------------------
    def __enter__(self):
        st = _stack()
        if self.parent_id is None and st:
            self.parent_id = st[-1].span_id
            if self.trace_id is None:
                self.trace_id = st[-1].trace_id
        if self.trace_id is None:
            self.trace_id = new_trace_id()
        self.start_ns = time.perf_counter_ns()   # exclude setup time
        st.append(self)
        self._pushed = True
        _track_open(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._pushed:
            st = _stack()
            if st and st[-1] is self:
                st.pop()
            else:                       # tolerate unbalanced exits
                try:
                    st.remove(self)
                except ValueError:
                    pass
            self._pushed = False
        if exc_type is not None:
            # a failing with-block still records its span, marked — the
            # failing step is exactly what a trace gets pulled for
            self.attrs.setdefault("error", True)
        self.end()
        return False

    # -- explicit form ----------------------------------------------------
    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def end(self, **attrs):
        """Stamp the end time and record the span (idempotent)."""
        if self.end_ns is not None:
            return self
        with _LOCK:
            _OPEN.pop(self.span_id, None)
        if attrs:
            self.attrs.update(attrs)
        if self.trace_id is None:
            self.trace_id = new_trace_id()
        self.end_ns = time.perf_counter_ns()
        _record(self)
        return self

    @property
    def duration_ms(self):
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "subsystem": self.subsystem, "tid": self.tid,
                "start_ns": self.start_ns, "end_ns": self.end_ns,
                "attrs": dict(self.attrs)}


def _json_safe(v):
    """Coerce one attribute value for the JSON writers: primitives pass
    through, numpy scalars unwrap via .item(), anything else stringifies
    — a traced workload must never crash inside span.end() because a
    caller attached an array."""
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    item = getattr(v, "item", None)
    if item is not None:
        try:
            got = item()
            if isinstance(got, (int, float, str, bool)):
                return got
        except Exception:
            pass
    return str(v)


def _record(sp):
    with _LOCK:
        _BUF.append(sp)
    _blackbox.note_span(sp)   # flight-recorder close digest (one boolean
    #                           check when the recorder is off)
    path = _flags.get_flag("trace_log_path", "")
    if path:
        from .. import monitor as _monitor

        rec = sp.to_dict()
        rec["attrs"] = {k: _json_safe(v) for k, v in rec["attrs"].items()}
        _monitor.log_event("span", _path=path, **rec)


def _resolve_parent(parent, trace_id):
    """Normalize a parent= argument (Span | span_id int | _NoopSpan from
    a disabled window | None) into (parent_id, trace_id): an explicit
    Span parent donates its trace_id when the caller gave none."""
    if parent is not None and isinstance(parent, Span):
        if trace_id is None:
            trace_id = parent.trace_id
        return parent.span_id, trace_id
    if isinstance(parent, int):
        return parent, trace_id
    return None, trace_id


def span(name, subsystem=None, trace_id=None, parent=None, **attrs):
    """Context-manager span: nests on the thread-local stack, inheriting
    trace/parent ids from the enclosing span (root spans mint a fresh
    trace id); an explicit parent= overrides the stack and the child
    joins ITS trace. Returns a no-op when tracing is disabled."""
    if not _ENABLED[0]:
        return _NOOP
    parent, trace_id = _resolve_parent(parent, trace_id)
    return Span(name, trace_id=trace_id, parent_id=parent,
                subsystem=subsystem, attrs=attrs)


def start_span(name, subsystem=None, trace_id=None, parent=None, **attrs):
    """Begin a span NOW without touching the nesting stack — for work
    that crosses call boundaries; finish it with ``.end(**attrs)``."""
    if not _ENABLED[0]:
        return _NOOP
    parent, trace_id = _resolve_parent(parent, trace_id)
    if trace_id is None:
        # a root started explicitly IS a new trace: mint the id now so
        # children created before .end() inherit it
        trace_id = new_trace_id()
    sp = Span(name, trace_id=trace_id, parent_id=parent,
              subsystem=subsystem, attrs=attrs)
    _track_open(sp)
    return sp


def emit(name, start_ns, end_ns, subsystem=None, trace_id=None, parent=None,
         **attrs):
    """Retro-record one span whose window was already measured (e.g. a
    batched device step attributed to each active slot's request)."""
    if not _ENABLED[0]:
        return _NOOP
    parent, trace_id = _resolve_parent(parent, trace_id)
    sp = Span(name, trace_id=trace_id, parent_id=parent,
              subsystem=subsystem, attrs=attrs, start_ns=start_ns)
    sp.end_ns = int(end_ns)
    if sp.trace_id is None:
        sp.trace_id = new_trace_id()
    _record(sp)
    return sp


@contextlib.contextmanager
def scoped_enabled(on=True):
    """Test helper: flip tracing on/off for a with-block."""
    old = _ENABLED[0]
    _ENABLED[0] = bool(on)
    try:
        yield
    finally:
        _ENABLED[0] = old


# -- summaries ----------------------------------------------------------------

def summary():
    """Aggregate {name: {"count", "total_ms"}} over the ring buffer."""
    agg = {}
    for sp in spans():
        if sp.end_ns is None:
            continue
        st = agg.setdefault(sp.name, {"count": 0, "total_ms": 0.0})
        st["count"] += 1
        st["total_ms"] += (sp.end_ns - sp.start_ns) / 1e6
    return agg


def top_spans(n=3):
    """[(name, total_ms, count)] of the n largest span totals — what
    bench.py's phase heartbeats and metrics_dump --trace attach."""
    rows = [(name, st["total_ms"], st["count"])
            for name, st in summary().items()]
    rows.sort(key=lambda r: -r[1])
    return [(name, round(ms, 3), c) for name, ms, c in rows[:n]]


def snapshot_summary(n=3):
    """The compact trace view shared by bench heartbeats and
    tools/metrics_dump.py --trace: span count + top-n span totals."""
    return {"spans": len(spans()),
            "top": [list(r) for r in top_spans(n)]}


# -- chrome://tracing export ---------------------------------------------------

def export_chrome(path=None, include_host_events=True):
    """Merged chrome://tracing JSON: host RecordEvents + trace spans
    (pid = subsystem, flow events linking each multi-span trace_id) +
    counter samples. Returns the trace dict; writes it when `path` given
    (tools/timeline.py parity, extended with span identity)."""
    events = []
    pids = {"host": 1}

    def pid_of(subsystem):
        key = subsystem or "trace"
        if key not in pids:
            pids[key] = len(pids) + 1
        return pids[key]

    if include_host_events:
        from .. import profiler as _profiler

        for name, s, e, tid, depth in _profiler.host_events():
            events.append({"name": name, "ph": "X", "ts": s / 1e3,
                           "dur": (e - s) / 1e3, "pid": pids["host"],
                           "tid": tid, "cat": "host",
                           "args": {"depth": depth}})

    by_trace = {}
    for sp in sorted(spans(), key=lambda s: s.start_ns):
        if sp.end_ns is None:
            continue
        pid = pid_of(sp.subsystem)
        args = {"trace_id": sp.trace_id, "span_id": sp.span_id}
        if sp.parent_id is not None:
            args["parent_id"] = sp.parent_id
        for k, v in sp.attrs.items():
            args[k] = _json_safe(v)
        events.append({"name": sp.name, "ph": "X", "ts": sp.start_ns / 1e3,
                       "dur": (sp.end_ns - sp.start_ns) / 1e3, "pid": pid,
                       "tid": sp.tid, "cat": "span", "args": args})
        if sp.trace_id is not None:
            by_trace.setdefault(sp.trace_id, []).append((sp, pid))

    # flow events: one chain per trace_id that spans >1 slice, so chrome
    # draws arrows following a request across threads/subsystems
    for tid_, members in by_trace.items():
        if len(members) < 2:
            continue
        flow_id = abs(hash(tid_)) % (1 << 31)
        for i, (sp, pid) in enumerate(members):
            ph = "s" if i == 0 else ("f" if i == len(members) - 1 else "t")
            ev = {"name": "trace", "cat": "flow", "ph": ph, "id": flow_id,
                  "pid": pid, "tid": sp.tid, "ts": sp.start_ns / 1e3}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)

    for ts_ns, name, value in counter_samples():
        events.append({"name": name, "ph": "C", "pid": pid_of("counters"),
                       "ts": ts_ns / 1e3, "args": {name: value}})

    for name, pid in pids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": name}})

    trace_doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path:
        with open(path, "w") as f:
            json.dump(trace_doc, f)
    return trace_doc


def load_spans(path):
    """Read a FLAGS_trace_log_path JSONL span log back into span dicts
    (the 'span' events only) — the round-trip tests pin this."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event") == "span":
                out.append(rec)
    return out


# seed from the environment (FLAGS_trace=1 python serve.py)
sync_from_flag()

# span-close digests feed the black-box flight recorder; imported at the
# bottom (lazily resolved attribute at call time) so the monitor/trace
# import order stays cycle-free whichever package loads first
from ..monitor import blackbox_lazy as _blackbox  # noqa: E402  (ISSUE 12:
# the facade forwards only while the recorder is enabled — a traced but
# unrecorded process never imports monitor/blackbox.py)

from . import costs  # noqa: E402,F401


# ``paddle.trace`` was already a public API before this module existed:
# the matrix-trace op (tensor/math.py). Importing this submodule sets the
# package attribute to the module, which would break ``paddle.trace(x)``
# callers — so the module is made CALLABLE, delegating to the op. Both
# worlds keep working: ``paddle.trace(x, offset=1)`` and
# ``paddle.trace.span("...")`` / ``from paddle_tpu.trace import span``.
import sys as _sys  # noqa: E402


class _CallableTraceModule(type(_sys.modules[__name__])):
    def __call__(self, x, offset=0, axis1=0, axis2=1, name=None):
        from ..tensor.math import trace as _op

        return _op(x, offset=offset, axis1=axis1, axis2=axis2, name=name)


_sys.modules[__name__].__class__ = _CallableTraceModule
