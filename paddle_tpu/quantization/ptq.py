"""Post-training quantization.

Reference parity: fluid/contrib/slim/quantization/post_training_quantization.py —
run calibration batches through the float model collecting activation ranges
(abs_max or histogram percentile, the reference's 'abs_max'/'hist' algos), then emit a
model whose Linear layers hold real int8 weights + scales (Int8Linear).
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.common import Linear
from .layers import Int8Linear
from .quant_ops import quantize_to_int8


def save_quantized_model(model, path_prefix, input_spec):
    """Export a PTQ-converted model as a deployable INT8 artifact
    (reference: slim post_training_quantization's save_quantized_model →
    int8 program + params).

    TPU-native: the Int8Linear buffers (int8 weights + dequant scales) ride
    the standard save_inference_model path — the params npz stores the real
    int8 arrays (4x smaller than f32) and the traced StableHLO/jax.export
    program contains the int8 x int8 -> int32 MXU matmuls, so the AOT
    Predictor serves int8 with no python model code. `input_spec`: list of
    example tensors (None/-1 dims export batch-polymorphic)."""
    from ..static.io import save_inference_model

    return save_inference_model(path_prefix, input_spec, None, layer=model)


class _Observer:
    """Range observer: plain abs_max, or a fixed-size |x| histogram whose range
    grows by proportional rebinning (memory O(hist_bins) per layer, never the
    raw activations)."""

    def __init__(self, algo="abs_max", hist_bins=2048, percentile=0.99999):
        self.algo = algo
        self.hist_bins = hist_bins
        self.percentile = percentile
        self.abs_max = 0.0
        self._hist = None  # counts over [0, abs_max] in hist_bins bins

    def collect(self, arr):
        a = np.abs(np.asarray(arr, np.float32)).reshape(-1)
        cur_max = float(a.max()) if a.size else 0.0
        if self.algo == "hist" and a.size:
            new_max = max(self.abs_max, cur_max)
            if new_max > 0:
                if self._hist is None:
                    self._hist = np.zeros(self.hist_bins, np.float64)
                elif new_max > self.abs_max and self.abs_max > 0:
                    # stretch old bins into the wider range proportionally
                    old_edges = np.linspace(0, self.abs_max, self.hist_bins + 1)
                    centers = (old_edges[:-1] + old_edges[1:]) / 2
                    idx = np.minimum(
                        (centers / new_max * self.hist_bins).astype(int),
                        self.hist_bins - 1)
                    stretched = np.zeros(self.hist_bins, np.float64)
                    np.add.at(stretched, idx, self._hist)
                    self._hist = stretched
                bins = np.minimum((a / new_max * self.hist_bins).astype(int),
                                  self.hist_bins - 1)
                np.add.at(self._hist, bins, 1.0)
        self.abs_max = max(self.abs_max, cur_max)

    def scale(self):
        if self.algo == "hist" and self._hist is not None and self._hist.sum() > 0:
            cdf = np.cumsum(self._hist) / self._hist.sum()
            bin_idx = int(np.searchsorted(cdf, self.percentile))
            edge = (bin_idx + 1) / self.hist_bins * self.abs_max
            return edge or self.abs_max
        return self.abs_max


class PostTrainingQuantization:
    """Calibrate a float model on sample data, then convert Linears to int8.

    usage:
        ptq = PostTrainingQuantization(model, algo="abs_max")
        for batch in calib_loader: ptq.collect(model, batch)   # or ptq.quantize(data)
        qmodel_stats = ptq.convert(model)                      # in place
    """

    def __init__(self, model=None, algo="abs_max", skip_layers=()):
        self.algo = algo
        self.skip_layers = set(skip_layers)
        self._observers = {}
        self._hooks = []
        if model is not None:
            self.attach(model)

    def attach(self, model):
        """Register forward-pre hooks on every Linear to observe input ranges."""
        for name, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, Linear) and name not in self.skip_layers:
                obs = _Observer(self.algo)
                self._observers[name] = obs

                def hook(l, inputs, _obs=obs):
                    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                    _obs.collect(x._data)
                    return None

                self._hooks.append(layer.register_forward_pre_hook(hook))
        return len(self._observers)

    def collect(self, model, *batch):
        """Run one calibration forward (observers collect via hooks)."""
        model.eval()
        return model(*batch)

    def convert(self, model):
        """Replace observed Linears with Int8Linear (real int8 weights). In place."""
        converted = 0
        names = {id(l): n
                 for n, l in model.named_sublayers(include_self=True)}
        for parent in model.sublayers(include_self=True):
            for cname, child in list(parent._sub_layers.items()):
                if not isinstance(child, Linear):
                    continue
                full = names.get(id(child))
                if full is None:
                    continue
                obs = self._observers.get(full)
                if obs is None or obs.abs_max == 0.0:
                    continue
                w_q, w_s = quantize_to_int8(child.weight._data, axis=-1)
                parent._sub_layers[cname] = Int8Linear(
                    w_q, jnp.asarray(w_s), child.bias, obs.scale())
                converted += 1
        for h in self._hooks:
            h.remove()
        self._hooks = []
        return converted
