"""Quantization toolkit — the fluid/contrib/slim capability family.

Reference parity:
- imperative QAT: fluid/contrib/slim/quantization/imperative/qat.py
  (ImperativeQuantAware — wraps Linear/Conv2D with fake-quant of weights+activations)
- static QAT passes: slim/quantization/quantization_pass.py (QuantizationTransformPass)
- post-training: slim/quantization/post_training_quantization.py
- fake-quant ops: operators/fake_quantize_op.cc (abs_max, moving_average_abs_max,
  channel_wise_abs_max)

TPU-native design: fake quantization is a pure jnp function with a
straight-through-estimator gradient (x + stop_gradient(q(x) - x)); there is no graph
pass — QAT is a Layer substitution (QuantedLinear/QuantedConv2D), which jax.jit then
fuses. Int8 inference export stores real int8 weights + scales; the int8 matmul is an
XLA dot over int8 with f32 rescale (MXU-native on TPU).
"""
from .quant_ops import (  # noqa: F401
    dequantize,
    fake_quantize_abs_max,
    fake_quantize_channel_wise_abs_max,
    fake_quantize_moving_average_abs_max,
    fake_quantize_range_abs_max,
    quantize_to_int8,
)
from .imperative import ImperativeQuantAware, QuantConfig  # noqa: F401
from .layers import QuantedConv2D, QuantedLinear  # noqa: F401
from .ptq import PostTrainingQuantization, save_quantized_model  # noqa: F401
