"""Fake-quantize ops with straight-through-estimator gradients.

Reference parity: operators/fake_quantize_op.cc — FakeQuantizeAbsMax,
FakeChannelWiseQuantizeAbsMax, FakeQuantizeMovingAverageAbsMax (the three kernels the
slim QAT passes insert). The STE is expressed as x + stop_gradient(q(x) - x), which
XLA folds into the forward while jax.vjp sees identity — no custom grad op needed.
"""
import jax
import jax.numpy as jnp


def _qmax(bits):
    return float(2 ** (bits - 1) - 1)  # 127 for int8


def _ste(x, q):
    return x + jax.lax.stop_gradient(q - x)


def fake_quantize_abs_max(x, bits=8):
    """Per-tensor abs-max fake quant. Returns (quantized_float, scale)."""
    qmax = _qmax(bits)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax) / qmax * scale
    return _ste(x, q), scale


def fake_quantize_channel_wise_abs_max(x, bits=8, axis=-1):
    """Per-channel (weight) abs-max fake quant along `axis`."""
    qmax = _qmax(bits)
    reduce_axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=reduce_axes, keepdims=True), 1e-8)
    q = jnp.round(x / scale * qmax) / qmax * scale
    return _ste(x, q), scale.reshape(-1)


def fake_quantize_moving_average_abs_max(x, state_scale, bits=8, rate=0.9,
                                         training=True):
    """Activation fake quant with a moving-average abs-max range.

    state_scale: scalar array (the observer state). Returns (q, new_scale).
    In eval mode the stored scale is used unchanged.
    """
    qmax = _qmax(bits)
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    if training:
        new_scale = jnp.where(state_scale > 0, rate * state_scale + (1 - rate) * cur,
                              cur)
    else:
        new_scale = jnp.where(state_scale > 0, state_scale, cur)
    q = jnp.clip(jnp.round(x / new_scale * qmax), -qmax, qmax) / qmax * new_scale
    return _ste(x, q), new_scale


def fake_quantize_range_abs_max(x, scales_window, it, bits=8,
                                window_size=10000, training=True):
    """Activation fake quant with a sliding-window abs-max range
    (operators/fake_quantize_op.cc FakeQuantizeRangeAbsMax /
    FindRangeAbsMaxFunctor): the observer keeps the last `window_size`
    per-step abs-max values and quantizes with their maximum. The
    reference's incremental update (track last max, rescan only when the
    evicted entry WAS the max) is an optimization of exactly this running
    window max — computed directly here, one reduction under jit.

    scales_window: [window_size] array (the observer state, zeros-init);
    it: scalar int32 step counter. Returns (q, new_window, new_it, scale).
    Eval mode quantizes with the stored window max without updating it."""
    qmax = _qmax(bits)
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    if training:
        new_window = scales_window.at[it % window_size].set(cur)
        new_it = it + 1
    else:
        new_window, new_it = scales_window, it
    scale = jnp.maximum(jnp.max(new_window), 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax) / qmax * scale
    return _ste(x, q), new_window, new_it, scale


def quantize_to_int8(w, axis=-1):
    """Real int8 weight quantization for export. Returns (int8 array, f32 scales)."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True), 1e-8)
    q = jnp.clip(jnp.round(w / scale * 127.0), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) / 127.0 * scale
