"""Quantization-aware layers: Linear/Conv2D with fake-quant weights + activations.

Reference parity: the QuantizedLinear/QuantizedConv2D wrappers that
slim/quantization/imperative/qat.py substitutes into the model, backed by the
fake_quantize_op.cc kernels. Weight quant is channel-wise abs_max; activation quant is
moving-average abs_max with the running range stored as a Layer buffer (so it rides the
functional-state path through jit/SpmdTrainer like BatchNorm statistics).
"""
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer
from . import quant_ops as Q


class _QuantedBase(Layer):
    def __init__(self, bits, act_rate):
        super().__init__()
        self.bits = bits
        self.act_rate = act_rate
        self.register_buffer("act_scale", Tensor(jnp.zeros([], jnp.float32)))

    def _fake_quant_input(self, x):
        out, new_scale = apply(
            Q.fake_quantize_moving_average_abs_max, x, self.act_scale,
            bits=self.bits, rate=self.act_rate, training=self.training)
        self.act_scale._data = jnp.asarray(new_scale._data)
        return out

    def _fake_quant_weight(self, w, axis):
        out, _ = apply(Q.fake_quantize_channel_wise_abs_max, w,
                       bits=self.bits, axis=axis)
        return out


class QuantedLinear(_QuantedBase):
    """Linear with fake-quantized input + per-out-channel weight."""

    def __init__(self, layer, bits=8, act_rate=0.9):
        super().__init__(bits, act_rate)
        self.weight = layer.weight  # [in, out]; quant per out channel (axis -1)
        if layer.bias is not None:
            self.bias = layer.bias
        else:
            self.bias = None

    def forward(self, x):
        xq = self._fake_quant_input(x)
        wq = self._fake_quant_weight(self.weight, axis=-1)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(_QuantedBase):
    """Conv2D with fake-quantized input + per-out-channel weight."""

    def __init__(self, layer, bits=8, act_rate=0.9):
        super().__init__(bits, act_rate)
        self.weight = layer.weight  # [out_c, in_c, kh, kw]; quant axis 0
        if layer.bias is not None:
            self.bias = layer.bias
        else:
            self.bias = None
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups
        self._data_format = layer._data_format

    def forward(self, x):
        xq = self._fake_quant_input(x)
        wq = self._fake_quant_weight(self.weight, axis=0)
        return F.conv2d(xq, wq, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Int8Linear(Layer):
    """Inference-only Linear over real int8 weights (PTQ `convert` output).

    The matmul runs int8 x int8 -> int32 on the MXU with a float rescale —
    the TPU-native analog of the mkldnn int8 kernels the reference converts to
    (slim/quantization/quant_int8_mkldnn_pass.py).
    """

    def __init__(self, w_int8, w_scale, bias, act_scale, bits=8):
        super().__init__()
        self.register_buffer("w_int8", Tensor(w_int8))
        self.register_buffer("w_scale", Tensor(w_scale))
        self.register_buffer("act_scale", Tensor(jnp.asarray(act_scale, jnp.float32)))
        self.bias = bias
        self.bits = bits

    def forward(self, x):
        def fn(v, w_q, w_s, a_s, *b):
            qmax = 127.0
            xq = jnp.clip(jnp.round(v / a_s * qmax), -qmax, qmax).astype(jnp.int8)
            acc = jnp.matmul(xq.astype(jnp.int32), w_q.astype(jnp.int32))
            out = acc.astype(jnp.float32) * (a_s / qmax) * (w_s.reshape(1, -1) / qmax)
            if b:
                out = out + b[0]
            return out

        args = [x, self.w_int8, self.w_scale, self.act_scale]
        if self.bias is not None:
            args.append(self.bias)
        return apply(fn, *args)
