"""Imperative quantization-aware training.

Reference parity: fluid/contrib/slim/quantization/imperative/qat.py
(ImperativeQuantAware.quantize — in-place substitution of quantizable sublayers) with
the weight/activation quantizer choices of QuantizationTransformPass
(slim/quantization/quantization_pass.py) reduced to the TPU-relevant pair:
channel_wise_abs_max weights + moving_average_abs_max activations.
"""
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..nn.layer.layers import Layer
from .layers import QuantedConv2D, QuantedLinear


class QuantConfig:
    """Quantization settings (the knobs of ImperativeQuantAware's ctor)."""

    def __init__(self, weight_bits=8, activation_bits=8, act_moving_rate=0.9,
                 quantizable_layer_types=("Linear", "Conv2D"), skip_layers=()):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_moving_rate = act_moving_rate
        self.quantizable_layer_types = tuple(quantizable_layer_types)
        self.skip_layers = set(skip_layers)


class ImperativeQuantAware:
    """Wrap quantizable sublayers of a model with fake-quant QAT layers in place.

    usage:
        quanter = ImperativeQuantAware()
        quanter.quantize(model)          # model now trains with fake quant
        ... train ...
        quanter.save_quantized_model(model, path, input_spec)  # jit.save
    """

    def __init__(self, config=None, **kwargs):
        self.config = config or QuantConfig(**kwargs)

    def _make_quanted(self, layer):
        cfg = self.config
        if isinstance(layer, Linear) and "Linear" in cfg.quantizable_layer_types:
            return QuantedLinear(layer, bits=cfg.weight_bits,
                                 act_rate=cfg.act_moving_rate)
        if isinstance(layer, Conv2D) and "Conv2D" in cfg.quantizable_layer_types:
            return QuantedConv2D(layer, bits=cfg.weight_bits,
                                 act_rate=cfg.act_moving_rate)
        return None

    def quantize(self, model):
        """In-place: replace every quantizable sublayer (skip_layers by name)."""
        replaced = 0
        for parent in model.sublayers(include_self=True):
            for name, child in list(parent._sub_layers.items()):
                if child is None or name in self.config.skip_layers:
                    continue
                q = self._make_quanted(child)
                if q is not None:
                    parent._sub_layers[name] = q
                    replaced += 1
        return replaced

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        model.eval()
        jit.save(model, path, input_spec=input_spec)
