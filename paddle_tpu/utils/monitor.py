"""Named int64 gauges (paddle/fluid/platform/monitor.h:77 StatRegistry + STAT_ADD:130
parity)."""
import threading


class StatRegistry:
    _inst = None
    _lock = threading.Lock()

    def __init__(self):
        self._stats = {}

    @classmethod
    def instance(cls):
        with cls._lock:
            if cls._inst is None:
                cls._inst = cls()
            return cls._inst

    def add(self, name, value):
        self._stats[name] = self._stats.get(name, 0) + int(value)

    def get(self, name):
        return self._stats.get(name, 0)

    def reset(self, name=None):
        if name is None:
            self._stats.clear()
        else:
            self._stats.pop(name, None)

    def stats(self):
        return dict(self._stats)


def stat_add(name, value=1):
    StatRegistry.instance().add(name, value)


def stat_get(name):
    return StatRegistry.instance().get(name)
