"""Unique name generator (python/paddle/fluid/unique_name.py parity)."""
import contextlib

_COUNTERS = {}
_PREFIX = [""]


def generate(key):
    full = _PREFIX[0] + key
    n = _COUNTERS.get(full, 0)
    _COUNTERS[full] = n + 1
    return f"{full}_{n}"


def switch(new_generator=None):
    _COUNTERS.clear()


@contextlib.contextmanager
def guard(new_generator=None):
    old = dict(_COUNTERS)
    old_prefix = _PREFIX[0]
    if isinstance(new_generator, str):
        _PREFIX[0] = new_generator
    _COUNTERS.clear()
    try:
        yield
    finally:
        _COUNTERS.clear()
        _COUNTERS.update(old)
        _PREFIX[0] = old_prefix
