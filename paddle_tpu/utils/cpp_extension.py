"""Custom-op extension path.

Reference parity: python/paddle/utils/cpp_extension/cpp_extension.py:50,206,256
(setup/CppExtension/CUDAExtension + runtime registration through
framework/custom_operator.cc:865).

TPU-native design: a custom op = a python function (optionally backed by a C shared
library via ctypes for host-side work, or a Pallas kernel for device work) plus an
optional custom VJP. `load`/`setup` compile C++ sources with the system toolchain into a
shared library and return a ctypes handle; `register_op` wires a python wrapper into the
autodiff dispatcher.
"""
import ctypes
import os
import subprocess
import sysconfig
import tempfile

_REGISTRY = {}


def register_op(name, forward, backward=None):
    """Register a custom op: forward is a pure jnp function; backward (optional) a
    custom VJP (fn(*inputs, *cotangents) -> input grads)."""
    import jax

    if backward is not None:
        f = jax.custom_vjp(forward)

        def fwd(*args):
            return forward(*args), args

        def bwd(res, g):
            out = backward(*res, g)
            return tuple(out) if isinstance(out, (list, tuple)) else (out,)

        f.defvjp(fwd, bwd)
    else:
        f = forward

    def op(*tensors, **kwargs):
        from ..core.dispatch import apply

        return apply(f, *tensors, **kwargs)

    _REGISTRY[name] = op
    return op


def get_op(name):
    return _REGISTRY[name]


class CppExtension:
    def __init__(self, sources, name=None, extra_compile_args=None, include_dirs=None, **kw):
        self.sources = sources
        self.name = name
        self.extra_compile_args = extra_compile_args or []
        self.include_dirs = include_dirs or []


CUDAExtension = CppExtension  # no CUDA on TPU; accepted for compat, built as C++


def load(name, sources, extra_cxx_cflags=None, build_directory=None, verbose=False, **kw):
    """Compile C++ sources into a shared lib and return a ctypes CDLL
    (cpp_extension.load parity, minus pybind11: use extern "C" symbols)."""
    build_dir = build_directory or tempfile.mkdtemp(prefix="pt_ext_")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"{name}.so")
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", so_path]
    cmd += [f"-I{sysconfig.get_paths()['include']}"]
    cmd += extra_cxx_cflags or []
    cmd += list(sources)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(so_path)


def setup(name=None, ext_modules=None, **kw):
    libs = []
    for ext in ext_modules or []:
        libs.append(load(ext.name or name, ext.sources, ext.extra_compile_args))
    return libs
