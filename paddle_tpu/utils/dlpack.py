"""DLPack interop (paddle/fluid/framework/dlpack_tensor.cc + pybind tensor exchange
parity) — zero-copy with any dlpack-speaking library (torch/numpy/cupy)."""
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def to_dlpack(x):
    return x._data.__dlpack__()


def from_dlpack(capsule):
    arr = jnp.from_dlpack(capsule)
    return Tensor(arr)
