"""try_import (python/paddle/utils/lazy_import.py parity)."""
import importlib


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg:
            raise ImportError(err_msg)
        raise
