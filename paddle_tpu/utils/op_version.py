"""Op version checkpoints (reference python/paddle/utils/op_version.py:50).

The reference tracks per-op attribute/IO changes across framework versions
(core.get_op_version_map) so converters can gate on op compatibility. This
framework has a single op surface (the jnp/lax functionals) with no version
drift to track, so the checker is a faithful-but-empty compat: every query
reports no pending updates."""


class OpUpdateInfoHelper:
    def __init__(self, info):
        self._info = info

    def verify_key_value(self, name=""):
        return name == ""


class OpLastCheckpointChecker:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.raw_version_map = {}
            cls._instance.checkpoints_map = {}
        return cls._instance

    def filter_updates(self, op_name, type=None, key=""):
        return []
