"""paddle.utils parity: unique_name, deprecated, try_import, monitor gauges, dlpack."""
from . import unique_name  # noqa: F401
from .monitor import StatRegistry, stat_add, stat_get  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401


def deprecated(since=None, update_to=None, reason=None):
    def wrap(fn):
        return fn

    return wrap


def run_check():
    """paddle.utils.run_check parity: verifies the device works."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = jax.devices()[0]
    print(f"paddle_tpu works on {dev.platform}:{dev.id} (matmul checksum {float(y.sum()):.0f})")
    return True
