"""paddle.utils parity: unique_name, deprecated, try_import, monitor gauges, dlpack."""
from . import unique_name  # noqa: F401
from .monitor import StatRegistry, stat_add, stat_get  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .op_version import OpLastCheckpointChecker  # noqa: F401


def deprecated(since=None, update_to=None, reason=None):
    def wrap(fn):
        return fn

    return wrap


def run_check():
    """paddle.utils.run_check parity: verifies the device works."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = jax.devices()[0]
    print(f"paddle_tpu works on {dev.platform}:{dev.id} (matmul checksum {float(y.sum()):.0f})")
    return True


def require_version(min_version, max_version=None):
    """paddle.utils.require_version parity — this framework tracks the 2.x
    API surface; accepts any 0/1/2 constraint."""
    return True


def download(url, path=None, md5sum=None):
    """paddle.utils.download parity: zero-egress image — only file:// or
    existing local paths resolve; network URLs raise with a clear message."""
    import os
    import shutil

    src = url[7:] if url.startswith("file://") else url
    if os.path.exists(src):
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            shutil.copy(src, path)
            return path
        return src
    raise RuntimeError(
        f"download({url!r}): no network egress in this environment; place "
        "the file locally and pass its path (or file:// URL)")


class ProfilerOptions:
    def __init__(self, options=None):
        self.options = options or {}


class Profiler:
    """Compat shim over paddle_tpu.profiler (RecordEvent tree + chrome trace)."""

    def __init__(self, enabled=True, options=None):
        self.enabled = enabled
        self.options = options

    def __enter__(self):
        from .. import profiler as P

        if self.enabled:
            P.start_profiler("All")
        return self

    def __exit__(self, *a):
        from .. import profiler as P

        if self.enabled:
            P.stop_profiler()
        return False


def get_profiler(options=None):
    return Profiler(options=options)
