"""TensorBoard-format scalar event writer — no tensorboard/visualdl dep.

The reference's VisualDL callback (hapi/callbacks.py VisualDL) streams
scalars to the visualdl LogWriter; neither visualdl nor tensorboard ships
in this image, so this module hand-emits the standard TF events wire
format that BOTH VisualDL and TensorBoard read: TFRecord framing
(length + masked-crc32c of length, payload, masked-crc32c of payload)
around serialized Event protos carrying Summary/simple_value scalars.
Field numbers from the public event.proto / summary.proto:
  Event:   wall_time=1 (double), step=2 (int64), file_version=3 (string),
           summary=5 (message)
  Summary: value=1 (repeated); Summary.Value: tag=1 (string),
           simple_value=2 (float)
A reader for the same subset lives here too; the tests round-trip files
through it.
"""
import os
import struct
import time

# ---- crc32c (Castagnoli), table-driven -------------------------------------
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---- protobuf wire helpers (varint + length-delimited + fixed) -------------

def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _f_varint(field, v):
    return _varint(field << 3) + _varint(int(v))


def _f_bytes(field, payload):
    if isinstance(payload, str):
        payload = payload.encode()
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _f_double(field, v):
    return _varint(field << 3 | 1) + struct.pack("<d", float(v))


def _f_float(field, v):
    return _varint(field << 3 | 5) + struct.pack("<f", float(v))


def _event(wall_time, step=None, file_version=None, summary=None):
    out = _f_double(1, wall_time)
    if step is not None:
        out += _f_varint(2, step)
    if file_version is not None:
        out += _f_bytes(3, file_version)
    if summary is not None:
        out += _f_bytes(5, summary)
    return out


def _scalar_summary(tag, value):
    val = _f_bytes(1, tag) + _f_float(2, value)
    return _f_bytes(1, val)


class EventFileWriter:
    """Append scalar events to a `events.out.tfevents.<ts>.<host>` file."""

    _serial = 0

    def __init__(self, log_dir):
        os.makedirs(log_dir, exist_ok=True)
        # pid + per-process serial keep concurrent/back-to-back runs in
        # distinct files (second-granularity timestamps alone collide)
        EventFileWriter._serial += 1
        name = (f"events.out.tfevents.{int(time.time())}"
                f".{os.getpid()}.{EventFileWriter._serial}.paddle_tpu")
        self._f = open(os.path.join(log_dir, name), "ab")
        self._record(_event(time.time(), file_version="brain.Event:2"))

    def _record(self, payload):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag, value, step):
        self._record(_event(time.time(), step=step,
                            summary=_scalar_summary(tag, value)))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()


# ---- reader (validation + offline inspection) ------------------------------

def _read_varint(buf, pos):
    shift = val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def read_scalars(path):
    """Parse an events file; returns [(step, tag, value)]. Every COMPLETE
    record's masked crc32c is verified (mismatch raises); a truncated
    final record — the normal artifact of a killed writer on an
    append-streamed file — is tolerated: the valid prefix is returned,
    matching what TF/VisualDL readers do."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        if pos + 12 > len(data):
            break                      # torn tail: header incomplete
        (ln,) = struct.unpack_from("<Q", data, pos)
        header = data[pos:pos + 8]
        (hcrc,) = struct.unpack_from("<I", data, pos + 8)
        if _masked_crc(header) != hcrc:
            raise ValueError("corrupt length crc")
        if pos + 16 + ln > len(data):
            break                      # torn tail: payload incomplete
        payload = data[pos + 12:pos + 12 + ln]
        (pcrc,) = struct.unpack_from("<I", data, pos + 12 + ln)
        if _masked_crc(payload) != pcrc:
            raise ValueError("corrupt payload crc")
        pos += 16 + ln

        step, summary = 0, None
        p = 0
        while p < len(payload):
            tag_, p = _read_varint(payload, p)
            field, wire = tag_ >> 3, tag_ & 7
            if wire == 1:
                p += 8
                val = None
            elif wire == 5:
                p += 4
                val = None
            elif wire == 0:
                val, p = _read_varint(payload, p)
            else:
                ln2, p = _read_varint(payload, p)
                val = payload[p:p + ln2]
                p += ln2
            if field == 2 and wire == 0:
                step = val
            elif field == 5 and wire == 2:
                summary = val
        if summary is None:
            continue
        sp = 0
        while sp < len(summary):
            tag_, sp = _read_varint(summary, sp)
            if tag_ >> 3 == 1 and tag_ & 7 == 2:
                vlen, sp = _read_varint(summary, sp)
                vbuf = summary[sp:sp + vlen]
                sp += vlen
                vp, tg, sv = 0, None, None
                while vp < len(vbuf):
                    t2, vp = _read_varint(vbuf, vp)
                    f2, w2 = t2 >> 3, t2 & 7
                    if w2 == 2:
                        l2, vp = _read_varint(vbuf, vp)
                        if f2 == 1:
                            tg = vbuf[vp:vp + l2].decode()
                        vp += l2
                    elif w2 == 5:
                        if f2 == 2:
                            (sv,) = struct.unpack_from("<f", vbuf, vp)
                        vp += 4
                    elif w2 == 0:
                        _, vp = _read_varint(vbuf, vp)
                    elif w2 == 1:
                        vp += 8
                if tg is not None and sv is not None:
                    out.append((step, tg, sv))
            else:
                break
    return out
