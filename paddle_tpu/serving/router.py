"""Front-door request router over N named ServingEngine instances.

``Router({"a": eng_a, "b": eng_b}).submit(...)`` fans requests across
engines (per-model, per-mesh — engines may serve different models via the
``models=`` labels) with three placement inputs (docs/SERVING.md):

- **health**: each engine's ``health()`` verdict — a draining or dead
  engine never receives new work; a degraded engine is skipped by
  affinity and only used when every candidate is degraded;
- **deadline/priority**: a request carrying ``deadline_ms`` routes to the
  least-loaded candidate (queue depth + active slots, tie-broken by the
  engine's measured per-step decode time from ``stats()["breakdown"]``)
  instead of its affinity target — the engine most likely to start it
  before the clock runs out;
- **session/prefix affinity**: requests sharing a ``session_id`` (or a
  router-registered prefix, or failing those their first
  ``affinity_tokens`` prompt tokens) hash to the SAME engine, so that
  engine's shared-prefix KV cache and warm slots actually hit.

Failover: an engine whose ``step()`` raises is marked dead; its queued
AND in-flight requests are resubmitted to surviving candidates (greedy
decoding is deterministic, so a re-decoded request finishes with the
exact tokens it would have produced — pinned by the ``router_failover``
chaos scenario). ``drain(name)`` stops new placements on that engine and
re-routes its still-QUEUED requests while in-flight work finishes in
place.

Tracing: the router mints one trace_id per request and opens a ``route``
span; the engine's ``request`` root span joins that trace (``submit(...,
trace_id=, parent_span=)``), so one request's spans thread
router -> engine -> slot. Metrics: ``router_requests_total{engine}``,
``router_failover_total{reason}``, ``router_affinity_total{event}``.
"""
import time
import zlib

import numpy as np

from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from .. import trace as _trace
from ..core.tensor import Tensor
from ..inference.serving import QueueFullError

__all__ = ["Router", "NoLiveEngineError"]


class NoLiveEngineError(RuntimeError):
    """No candidate engine is alive + admitting for the request."""


_ROUTER_REQ = _monitor.counter(
    "router_requests_total",
    "requests placed by the Router, by target engine",
    labelnames=("engine",))
_ROUTER_FAILOVER = _monitor.counter(
    "router_failover_total",
    "requests re-routed off an engine (engine_error = its step() raised "
    "and it was marked dead; drain = still-queued work moved off a "
    "draining engine)",
    labelnames=("reason",))
_ROUTER_AFFINITY = _monitor.counter(
    "router_affinity_total",
    "affinity-hash placements: hit = the key's engine was warm (seen "
    "before / prefix already registered there), miss = first placement "
    "or re-route",
    labelnames=("event",))


class _RouterReq:
    """Router-side record of one accepted request; survives re-routing
    (the engine-side Request is replaced on failover)."""

    __slots__ = ("rid", "ids", "kwargs", "model", "affinity_key",
                 "prefix_id", "engine", "erid", "trace_id", "resubmits",
                 "t0")

    def __init__(self, rid, ids, kwargs, model, affinity_key, prefix_id):
        self.rid = rid
        self.ids = ids
        self.kwargs = kwargs
        self.model = model
        self.affinity_key = affinity_key
        self.prefix_id = prefix_id
        self.engine = None
        self.erid = None
        self.trace_id = None
        self.resubmits = 0
        self.t0 = None   # first router-level submit (deadline anchor)


def _blackbox_router_table(router):
    """Router placement state for a dump bundle: which engines are
    alive/dead, what each one still owes, and what is parked."""
    owed = {}
    for (name, erid), rid in router._by_engine.items():
        owed.setdefault(name, []).append(rid)
    return {"alive": sorted(router._alive),
            "dead": sorted(set(router._engines) - router._alive),
            "outstanding": {n: sorted(rids) for n, rids in owed.items()},
            "parked": [r.rid for r in router._parked],
            "finished": len(router._results)}


class Router:
    def __init__(self, engines, models=None, affinity_tokens=8):
        """engines: ``{name: ServingEngine}`` (order = step order).
        models: optional ``{name: model_label}`` — ``submit(model=...)``
        only considers engines whose label matches (unlabelled engines
        serve any model). affinity_tokens: prompt-prefix length hashed
        for requests with no session_id/prefix."""
        if not engines:
            raise ValueError("Router needs at least one engine")
        self._engines = dict(engines)
        self._models = dict(models or {})
        self._affinity_tokens = int(affinity_tokens)
        self._alive = set(self._engines)
        self._reqs = {}          # rid -> _RouterReq
        self._by_engine = {}     # (engine_name, erid) -> rid
        self._parked = []        # rreqs awaiting capacity (failover hit
                                 # full bounded queues on live survivors)
        self._results = {}       # rid -> finished engine Request
        self._next_rid = 0
        self._prefixes = {}      # router pid -> ids
        self._prefix_sites = {}  # router pid -> {engine_name: engine pid}
        self._next_pid = 0
        self._affinity_seen = {}  # affinity key -> engine_name
        self._m = {"requests": {}, "failover": {}, "affinity_hit": 0,
                   "affinity_miss": 0}
        # one all-dead dump per outage: a front-end retry loop hammering
        # submit() against a dead router must not write a bundle per call
        self._no_live_dumped = False
        # blackbox dump bundles carry the router's placement state next
        # to each engine's own in-flight table (weakly held)
        _blackbox.register_provider("router", self, _blackbox_router_table)

    # -- placement ---------------------------------------------------------
    def _health(self, name):
        return self._engines[name].health()

    def _candidates(self, model):
        out = []
        for name in self._engines:
            if name not in self._alive:
                continue
            if model is not None and name in self._models \
                    and self._models[name] != model:
                continue
            if self._health(name)["state"] == "draining":
                continue
            out.append(name)
        if not out:
            # the all-dead path is the router's terminal wedge: leave a
            # dump bundle behind and name it in the error, so the
            # operator gets stacks + per-engine state, not just a message
            msg = (f"no live admitting engine for model={model!r} "
                   f"(alive: {sorted(self._alive)}, "
                   f"engines: {sorted(self._engines)})")
            if _blackbox.is_enabled() and not self._no_live_dumped:
                self._no_live_dumped = True
                path = _blackbox.dump(
                    "crash", site="router/no_live_engine",
                    extra={"model": repr(model),
                           "alive": sorted(self._alive),
                           "engines": sorted(self._engines)})
                if path:
                    msg += f"; blackbox dump bundle: {path}"
            raise NoLiveEngineError(msg)
        return out

    def _load_score(self, name):
        """Placement load estimate: outstanding work first, the engine's
        measured per-step decode wall time as the tie-break. The two
        components stay separate — multiplying them would make a warmed
        engine (known ms) incomparable with a cold one (no breakdown yet)
        and could route a deadline request INTO the deeper backlog."""
        h = self._health(name)
        load = h["queue_depth"] + h["active_slots"]
        # the engine's raw per-kind [count, wall_ms] accumulator — the
        # source stats()['breakdown'] is built from, without assembling
        # the full snapshot on the routing hot path
        step_ms = self._engines[name]._m["step_ms"]
        ms = 0.0
        for kind in ("decode_greedy", "decode_sample", "speculative"):
            row = step_ms.get(kind)
            if row and row[0]:
                ms = max(ms, row[1] / row[0])
        return (load, ms)

    def _least_loaded(self, candidates):
        return min(candidates, key=lambda n: self._load_score(n))

    def _place(self, model, affinity_key, deadline_ms):
        """Pick the target engine; returns (name, affinity_event)."""
        candidates = self._candidates(model)
        if deadline_ms is not None:
            # deadline-aware: the engine most likely to START the request
            # in time beats cache warmth
            return self._least_loaded(candidates), None
        ranked = sorted(candidates)
        key = (model, affinity_key)
        name = ranked[zlib.crc32(repr(key).encode()) % len(ranked)]
        if self._health(name)["state"] == "degraded":
            healthy = [n for n in candidates
                       if self._health(n)["state"] == "ok"]
            if healthy:   # degraded target only serves as a last resort
                name = self._least_loaded(healthy)
        # hit = the key's traffic actually LANDED here before (the seen
        # table is written by _submit_to on successful placement only —
        # a queue-full divert must not fake warmth on the hash target)
        event = "hit" if self._affinity_seen.get(key) == name else "miss"
        return name, event

    # -- API ---------------------------------------------------------------
    def register_prefix(self, prefix_ids):
        """Register a shared prefix ONCE with the router; returns a router
        prefix id for ``submit(prefix_id=...)``. The prefix's KV is
        materialized LAZILY per engine — affinity hashing sends every
        request sharing it to the same engine, so in steady state exactly
        one engine pays the prefill and every request hits its cache."""
        ids = prefix_ids._data if isinstance(prefix_ids, Tensor) \
            else np.asarray(prefix_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if len(ids) == 0:
            raise ValueError("empty prefix")
        pid = self._next_pid
        self._next_pid += 1
        self._prefixes[pid] = ids
        self._prefix_sites[pid] = {}
        return pid

    def _engine_prefix(self, name, pid):
        sites = self._prefix_sites[pid]
        if name not in sites:
            sites[name] = self._engines[name].register_prefix(
                self._prefixes[pid])
        return sites[name]

    def submit(self, prompt_ids, max_new_tokens=32, model=None,
               session_id=None, prefix_id=None, **kwargs):
        """Place one request; returns the ROUTER request id. ``kwargs``
        pass through to ``ServingEngine.submit`` (temperature, top_k,
        top_p, seed, deadline_ms, priority). ``prefix_id`` is a router id
        from :meth:`register_prefix`; ``session_id`` pins a conversation
        to one engine's warm cache."""
        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if prefix_id is not None and prefix_id not in self._prefixes:
            raise ValueError(f"unknown router prefix_id {prefix_id}")
        if session_id is not None:
            affinity_key = ("session", session_id)
        elif prefix_id is not None:
            affinity_key = ("prefix", prefix_id)
        else:
            affinity_key = ("prompt",
                            tuple(ids[:self._affinity_tokens].tolist()))
        rid = self._next_rid
        self._next_rid += 1
        rreq = _RouterReq(rid, ids, dict(kwargs,
                                         max_new_tokens=max_new_tokens),
                          model, affinity_key, prefix_id)
        rreq.t0 = time.perf_counter()
        # register only AFTER a successful placement: a rejected submit
        # (validation error, every queue full -> QueueFullError) must not
        # leak a phantom record with engine=None
        self._dispatch(rreq, deadline_aware=True)
        self._reqs[rid] = rreq
        return rid

    def _dispatch(self, rreq, deadline_aware=True, exclude=()):
        """(Re)place one router request on an engine; on a full bounded
        queue the remaining candidates are tried by load. Raises
        QueueFullError when every LIVE candidate rejected (transient
        pressure — retryable), NoLiveEngineError when no live admitting
        candidate exists at all."""
        deadline_ms = rreq.kwargs.get("deadline_ms") if deadline_aware \
            else None
        name, event = self._place(rreq.model, rreq.affinity_key,
                                  deadline_ms)
        tried = set(exclude)
        while True:
            if name in tried:
                remaining = [n for n in self._candidates(rreq.model)
                             if n not in tried]
                if not remaining:
                    raise QueueFullError(
                        f"request {rreq.rid}: every live candidate "
                        "engine's bounded queue rejected the submission")
                name, event = self._least_loaded(remaining), None
            try:
                self._submit_to(rreq, name, event)
                return name
            except QueueFullError:
                tried.add(name)

    def _submit_to(self, rreq, name, affinity_event):
        eng = self._engines[name]
        route_sp, tid = None, None
        if _trace.is_enabled():
            tid = rreq.trace_id or _trace.new_trace_id()
            route_sp = _trace.start_span(
                "route", subsystem="router", trace_id=tid, rid=rreq.rid,
                engine=name, resubmits=rreq.resubmits)
        kwargs = dict(rreq.kwargs)
        ids = rreq.ids
        if rreq.prefix_id is not None:
            kwargs["prefix_id"] = self._engine_prefix(name, rreq.prefix_id)
        if kwargs.get("deadline_ms") is not None and rreq.t0 is not None \
                and rreq.resubmits:
            # a re-routed request keeps its ORIGINAL wall-clock budget:
            # hand the engine only what remains (a non-positive remainder
            # still submits with an epsilon budget — the engine expires
            # it through the standard deadline machinery)
            elapsed_ms = (time.perf_counter() - rreq.t0) * 1e3
            kwargs["deadline_ms"] = max(
                1e-3, rreq.kwargs["deadline_ms"] - elapsed_ms)
        try:
            erid = eng.submit(ids, trace_id=tid, parent_span=route_sp,
                              **kwargs)
        except BaseException:
            if route_sp is not None:
                route_sp.end(error=True)
            raise
        if route_sp is not None:
            route_sp.end()
        rreq.engine, rreq.erid, rreq.trace_id = name, erid, tid
        self._by_engine[(name, erid)] = rreq.rid
        if kwargs.get("seed") is None and \
                float(kwargs.get("temperature", 0.0) or 0.0) > 0:
            # pin the engine-resolved seed (defaults to the ENGINE-local
            # rid) so a failover re-decode continues the SAME sampled
            # stream instead of silently switching distributions
            rreq.kwargs["seed"] = eng.get_request(erid).seed
        self._m["requests"][name] = self._m["requests"].get(name, 0) + 1
        _ROUTER_REQ.labels(engine=name).inc()
        if affinity_event is not None:
            self._affinity_seen[(rreq.model, rreq.affinity_key)] = name
            self._m["affinity_%s" % affinity_event] += 1
            _ROUTER_AFFINITY.labels(event=affinity_event).inc()

    def get_request(self, rid):
        """The live engine-side Request for a router id (the CURRENT one
        after any failover), or the finished result."""
        if rid in self._results:
            return self._results[rid]
        rreq = self._reqs.get(rid)
        if rreq is None:
            raise KeyError(f"unknown router request id {rid}")
        return self._engines[rreq.engine].get_request(rreq.erid)

    def cancel(self, rid):
        """Cancel a router request wherever it currently lives — on an
        engine, or parked awaiting failover capacity."""
        if rid in self._results:
            return False
        rreq = self._reqs.get(rid)
        if rreq is None:
            raise KeyError(f"unknown router request id {rid}")
        if rreq in self._parked:
            # parked = waiting for a survivor slot; its last engine-side
            # copy (on the dead/draining engine) supplies the terminal
            # "cancelled" record. Removing it from _parked is the real
            # cancellation — it must never be re-dispatched.
            self._parked.remove(rreq)
            eng = self._engines[rreq.engine]
            try:
                eng.cancel(rreq.erid)
            except Exception:
                pass
            self._results[rid] = eng.get_request(rreq.erid)
            return True
        out = self._engines[rreq.engine].cancel(rreq.erid)
        # the engine's terminal "cancelled" record becomes the result
        self._collect(rreq.engine,
                      self._engines[rreq.engine].get_request(rreq.erid))
        return out

    # -- stepping / failover ----------------------------------------------
    def _collect(self, name, ereq):
        rid = self._by_engine.pop((name, ereq.rid), None)
        if rid is not None:
            self._results[rid] = ereq
        return rid

    def _unfinished_on(self, name):
        return [self._reqs[rid] for (n, erid), rid
                in list(self._by_engine.items()) if n == name]

    def _fail_engine(self, name, exc):
        """Mark an engine dead and re-route EVERYTHING it still owed.
        Greedy requests restart from the prompt on the survivor and
        reproduce their exact tokens (deterministic decode). Survivors
        whose bounded queues are momentarily full are TRANSIENT: those
        requests park and retry at the next step(). With NO surviving
        candidate at all the stranded requests are terminated on the
        dead engine (reason="cancelled", visible to get_request pollers)
        and the NoLiveEngineError still propagates — loud, but
        consistent."""
        self._alive.discard(name)
        eng = self._engines[name]
        stranded = self._unfinished_on(name)
        for idx, rreq in enumerate(stranded):
            del self._by_engine[(name, rreq.erid)]
            # the dead engine's host state is still readable: a request
            # already terminal there (shed/cancelled outside step, before
            # the sweep collected it) must NOT be resurrected on a
            # survivor — its outcome stands
            ereq = eng._finished.get(rreq.erid)
            if ereq is not None:
                self._results[rreq.rid] = ereq
                continue
            _ROUTER_FAILOVER.labels(reason="engine_error").inc()
            self._m["failover"]["engine_error"] = \
                self._m["failover"].get("engine_error", 0) + 1
            rreq.resubmits += 1
            try:
                self._dispatch(rreq, deadline_aware=True, exclude={name})
            except QueueFullError:
                # live survivors exist but are at their bounds right now
                # — transient pressure, not router death: retry at the
                # next step() once their backlogs drain
                self._parked.append(rreq)
            except NoLiveEngineError:
                # nowhere left to go: terminate the stranded requests on
                # the dead engine (reason="cancelled" via its own
                # machinery) so pollers see a terminal state, then let
                # the error propagate
                for rr in stranded[idx:]:
                    self._by_engine.pop((name, rr.erid), None)
                    try:
                        er = eng.get_request(rr.erid)
                        if not er.finished:
                            eng.cancel(rr.erid)
                    except Exception:
                        er = None
                    if er is not None:
                        self._results[rr.rid] = er
                raise

    def drain(self, name):
        """Gracefully drain one engine: it stops receiving placements
        (health -> "draining"), its still-QUEUED requests re-route to
        live candidates, and its in-flight slots finish in place."""
        eng = self._engines[name]
        eng.drain()
        for rreq in self._unfinished_on(name):
            ereq = eng.get_request(rreq.erid)
            if ereq.finished or ereq.admit_time is not None:
                continue   # in-flight (or already done): finish here
            # place on a survivor FIRST, cancel the old copy after — if
            # every candidate rejects (none live, or bounded queues all
            # full) the request stays QUEUED on the draining engine,
            # which still runs queued work to completion
            old_key = (name, rreq.erid)
            del self._by_engine[old_key]
            try:
                rreq.resubmits += 1
                self._dispatch(rreq, deadline_aware=True, exclude={name})
            except (NoLiveEngineError, QueueFullError):
                rreq.resubmits -= 1
                rreq.engine, rreq.erid = old_key
                self._by_engine[old_key] = rreq.rid
                continue
            eng.cancel(old_key[1])
            _ROUTER_FAILOVER.labels(reason="drain").inc()
            self._m["failover"]["drain"] = \
                self._m["failover"].get("drain", 0) + 1

    def step(self):
        """One step across every live engine; an engine that raises is
        failed over. Returns the router requests finished this step as
        {rid: Request}."""
        with _blackbox.progress("router/step"):
            return self._step_inner()

    def _step_inner(self):
        done = {}
        if self._parked:
            # capacity may have freed since the failover that parked
            # these; still-full queues keep them parked (no metric
            # re-count — their failover was recorded once). Bookkeeping
            # is exception-safe: a request leaves _parked ONLY once
            # placed, so a NoLiveEngineError mid-loop cannot leave an
            # already-placed request parked for a duplicate dispatch.
            retry, self._parked = self._parked, []
            for i, rreq in enumerate(retry):
                try:
                    self._dispatch(rreq, deadline_aware=True)
                except QueueFullError:
                    self._parked.append(rreq)
                except NoLiveEngineError:
                    self._parked.extend(retry[i:])
                    raise
        for name in list(self._engines):
            if name not in self._alive:
                continue
            eng = self._engines[name]
            if not eng.has_work():
                continue
            try:
                finished = eng.step()
            except Exception as exc:
                self._fail_engine(name, exc)
                continue
            for ereq in finished:
                rid = self._collect(name, ereq)
                if rid is not None:
                    done[rid] = ereq
        # requests can also finish OUTSIDE an engine's step() — shed by a
        # bounded queue at submit time, or cancelled directly on the
        # engine — sweep outstanding mappings so no terminal request is
        # ever stranded un-collected. O(1) per mapping: finished requests
        # always land in the engine's _finished table
        for (name, erid), rid in list(self._by_engine.items()):
            if name not in self._alive:
                continue
            ereq = self._engines[name]._finished.get(erid)
            if ereq is not None:
                self._collect(name, ereq)
                done[rid] = ereq
        return done

    def has_work(self):
        return bool(self._parked) \
            or any(self._engines[n].has_work() for n in self._alive)

    def run_until_complete(self, max_steps=100_000):
        """Drain every engine; returns {router rid: finished Request}."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                msg = (f"router did not converge within {max_steps} "
                       "steps; outstanding: "
                       f"{sorted(self._by_engine.values())}")
                if _blackbox.is_enabled():
                    path = _blackbox.dump(
                        "stall", site="router/step",
                        extra={"trigger": "run_until_complete",
                               "max_steps": max_steps})
                    if path:
                        msg += f"; blackbox dump bundle: {path}"
                raise RuntimeError(msg)
        return dict(self._results)

    # -- observability -----------------------------------------------------
    def health(self):
        """Per-engine health verdicts; a dead engine reports
        {"state": "dead"}."""
        out = {}
        for name, eng in self._engines.items():
            out[name] = eng.health() if name in self._alive \
                else {"state": "dead"}
        return out

    def stats(self):
        """Router placement/failover/affinity accounting plus each
        engine's own stats() snapshot."""
        aff = self._m["affinity_hit"] + self._m["affinity_miss"]
        return {
            "engines": {n: self._engines[n].stats() for n in self._engines
                        if n in self._alive},
            "router": {
                "requests": dict(self._m["requests"]),
                "failover": dict(self._m["failover"]),
                "affinity": {
                    "hit": self._m["affinity_hit"],
                    "miss": self._m["affinity_miss"],
                    "hit_rate": (self._m["affinity_hit"] / aff
                                 if aff else None)},
                "alive": sorted(self._alive),
                "dead": sorted(set(self._engines) - self._alive),
                "outstanding": len(self._by_engine),
                "parked": len(self._parked),
            },
            "health": self.health(),
        }
