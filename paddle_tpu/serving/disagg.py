"""Prefill/decode disaggregation: dedicated prefill workers feed decode
engines through the DecodeModel cache-pytree handoff.

The monolithic ``ServingEngine`` runs admission prefill and the decode
loop on the same program family; at scale the two want DIFFERENT
placement — prefill is compute-bound and bursty, decode is HBM-bound and
steady (the per-stage multi-program split MPMD pipeline parallelism
argues for, PAPERS.md arXiv:2412.14374). ``DisaggregatedPool`` is that
split in-process:

- ``PrefillWorker`` builds ONLY the bucketed whole-prompt prefill program
  from a model's :class:`~paddle_tpu.serving.decode_model.DecodeModel`
  adapter and turns a prompt into ``((kc1, vc1), last_logits)`` — one
  single-row KV cache in the adapter's documented cache-pytree layout;
- the pool hands that row to the least-loaded decode engine via
  ``ServingEngine.admit_prefilled`` — a ``kv_handoff`` span and the
  ``kv_handoff_bytes_total`` metric meter every transfer;
- the decode engine picks the first token through the SAME pick program
  monolithic admission uses, so pool completions are **bit-identical** to
  a single engine serving the same prompts (tests/test_serving_disagg.py).

Workers and engines must share adapter/config/dtype/cache_dtype — the
pool constructor builds both sides from one model so the contract holds
by construction.
"""
import time

import numpy as np

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from .. import trace as _trace
from ..core.tensor import Tensor
from ..framework import aot as _aot
from . import decode_model as _dm_registry

__all__ = ["PrefillWorker", "DisaggregatedPool", "HANDOFF_SCHEMA"]

#: The prefill->decode KV transfer edge, declared (ISSUE 13; docs/
#: ANALYSIS.md "Declaring a transfer edge"). This literal is the ONE
#: source of truth for the handoff payload: the static auditor
#: (analysis/handoff_schema.py) AST-extracts it and pins its fingerprint
#: in tests/handoff_baseline.json, and ``ServingEngine.admit_prefilled``
#: validates every incoming row against it at runtime — a silent
#: KV-layout drift fails lint AND raises at the door, never corrupts a
#: decode. Symbolic dims bind to the consuming engine's config (L =
#: num_layers, KVh = compact kv heads, T = max_seq_len, hd = head_dim,
#: V = vocab); ``$cache`` binds to the engine's cache dtype;
#: ``quantizable`` sides accept the int8/fp8 (values, scales) pair.
HANDOFF_SCHEMA = {
    "edge": "disagg_kv",
    "producer": "paddle_tpu/serving/disagg.py::PrefillWorker.prefill",
    "consumer": ("paddle_tpu/inference/serving.py::"
                 "ServingEngine.admit_prefilled"),
    "runtime_checked": True,
    "doc": "one prefilled single-row KV cache pair + the prompt's "
           "last-position vocab logits, in the DecodeModel adapter's "
           "documented cache-pytree layout",
    "payload": {
        "kc": {"shape": ("L", 1, "KVh", "T", "hd"), "dtype": "$cache",
               "layout": "[L, B, KVh, T, hd]", "quantizable": True},
        "vc": {"shape": ("L", 1, "KVh", "T", "hd"), "dtype": "$cache",
               "layout": "[L, B, KVh, T, hd]", "quantizable": True},
        "logits": {"shape": ("V",), "dtype": "float32"},
    },
}

_KV_BYTES = _monitor.counter(
    "kv_handoff_bytes_total",
    "bytes of prefilled KV rows handed from prefill workers to decode "
    "engines (disaggregated serving)")
_KV_HANDOFFS = _monitor.counter(
    "kv_handoff_total",
    "prefill->decode handoffs, by outcome",
    labelnames=("event",))


class PrefillWorker:
    """The prefill half of a disaggregated pair: owns the model params
    and ONE program — bucketed whole-prompt prefill — built through the
    DecodeModel adapter exactly like ``ServingEngine``'s, so the row it
    produces is the row the engine would have produced itself."""

    def __init__(self, model, dtype=None, cache_dtype=None,
                 prompt_buckets=(32, 64, 128, 256, 512, 1024),
                 decode_model=None):
        import jax
        import jax.numpy as jnp

        dm = _dm_registry.resolve(model, decode_model)
        self._dm = dm
        cfg = model.cfg
        dm.check_config(cfg)
        self.cfg = cfg
        self.T = cfg.max_seq_len
        self._buckets = tuple(sorted(b for b in prompt_buckets
                                     if b <= self.T))
        if not self._buckets:
            raise ValueError("no prompt bucket fits max_seq_len")
        params, aux = dm.extract_params(model, "the model")
        self._compute_dtype = dm.compute_dtype(dtype)
        if self._compute_dtype is not None:
            params = {k: (v.astype(self._compute_dtype)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}
        self._params = params
        fwd, logits_of, cache_init = dm.decode_fns(cfg, aux,
                                                   cache_dtype=cache_dtype)
        cache_dt = self._compute_dtype or jnp.float32

        def prefill(p, ids_padded, true_len):
            kc1, vc1 = cache_init(1, self.T, cache_dt)
            x, kc1, vc1 = fwd(p, ids_padded, 0, kc1, vc1)
            x_last = jax.lax.dynamic_slice_in_dim(
                x, true_len - 1, 1, axis=1)[:, 0]
            return kc1, vc1, logits_of(p, x_last).astype(jnp.float32)[0]

        # the same AOT-cache site/label family as the engine's prefill, so
        # a warmed disk cache serves both sides of the split
        self._prefill = _aot.cached_jit(
            prefill, site="serving", label="prefill",
            record_event="serving/compile",
            extra_key=(_aot.mesh_fingerprint(None),))
        self._m = {"prefills": 0, "prefill_ms": 0.0}

    def _bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return self.T

    def prefill(self, prompt_ids):
        """Prefill one prompt; returns ``((kc1, vc1), logits)`` — the
        handoff unit ``ServingEngine.admit_prefilled`` consumes."""
        import jax.numpy as jnp

        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) + 1 > self.T:
            raise ValueError(
                f"prompt ({len(ids)}) too long for max_seq_len {self.T}")
        n = len(ids)
        pb = self._bucket(n)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :n] = ids
        t0 = time.perf_counter()
        with _blackbox.progress("disagg/prefill"):
            kc1, vc1, logits = self._prefill(self._params,
                                             jnp.asarray(padded),
                                             np.int32(n))
        self._m["prefills"] += 1
        self._m["prefill_ms"] += (time.perf_counter() - t0) * 1e3
        return (kc1, vc1), logits

    def stats(self):
        return dict(self._m)


class DisaggregatedPool:
    """N prefill workers + M decode engines behind one submit()/step()
    surface with the monolithic engine's semantics (and bit-identical
    outputs on the same prompts)."""

    def __init__(self, model, prefill_workers=1, decode_engines=2,
                 max_batch=4, dtype=None, cache_dtype=None,
                 eos_token_id=None,
                 prompt_buckets=(32, 64, 128, 256, 512, 1024),
                 max_queue=None, decode_model=None, compress=None):
        from ..inference.serving import ServingEngine

        if int(prefill_workers) < 1 or int(decode_engines) < 1:
            raise ValueError("the pool needs >= 1 prefill worker and "
                             ">= 1 decode engine")
        # MPMD stage edge (distributed/stage.py): FLAGS_mpmd is consumed
        # HERE — armed, the prefill->decode hand-off travels a typed
        # StageEdge validating this module's HANDOFF_SCHEMA (compress=8
        # rides the int8 row codec); a post-construction toggle raises
        # (_mpmd_active). Unset, the module is never imported and the
        # hand-off below is byte-identical to the pre-PR pool.
        self._mpmd = bool(_flags.get_flag("mpmd", False))
        self._edge = None
        self._backpressure_excs = ()
        if compress is not None and not self._mpmd:
            raise ValueError(
                "compress quantizes the prefill->decode stage edge "
                "(distributed/stage.py) — set FLAGS_mpmd before "
                "constructing the pool")
        if self._mpmd:
            from ..distributed import stage as _stage_mod

            self._edge = _stage_mod.StageEdge(
                "disagg_kv", HANDOFF_SCHEMA,
                capacity=int(decode_engines) * int(max_batch),
                compress=compress)
            self._backpressure_excs = (_stage_mod.EdgeFullError,)
        shared = dict(dtype=dtype, cache_dtype=cache_dtype,
                      prompt_buckets=prompt_buckets,
                      decode_model=decode_model)
        self.workers = [PrefillWorker(model, **shared)
                        for _ in range(int(prefill_workers))]
        self.engines = {
            f"decode{i}": ServingEngine(model, max_batch=max_batch,
                                        eos_token_id=eos_token_id,
                                        max_queue=max_queue, **shared)
            for i in range(int(decode_engines))}
        self.T = model.cfg.max_seq_len
        self._pending = []   # (rid, ids, kwargs, t0) awaiting prefill
        self._placed = {}        # rid -> (engine_name, erid)
        self._by_erid = {}       # (engine_name, erid) -> rid, LIVE only
        self._results = {}       # rid -> finished Request
        self._next_rid = 0
        self._next_worker = 0
        self._m = {"submitted": 0, "handoffs": 0, "handoff_bytes": 0,
                   "per_engine": {}}

    def _mpmd_active(self):
        """FLAGS_mpmd was consumed at construction (the stage edge is
        built then); a post-construction toggle is loud instead of
        silently re-routing the hand-off. One get_flag + compare when
        disarmed."""
        m = bool(_flags.get_flag("mpmd", False))
        if m != self._mpmd:
            raise RuntimeError(
                "FLAGS_mpmd changed after this DisaggregatedPool was "
                "constructed; the prefill->decode stage edge is built at "
                "__init__ — build a new pool under the new flag value")
        return self._mpmd

    def submit(self, prompt_ids, max_new_tokens=32, **kwargs):
        """Queue one prompt; returns the pool request id. kwargs pass
        through to ``ServingEngine.admit_prefilled`` (temperature, top_k,
        top_p, seed, deadline_ms, priority)."""
        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) + 1 > self.T:
            raise ValueError(
                f"prompt ({len(ids)}) too long for max_seq_len {self.T}")
        # fail-fast with the ENGINE's own validation: a bad argument that
        # only surfaced at handoff time would re-raise from every step()
        # and head-of-line block the prefill queue forever
        next(iter(self.engines.values()))._validate_decode_args(
            ids, max_new_tokens, kwargs.get("temperature", 0.0),
            kwargs.get("deadline_ms"), kwargs.get("top_k"),
            kwargs.get("top_p"), kwargs.get("seed"))
        rid = self._next_rid
        self._next_rid += 1
        # t0 anchors deadline_ms at POOL submit: time spent waiting in
        # the prefill backlog counts against the budget, matching the
        # monolithic engine's submit-to-finish deadline semantics
        self._pending.append((rid, ids,
                              dict(kwargs, max_new_tokens=max_new_tokens),
                              time.perf_counter()))
        self._m["submitted"] += 1
        return rid

    def _free_slots(self, name):
        """Admission room on a decode engine: free decode slots minus the
        handoff backlog, capped by the engine's bounded-queue headroom —
        prefilling a prompt the engine would reject (QueueFullError)
        wastes the whole forward."""
        eng = self.engines[name]
        h = eng.health()
        free = eng.B - h["active_slots"] - len(eng._handoff)
        if eng._max_queue is not None:
            free = min(free, eng._max_queue - len(eng._queue)
                       - len(eng._handoff))
        return free

    def _target_engine(self):
        """Least-loaded decode engine by free slot count (ties broken by
        name order — deterministic placement)."""
        return max(sorted(self.engines),
                   key=lambda n: (self._free_slots(n),))

    def _advance_prefill(self):
        """Prefill pending prompts (round-robin over workers) while any
        decode engine has room, handing each finished row off."""
        if not self._pending:
            return
        # window beacon: the site is watched only while handoffs are in
        # flight (per-iteration beats inside keep the counter advancing)
        with _blackbox.progress("disagg/handoff"):
            self._advance_prefill_inner()

    def _advance_prefill_inner(self):
        while self._pending:
            _blackbox.beacon("disagg/handoff")
            name = self._target_engine()
            if self._free_slots(name) <= 0:
                return   # decode tier full: natural backpressure
            rid, ids, kwargs, t0 = self._pending.pop(0)
            eng_kwargs = kwargs
            if kwargs.get("deadline_ms") is not None:
                # hand the engine the REMAINING budget: prefill-backlog
                # wait already spent it (an exhausted budget still
                # submits with an epsilon — the engine's own deadline
                # machinery expires it with reason="deadline"). The
                # UN-adjusted kwargs go back on the queue if the handoff
                # fails, so a retry re-derives from the original budget.
                elapsed_ms = (time.perf_counter() - t0) * 1e3
                eng_kwargs = dict(kwargs, deadline_ms=max(
                    1e-3, kwargs["deadline_ms"] - elapsed_ms))
            worker = self.workers[self._next_worker % len(self.workers)]
            self._next_worker += 1
            eng = self.engines[name]
            tid = _trace.new_trace_id() if _trace.is_enabled() else None
            sp = None if tid is None else _trace.start_span(
                "kv_handoff", subsystem="serving", trace_id=tid,
                rid=rid, engine=name, prompt_tokens=int(len(ids)))
            try:
                kv_row, logits = worker.prefill(ids)
                if self._edge is not None:
                    # MPMD routing: the row crosses a typed StageEdge —
                    # validated against HANDOFF_SCHEMA, quantized when
                    # the edge compresses, metered (wire bytes) at the
                    # edge's own kv_handoff_bytes_total chokepoint
                    kc1, vc1 = kv_row
                    nbytes = self._edge.put(
                        {"kc": kc1, "vc": vc1, "logits": logits},
                        dtypes={"cache": str(kc1.dtype)})
                    payload = self._edge.get()
                    kv_row = (payload["kc"], payload["vc"])
                    logits = payload["logits"]
                else:
                    nbytes = _dm_registry.cache_row_bytes(kv_row)
                erid = eng.admit_prefilled(ids, kv_row, logits,
                                           trace_id=tid, parent_span=sp,
                                           **eng_kwargs)
            except BaseException as exc:
                # the popped request must not vanish with the failed
                # handoff: put it back at the head
                self._pending.insert(0, (rid, ids, kwargs, t0))
                if sp is not None:
                    sp.end(error=True)
                from ..inference.serving import QueueFullError

                if isinstance(exc,
                              (QueueFullError,) + self._backpressure_excs):
                    # a bounded decode engine (or a full stage edge) at
                    # capacity is BACKPRESSURE (same as no free slots),
                    # not a pool failure — retry the handoff later
                    return
                _KV_HANDOFFS.labels(event="error").inc()
                raise
            if sp is not None:
                sp.end(bytes=nbytes)
            if self._edge is None:
                _KV_BYTES.inc(nbytes)   # armed: the edge already metered
            _KV_HANDOFFS.labels(event="ok").inc()
            self._m["handoffs"] += 1
            self._m["handoff_bytes"] += nbytes
            self._m["per_engine"][name] = \
                self._m["per_engine"].get(name, 0) + 1
            self._placed[rid] = (name, erid)
            self._by_erid[(name, erid)] = rid

    def step(self):
        """Advance prefill handoffs, then one decode step per engine.
        Returns the pool requests finished this step as {rid: Request}."""
        self._mpmd_active()
        self._advance_prefill()
        done = {}
        for name, eng in self.engines.items():
            if not eng.has_work():
                continue
            for ereq in eng.step():
                # pop: _by_erid holds LIVE placements only, so per-step
                # cost tracks in-flight work, not pool lifetime
                rid = self._by_erid.pop((name, ereq.rid), None)
                if rid is not None:
                    self._results[rid] = ereq
                    done[rid] = ereq
        return done

    def get_request(self, rid):
        if rid in self._results:
            return self._results[rid]
        if rid in self._placed:
            name, erid = self._placed[rid]
            return self.engines[name].get_request(erid)
        for p_rid, ids, kwargs, t0 in self._pending:
            if p_rid == rid:
                raise KeyError(
                    f"request {rid} is still awaiting prefill (no Request "
                    "object exists until handoff)")
        raise KeyError(f"unknown pool request id {rid}")

    def has_work(self):
        return bool(self._pending) or any(e.has_work()
                                          for e in self.engines.values())

    def run_until_complete(self, max_steps=100_000):
        """Drain the pool; returns {rid: finished Request}."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                msg = (f"disaggregated pool did not converge within "
                       f"{max_steps} steps")
                if _blackbox.is_enabled():
                    path = _blackbox.dump(
                        "stall", site="disagg/handoff",
                        extra={"trigger": "run_until_complete",
                               "max_steps": max_steps,
                               "pending": len(self._pending)})
                    if path:
                        msg += f"; blackbox dump bundle: {path}"
                raise RuntimeError(msg)
        return dict(self._results)

    def stats(self):
        """Pool-level handoff accounting + each side's own stats."""
        out = {
            "pool": dict(self._m, pending=len(self._pending)),
            "workers": [w.stats() for w in self.workers],
            "engines": {n: e.stats() for n, e in self.engines.items()},
        }
        if self._edge is not None:
            out["edge"] = dict(self._edge.stats)
        return out
