"""The decode-model interface: what a model must provide to be SERVED.

The continuous-batching ``ServingEngine`` (inference/serving.py), the
front-door ``Router`` (serving/router.py), and the prefill/decode
``DisaggregatedPool`` (serving/disagg.py) are model-agnostic: they drive
any model through the :class:`DecodeModel` adapter protocol below instead
of importing a model module's privates. A model family registers ONE
adapter (``register_decode_model``); the serving tier resolves it by name
or by inspecting the model instance (``resolve``).

The protocol (docs/SERVING.md for the full contract):

``check_config(cfg)``
    Reject configs the decode programs cannot serve (MoE, megatron-
    training layouts, ...). Raises ``ValueError``.
``compute_dtype(dtype)``
    Map a user dtype string to the decode compute dtype (``None`` = f32).
``extract_params(model, who)``
    Name-addressed param snapshot -> ``(params, aux)``. ``params`` is a
    flat ``{name: jax array}`` dict; ``aux`` is adapter-opaque state
    threaded back into :meth:`decode_fns` (e.g. untied-head flags).
``decode_fns(cfg, aux, cache_dtype=None, tp_axis=None, tp_size=1)``
    The pure-jnp decode math: ``(fwd, logits_of, cache_init)`` with

    - ``cache_init(b, T, dt) -> (kc, vc)`` — the KV-cache pytree pair.
      Each of kc/vc is one "cache side": a plain array (leading axes
      ``[L, b, KVh, T, ...]``) or a (values, scales) tuple for quantized
      caches. Row 0..b-1 is one slot; the pair for ``b=1`` is the unit of
      PREFILL->DECODE HANDOFF (``ServingEngine.admit_prefilled``) — any
      engine built from the same adapter+config accepts another's rows.
    - ``fwd(params, tok_ids [B, t], pos, kc, vc) -> (x, kc, vc)`` — run
      the stack writing K/V at column(s) ``pos`` (scalar or per-row [B]).
    - ``logits_of(params, x_last) -> logits`` — project hidden states to
      vocab logits.
``tp_setup(tp_mesh, cfg, params)``
    Tensor-parallel serving setup -> ``(tp_axis, tp_size, params,
    param_specs)``; raise if the config cannot shard.
``tp_wrap(run, tp_mesh, tp_specs, n_extra_in, out_specs, in_specs=None,
  donate=())``
    jit(shard_map(run)) for the tp programs.
``cache_spec(cfg)``
    Machine-readable description of the cache pytree (layout string,
    axis names, quantized or not) — the handoff contract in data form.
``lora_init(cfg, n_slots, rank, dtype=None)`` / ``lora_pack(cfg,
  exported, rank)``
    Optional multi-LoRA batched decode (FLAGS_paged_kv engines): the
    stacked adapter pytree (slot 0 all-zero = base) and the packing of
    one exported adapter into a slot. ``fwd`` grows ``lora=`` /
    ``adapter_ids=`` kwargs applying the per-row low-rank delta.
``matches(model)``
    True when this adapter serves ``model`` (used by :func:`resolve`).

Exact-parity bar: an engine serving a model THROUGH its adapter must be
byte-identical to one calling the model's decode helpers directly — the
adapter delegates, it never re-implements math.
"""
import importlib

__all__ = ["DecodeModel", "register_decode_model", "get_decode_model",
           "registered_decode_models", "resolve", "cache_row_bytes"]


class DecodeModel:
    """Base adapter; subclasses implement the protocol documented in the
    module docstring. ``name`` is the registry key."""

    name = None

    # -- required ----------------------------------------------------------
    def check_config(self, cfg):
        raise NotImplementedError

    def compute_dtype(self, dtype):
        raise NotImplementedError

    def extract_params(self, model, who):
        raise NotImplementedError

    def decode_fns(self, cfg, aux, cache_dtype=None, tp_axis=None,
                   tp_size=1):
        raise NotImplementedError

    def matches(self, model):
        raise NotImplementedError

    # -- optional (dense-only adapters may leave these) --------------------
    def tp_setup(self, tp_mesh, cfg, params):
        raise NotImplementedError(
            f"decode model {self.name!r} does not support tensor-parallel "
            "serving")

    def tp_wrap(self, run, tp_mesh, tp_specs, n_extra_in, out_specs,
                in_specs=None, donate=()):
        raise NotImplementedError(
            f"decode model {self.name!r} does not support tensor-parallel "
            "serving")

    def cache_spec(self, cfg):
        """Default spec: opaque pytree pair, described minimally."""
        return {"kind": "kv_pair", "layout": "adapter-defined",
                "quantized": None}

    # -- optional (multi-LoRA batched decode, FLAGS_paged_kv engines) ------
    def lora_init(self, cfg, n_slots, rank, dtype=None):
        """Zero-filled stacked adapter pytree for ``n_slots`` adapter
        slots at ``rank`` (slot 0 is reserved all-zero = base requests);
        the pytree feeds ``fwd(..., lora=, adapter_ids=)``."""
        raise NotImplementedError(
            f"decode model {self.name!r} does not support multi-LoRA "
            "serving")

    def lora_pack(self, cfg, exported, rank):
        """One exported adapter (``incubate.lora.export_lora`` form) ->
        the per-slot update written into the stacked pytree: same tree
        shape as one ``lora_init`` slot, factors zero-padded to ``rank``
        (an exact-zero pad — padded lanes contribute nothing)."""
        raise NotImplementedError(
            f"decode model {self.name!r} does not support multi-LoRA "
            "serving")


# name -> DecodeModel instance. Model modules register themselves at
# import; the _LAZY table lets the serving tier resolve a bundled family
# without the caller having imported its module first.
_REGISTRY = {}
_LAZY = {"gpt": "paddle_tpu.models.gpt"}


def register_decode_model(adapter, clobber=False):
    """Register a :class:`DecodeModel` instance under ``adapter.name``.
    Re-registering an existing name raises unless ``clobber=True`` (a
    silent overwrite could swap the serving math under a live engine)."""
    name = getattr(adapter, "name", None)
    if not name:
        raise ValueError("decode-model adapter needs a non-empty .name")
    if name in _REGISTRY and not clobber:
        raise ValueError(
            f"decode model {name!r} is already registered "
            f"({type(_REGISTRY[name]).__name__}); pass clobber=True to "
            "replace it")
    _REGISTRY[name] = adapter
    return adapter


def _materialize(name):
    if name not in _REGISTRY and name in _LAZY:
        importlib.import_module(_LAZY[name])   # module registers itself
    return _REGISTRY.get(name)


def get_decode_model(name):
    """The registered adapter for ``name``; imports a bundled family's
    module lazily. Raises ``KeyError`` with the known names."""
    adapter = _materialize(name)
    if adapter is None:
        known = sorted(set(_REGISTRY) | set(_LAZY))
        raise KeyError(
            f"no decode model registered under {name!r}; known: {known}")
    return adapter


def registered_decode_models():
    """Tuple of registered names (lazy bundled families included)."""
    return tuple(sorted(set(_REGISTRY) | set(_LAZY)))


def resolve(model, spec=None):
    """The adapter serving ``model``: ``spec`` may be a registry name, a
    DecodeModel instance, or None (probe every adapter's ``matches``)."""
    if isinstance(spec, DecodeModel):
        return spec
    if spec is not None:
        return get_decode_model(spec)
    for name in registered_decode_models():
        adapter = _materialize(name)
        if adapter is not None and adapter.matches(model):
            return adapter
    raise TypeError(
        f"no registered decode model serves {type(model).__name__}; "
        f"known: {sorted(registered_decode_models())} — register a "
        "DecodeModel adapter (see paddle_tpu/serving/decode_model.py) or "
        "pass decode_model= explicitly")


def cache_row_bytes(row):
    """Total device bytes of one handoff unit (any cache pytree: a
    (kc, vc) pair, one side, or a quantized (values, scales) tuple) —
    the payload accounting behind ``kv_handoff_bytes_total``."""
    import jax

    return int(sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(row)))
