"""Paged KV-cache block pool + multi-LoRA adapter registry (FLAGS_paged_kv).

The dense ``ServingEngine`` allocates one FIXED ``[max_batch, max_seq]``
KV cache, so every session pays worst-case KV bytes and prefix-shared
sessions duplicate physical KV. This module is the vLLM-style fix,
TPU-shaped: physical KV lives in a pool of fixed-size blocks
(``[n_blocks, L, KVh, block_size, hd]`` per side, frame 0 a permanent
all-zero NULL frame), each slot holds a BLOCK TABLE of frame indices, and
the decode step gathers the pool through the tables into the exact dense
``[L, B, KVh, T, hd]`` layout the unchanged decode math consumes — so the
paged engine is bit-identical to the dense engine by construction (the
gathered cache differs only in causally-masked junk columns).

Sharing model:

- **Reservation up front**: a session's whole block budget
  (``ceil(min(T, prompt + max_new) / block_size)`` blocks) is reserved at
  admission, BEFORE any prefill compute — a full pool raises
  :class:`PagePoolFullError` with no work done (the ``EdgeFullError``
  backpressure discipline), and decode never allocates.
- **Prefix sharing + COW**: ``register_prefix`` writes the prefix's FULL
  blocks into the pool once; every session admitting with that prefix
  maps its leading table entries to the SAME frames (refcounted). The
  partial boundary block (``prefix_len % block_size != 0``) is where the
  session's own tokens land next to prefix content, so it is COPIED to a
  private frame at admission — copy-on-write at first divergence, counted
  on ``kv_page_cow_total``. Shared frames are read-only by layout: the
  decode frontier column always lives in a private frame.
- **Cold pages**: a prefix frame no live session references, untouched
  for ``cold_after`` sweeps, is compressed to int8 via the
  ``distributed/compress.py`` row codec (deterministic nearest rounding)
  and its frame FREED; the next admission touch decompresses it into a
  fresh frame. Dense parity is exact with cold compression off; int8
  cold pages carry the codec's declared band (per row of ``hd``:
  ``|err| <= absmax / 254``). Metered on
  ``kv_page_blocks_total{state=hot|cold}``.

Multi-LoRA tenancy rides the same pool: :class:`AdapterRegistry` manages
named adapter slots (slot 0 is reserved all-zero = base requests) with
LRU eviction and pinning, metered on
``serving_adapter_total{event=load|evict|hit}``. The engine keeps the
stacked factors device-resident and applies each row's adapter delta via
one gathered batched einsum inside the SAME jitted step (models/gpt.py
``_decode_fns`` ``lora=`` path) — no per-adapter recompiles.

Import discipline: a plain (disarmed) ``ServingEngine`` never imports
this module (pinned by tests/test_paging_gate.py; ``import_graph``
LAZY_MODULES). docs/SERVING.md "Paged KV & multi-LoRA" for block math.
"""
import numpy as np

from .. import monitor as _monitor
from ..analysis import handoff_schema as _hs

__all__ = ["PagePool", "PagePoolFullError", "AdapterRegistry",
           "gather_dense", "scatter_cols", "HANDOFF_SCHEMA"]

# pool metrics in the default registry (process-wide, like the serving
# counters; per-pool gauges live on PagePool.stats())
_BLOCKS = _monitor.counter(
    "kv_page_blocks_total",
    "KV pool block transitions: hot = a frame allocated (admission, "
    "prefix registration, cold-page decompression), cold = a frame "
    "compressed to an int8 host page and freed",
    labelnames=("state",))
_COW = _monitor.counter(
    "kv_page_cow_total",
    "copy-on-write boundary blocks: a session admitted on a shared "
    "prefix whose length is not block-aligned copies the partial block "
    "to a private frame before writing its own tokens")
_ADAPTER = _monitor.counter(
    "serving_adapter_total",
    "multi-LoRA adapter registry events (load = factors written into a "
    "device slot, evict = LRU or explicit eviction freed a slot, hit = "
    "a submitted request resolved an already-loaded adapter)",
    labelnames=("event",))


#: The per-session admission payload the pool consumes: the prefilled KV
#: row pair (the SAME handoff unit the dense engine's ``_admit`` copies
#: into its big cache, one slot row) plus the slot's block table. The
#: pool re-blocks the row into its reserved private frames; a layout
#: drift here would corrupt every block-table gather that follows.
HANDOFF_SCHEMA = {
    "edge": "kv_page_admit",
    "payload": {
        "kc": {"shape": ("L", "KVh", "T", "hd"), "dtype": "$cache",
               "layout": "[L, KVh, T, hd] (one prefilled slot row; "
                         "T = max_blocks * block_size)",
               "quantizable": False},
        "vc": {"shape": ("L", "KVh", "T", "hd"), "dtype": "$cache",
               "layout": "[L, KVh, T, hd]", "quantizable": False},
        "table": {"shape": ("maxb",), "dtype": "int32",
                  "layout": "[max_blocks] frame indices (0 = null frame)"},
    },
    "producer": "paddle_tpu/inference/serving.py::ServingEngine._activate",
    "consumer": "paddle_tpu/serving/paging.py::PagePool.admit_row",
    "runtime_checked": True,
    "doc": "paged-KV admission: prefilled dense row -> pool blocks",
}


class PagePoolFullError(RuntimeError):
    """Block reservation rejected: the pool has fewer free frames than
    the session's whole budget. Raised BEFORE any prefill compute or
    table mutation — admission backpressure, not a mid-decode fault."""


def gather_dense(kp, vp, tables):
    """Gather the pool through per-slot block tables into the dense
    cache layout the decode math consumes.

    ``kp``/``vp``: ``[NB, L, KVh, bs, hd]``; ``tables``: int ``[B, maxb]``.
    Returns ``(kc, vc)`` shaped ``[L, B, KVh, maxb*bs, hd]`` — the exact
    dense-engine layout, so the unchanged ``fwd`` runs on it. Table
    entries of 0 read the null frame; those columns are only ever
    causally masked (a session's reserved frames cover every column its
    queries can see)."""
    import jax.numpy as jnp

    def one(pool):
        g = pool[tables]                       # [B, maxb, L, KVh, bs, hd]
        g = jnp.transpose(g, (2, 0, 3, 1, 4, 5))
        L, B, KVh, maxb, bs, hd = g.shape
        return g.reshape(L, B, KVh, maxb * bs, hd)

    return one(kp), one(vp)


def scatter_cols(kp, vp, kc, vc, tables, pos):
    """Write each row's frontier column ``pos[b]`` of the post-step dense
    cache back into its pool frame (the inverse of one column of
    :func:`gather_dense`).

    A slot with no active session maps to the null frame; its junk write
    lands there and is never read meaningfully (null-frame columns are
    causally masked for every live query)."""
    import jax.numpy as jnp

    bs = kp.shape[3]
    B = tables.shape[0]
    blk = pos // bs
    off = pos % bs
    frames = jnp.take_along_axis(tables, blk[:, None], axis=1)[:, 0]
    rows = jnp.arange(B)
    colk = kc[:, rows, :, pos, :]              # [B, L, KVh, hd]
    colv = vc[:, rows, :, pos, :]
    kp = kp.at[frames, :, :, off, :].set(colk)
    vp = vp.at[frames, :, :, off, :].set(colv)
    return kp, vp


class _PrefixEntry:
    __slots__ = ("frames", "cold", "last_use", "n_blocks")

    def __init__(self, frames):
        self.frames = list(frames)   # hot frame id, or None while cold
        self.cold = {}               # block idx -> (kq, ks, vq, vs) host
        self.last_use = 0
        self.n_blocks = len(frames)


class PagePool:
    """The physical KV block pool + per-slot block tables (host-side
    bookkeeping; the device arrays ``kp``/``vp`` thread through the
    engine's jitted programs and are written back here).

    ``dims`` = ``(L, KVh, hd)`` of the served config; ``max_seq`` must be
    a multiple of ``block_size`` (the gather math relies on it). Frame 0
    is the permanent null frame: all-zero, never allocated, the target of
    every unreserved table entry."""

    def __init__(self, dims, dtype, block_size, n_blocks, max_batch,
                 max_seq, cold_after=None):
        import jax.numpy as jnp

        L, KVh, hd = dims
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_seq % block_size != 0:
            raise ValueError(
                f"max_seq={max_seq} must be a multiple of "
                f"block_size={block_size} (the block-table gather "
                "reconstructs the dense cache as maxb*bs columns)")
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (frame 0 is the null frame), "
                f"got {n_blocks}")
        self.dims = (L, KVh, hd)
        self.dtype = jnp.dtype(dtype)
        self.bs = int(block_size)
        self.n_blocks = int(n_blocks)
        self.maxb = max_seq // self.bs
        self.max_seq = int(max_seq)
        self.cold_after = cold_after
        self.kp = jnp.zeros((n_blocks, L, KVh, self.bs, hd), dtype)
        self.vp = jnp.zeros_like(self.kp)
        self.refs = np.zeros(n_blocks, np.int64)
        self.refs[0] = 1                       # null frame: held forever
        self._free = list(range(n_blocks - 1, 0, -1))  # pop() -> frame 1 first
        self.tables = np.zeros((max_batch, self.maxb), np.int32)
        self._nres = np.zeros(max_batch, np.int64)
        self._nshared = np.zeros(max_batch, np.int64)
        self._prefixes = {}
        self._sweeps = 0
        self._cold_pages = 0
        self._cold_bytes = 0

    # -- geometry ---------------------------------------------------------
    @property
    def block_bytes(self):
        """Device bytes of ONE block across both sides (k + v)."""
        L, KVh, hd = self.dims
        return 2 * L * KVh * self.bs * hd * self.dtype.itemsize

    def blocks_for(self, n_cols):
        """Whole-budget block count for a session spanning ``n_cols``."""
        return -(-int(n_cols) // self.bs)

    def free_blocks(self):
        return len(self._free)

    def tables_device(self):
        import jax.numpy as jnp

        return jnp.asarray(self.tables)

    # -- allocation -------------------------------------------------------
    def _alloc(self, n):
        if n > len(self._free):
            raise PagePoolFullError(
                f"KV page pool exhausted: need {n} free block(s), have "
                f"{len(self._free)} of {self.n_blocks - 1} — admission "
                "backs off until sessions finish (raise page_blocks= to "
                "provision more)")
        frames = [self._free.pop() for _ in range(n)]
        for f in frames:
            self.refs[f] = 1
        if n:
            _BLOCKS.labels(state="hot").inc(n)
        return frames

    def _deref(self, frame):
        f = int(frame)
        if f == 0:
            return
        self.refs[f] -= 1
        if self.refs[f] == 0:
            self._free.append(f)

    def reserve(self, slot, n_cols, shared_frames=(), cow=False):
        """Reserve the slot's WHOLE block budget for a session spanning
        ``n_cols`` cache columns: leading table entries map to
        ``shared_frames`` (refcounted prefix blocks), the rest allocate
        private frames. Raises :class:`PagePoolFullError` before any
        mutation when the pool cannot cover the private part; ``cow``
        marks a boundary-block copy (prefix not block-aligned)."""
        need = self.blocks_for(n_cols)
        n_shared = len(shared_frames)
        if n_shared > need:
            raise ValueError(
                f"slot {slot}: {n_shared} shared frames exceed the "
                f"{need}-block budget for {n_cols} columns")
        if self._nres[slot]:
            raise RuntimeError(f"slot {slot} already holds a reservation")
        priv = self._alloc(need - n_shared)    # raises before mutation
        for j, f in enumerate(shared_frames):
            self.tables[slot, j] = f
            self.refs[int(f)] += 1
        for j, f in enumerate(priv):
            self.tables[slot, n_shared + j] = f
        self._nres[slot] = need
        self._nshared[slot] = n_shared
        if cow:
            _COW.inc()
        return need

    def free_slot(self, slot):
        """Release a finished session's frames (shared frames deref; a
        prefix frame survives on its registry pin)."""
        for j in range(int(self._nres[slot])):
            self._deref(self.tables[slot, j])
        self.tables[slot, :] = 0
        self._nres[slot] = 0
        self._nshared[slot] = 0

    def admit_row(self, slot, kc_row, vc_row):
        """Re-block a prefilled dense row into the slot's PRIVATE frames
        (the reserved entries past the shared prefix). The COW boundary
        block is covered here too: the row carries the prefix content at
        its columns, so the private boundary frame gets prefix + session
        tokens in one write. Validates :data:`HANDOFF_SCHEMA`."""
        import jax.numpy as jnp

        L, KVh, hd = self.dims
        _hs.validate(
            HANDOFF_SCHEMA,
            {"kc": kc_row, "vc": vc_row, "table": self.tables[slot]},
            dims={"L": L, "KVh": KVh, "T": self.max_seq, "hd": hd,
                  "maxb": self.maxb},
            dtypes={"cache": str(self.dtype)})
        lo, hi = int(self._nshared[slot]), int(self._nres[slot])
        # fixed-shape scatter: one compiled write-back for EVERY admission
        # shape — non-private entries aim past the pool and drop
        fw = np.full(self.maxb, self.n_blocks, np.int32)
        fw[lo:hi] = self.tables[slot, lo:hi]
        fw_d = jnp.asarray(fw)

        def blocks(row):
            b = row.reshape(L, KVh, self.maxb, self.bs, hd)
            return jnp.transpose(b, (2, 0, 1, 3, 4))

        self.kp = self.kp.at[fw_d].set(blocks(kc_row), mode="drop")
        self.vp = self.vp.at[fw_d].set(blocks(vc_row), mode="drop")

    # -- shared prefixes + cold pages -------------------------------------
    def put_prefix(self, key, kc_row, vc_row, prefix_len):
        """Write a registered prefix's FULL blocks into the pool once
        (pinned by the registry ref). Returns the number of shared
        blocks; a prefix shorter than one block shares nothing (its
        content rides each session's private boundary frame)."""
        import jax.numpy as jnp

        if key in self._prefixes:
            raise ValueError(f"prefix {key!r} already registered")
        n_full = int(prefix_len) // self.bs
        frames = self._alloc(n_full)           # raises before mutation
        if n_full:
            L, KVh, hd = self.dims
            fw = np.full(self.maxb, self.n_blocks, np.int32)
            fw[:n_full] = frames
            fw_d = jnp.asarray(fw)

            def blocks(row):
                b = row.reshape(L, KVh, self.maxb, self.bs, hd)
                return jnp.transpose(b, (2, 0, 1, 3, 4))

            self.kp = self.kp.at[fw_d].set(blocks(kc_row), mode="drop")
            self.vp = self.vp.at[fw_d].set(blocks(vc_row), mode="drop")
        entry = _PrefixEntry(frames)
        entry.last_use = self._sweeps
        self._prefixes[key] = entry
        return n_full

    def prefix_frames(self, key):
        """The shared frame list for a registered prefix, decompressing
        any cold page back into a fresh hot frame (the touch path).
        Raises :class:`PagePoolFullError` when decompression cannot get
        a frame. Returns ``None`` for an unknown key."""
        entry = self._prefixes.get(key)
        if entry is None:
            return None
        entry.last_use = self._sweeps
        if entry.cold:
            import jax.numpy as jnp

            from ..distributed import compress as _compress

            need = len(entry.cold)
            if need > len(self._free):
                raise PagePoolFullError(
                    f"cold-page decompression for prefix {key!r} needs "
                    f"{need} free block(s), have {len(self._free)}")
            for idx in sorted(entry.cold):
                kq, ks, vq, vs = entry.cold.pop(idx)
                (f,) = self._alloc(1)
                self.kp = self.kp.at[f].set(jnp.asarray(
                    _compress.dequantize_rows(kq, ks, self.dtype)))
                self.vp = self.vp.at[f].set(jnp.asarray(
                    _compress.dequantize_rows(vq, vs, self.dtype)))
                entry.frames[idx] = f
                self._cold_pages -= 1
                self._cold_bytes -= kq.size + ks.size * 4 \
                    + vq.size + vs.size * 4
        return list(entry.frames)

    def drop_prefix(self, key):
        """Unpin a registered prefix (frames free once no session refs
        them; cold pages are discarded)."""
        entry = self._prefixes.pop(key)
        for f in entry.frames:
            if f is not None:
                self._deref(f)
        self._cold_pages -= len(entry.cold)
        self._cold_bytes -= sum(
            kq.size + ks.size * 4 + vq.size + vs.size * 4
            for kq, ks, vq, vs in entry.cold.values())

    def sweep(self):
        """One cold-compression round (the engine calls this per step):
        a prefix frame with NO live session ref, untouched for
        ``cold_after`` sweeps, compresses to an int8 host page
        (deterministic row codec) and frees its frame."""
        self._sweeps += 1
        if self.cold_after is None:
            return 0
        compressed = 0
        for key, entry in self._prefixes.items():
            if self._sweeps - entry.last_use < self.cold_after:
                continue
            for idx, f in enumerate(entry.frames):
                if f is None or self.refs[f] != 1:
                    continue                   # a session still maps it
                from ..distributed import compress as _compress

                kb = np.asarray(self.kp[f])
                vb = np.asarray(self.vp[f])
                kq, ks = (np.asarray(a) for a in
                          _compress.quantize_rows(kb))
                vq, vs = (np.asarray(a) for a in
                          _compress.quantize_rows(vb))
                entry.cold[idx] = (kq, ks, vq, vs)
                entry.frames[idx] = None
                self._deref(f)
                self._cold_pages += 1
                self._cold_bytes += kq.size + ks.size * 4 \
                    + vq.size + vs.size * 4
                compressed += 1
        if compressed:
            _BLOCKS.labels(state="cold").inc(compressed)
        return compressed

    # -- accounting -------------------------------------------------------
    def live_blocks(self):
        """Frames currently allocated (hot), null frame excluded."""
        return self.n_blocks - 1 - len(self._free)

    def bytes_in_use(self):
        """Physical KV bytes the pool holds right now: hot frames at the
        device dtype plus compressed cold pages (int8 values + f32 row
        scales). Shared prefix frames count ONCE — this is the number
        the >= 2x KV-bytes-per-session gate divides."""
        return self.live_blocks() * self.block_bytes + self._cold_bytes

    def stats(self):
        return {
            "block_size": self.bs,
            "n_blocks": self.n_blocks,
            "max_blocks_per_slot": self.maxb,
            "free_blocks": len(self._free),
            "live_blocks": self.live_blocks(),
            "cold_pages": self._cold_pages,
            "block_bytes": self.block_bytes,
            "bytes_in_use": self.bytes_in_use(),
            "cold_bytes": self._cold_bytes,
            "prefixes": len(self._prefixes),
            "sweeps": self._sweeps,
        }


class AdapterRegistry:
    """Named multi-LoRA adapter slots with LRU eviction + pinning.

    Slot 0 is reserved (all-zero factors = base-model requests); usable
    slots are ``1..n_slots``. The registry is pure bookkeeping — the
    engine owns the stacked device factors and writes/zeroes slots on
    load/evict. Events land on ``serving_adapter_total{event}``."""

    def __init__(self, n_slots):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self._slots = {}                       # name -> slot index
        self._pinned = set()
        self._lru = []                         # oldest first
        self._free = list(range(self.n_slots, 0, -1))

    def lookup(self, name):
        """Resolve a loaded adapter (LRU-touch + hit count), else None."""
        slot = self._slots.get(name)
        if slot is not None:
            self._touch(name)
            _ADAPTER.labels(event="hit").inc()
        return slot

    def peek(self, name):
        """Resolve without touching LRU or counting a hit."""
        return self._slots.get(name)

    def _touch(self, name):
        if name in self._lru:
            self._lru.remove(name)
        self._lru.append(name)

    def admit(self, name, pin=False):
        """Claim a slot for ``name``: a free slot if any, else evict the
        LRU unpinned adapter. Returns ``(slot, evicted_name)`` —
        ``evicted_name`` is not None when an adapter was displaced (the
        engine must zero/overwrite the device slot and requeue that
        adapter's in-flight sessions). Raises when every slot is pinned."""
        if name in self._slots:
            raise ValueError(f"adapter {name!r} is already loaded")
        evicted = None
        if self._free:
            slot = self._free.pop()
        else:
            victim = next((n for n in self._lru
                           if n not in self._pinned), None)
            if victim is None:
                raise RuntimeError(
                    f"all {self.n_slots} adapter slots are pinned — "
                    "evict_adapter() one or raise max_adapters=")
            slot = self._slots.pop(victim)
            self._lru.remove(victim)
            _ADAPTER.labels(event="evict").inc()
            evicted = victim
        self._slots[name] = slot
        if pin:
            self._pinned.add(name)
        self._touch(name)
        _ADAPTER.labels(event="load").inc()
        return slot, evicted

    def evict(self, name):
        """Explicitly evict ``name`` (pinned or not); returns its slot."""
        if name not in self._slots:
            raise KeyError(f"adapter {name!r} is not loaded")
        slot = self._slots.pop(name)
        self._pinned.discard(name)
        if name in self._lru:
            self._lru.remove(name)
        self._free.append(slot)
        _ADAPTER.labels(event="evict").inc()
        return slot

    def loaded(self):
        return dict(self._slots)

    def stats(self):
        return {"n_slots": self.n_slots,
                "loaded": len(self._slots),
                "pinned": len(self._pinned),
                "free_slots": len(self._free)}
