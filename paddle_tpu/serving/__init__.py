"""Multi-engine serving tier (docs/SERVING.md).

Three layers over the continuous-batching ``ServingEngine``:

- :mod:`.decode_model` — the documented decode-model protocol + registry
  that makes the engine model-agnostic (gpt registers itself; add your
  own family without touching engine code);
- :mod:`.router` — a front-door ``Router`` fanning ``submit()`` across N
  named engine instances with deadline/priority-aware placement,
  session/prefix-affinity hashing, drain-aware failover, and trace_id
  propagation (router -> engine -> slot spans share one trace);
- :mod:`.disagg` — ``DisaggregatedPool``: dedicated prefill workers hand
  finished KV rows to decode engines (the MPMD per-stage split),
  bit-identical to the monolithic engine;
- :mod:`.paging` — the FLAGS_paged_kv block pool: paged KV frames with
  per-slot block tables, refcounted shared prefixes (copy-on-write
  boundary blocks), int8 cold pages, and the multi-LoRA ``AdapterRegistry``
  behind ``ServingEngine.load_adapter``/``submit(adapter=)``.

Import cost discipline: ``Router``/``DisaggregatedPool``/``PagePool``
load lazily — constructing a plain single-engine ``ServingEngine`` never
imports them (pinned by tests/test_router_gate.py and
tests/test_paging_gate.py).
"""
from . import decode_model  # noqa: F401  (registry: always available)
from .decode_model import (  # noqa: F401
    DecodeModel, get_decode_model, register_decode_model,
    registered_decode_models)

__all__ = ["decode_model", "DecodeModel", "register_decode_model",
           "get_decode_model", "registered_decode_models", "Router",
           "DisaggregatedPool", "PrefillWorker", "PagePool",
           "PagePoolFullError", "AdapterRegistry"]

_LAZY_ATTRS = {"Router": ".router",
               "DisaggregatedPool": ".disagg",
               "PrefillWorker": ".disagg",
               "PagePool": ".paging",
               "PagePoolFullError": ".paging",
               "AdapterRegistry": ".paging",
               "router": ".router",
               "disagg": ".disagg",
               "paging": ".paging"}


def __getattr__(name):   # PEP 562: lazy submodule/class loading
    if name in _LAZY_ATTRS:
        import importlib

        mod = importlib.import_module(_LAZY_ATTRS[name], __name__)
        return mod if name in ("router", "disagg", "paging") \
            else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
