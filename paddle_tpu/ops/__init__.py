"""Custom TPU kernels (Pallas) — the analog of the reference's hand-written CUDA ops
(paddle/fluid/operators/*.cu): flash attention, NMS, and quantization kernels live here.
Only ops where XLA fusion is insufficient get a kernel; everything else is plain jnp."""
