"""Pallas flash-attention kernel for TPU.

No reference equivalent (the reference composes attention from matmuls,
python/paddle/nn/layer/transformer.py:83); this is a TPU-native addition following the
standard blockwise-softmax (Flash) recipe from /opt/skills/guides/pallas_guide.md.

Falls back (supported() -> False) when shapes don't tile onto the MXU (head_dim % 128,
seq % block) or when not running on TPU.
"""
import functools
import math

import jax
import jax.numpy as jnp

_BLOCK_Q = 128
_BLOCK_K = 128


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def supported(q_shape, dtype_str):
    """q_shape: (batch, seq, heads, head_dim)."""
    if len(q_shape) != 4:
        return False
    b, s, h, d = q_shape
    if not _on_tpu():
        return False
    if d % 128 != 0 or s % _BLOCK_Q != 0 or s < 2 * _BLOCK_Q:
        return False
    if dtype_str not in ("float32", "bfloat16"):
        return False
    return True


@functools.partial(jax.jit, static_argnames=("causal",))
def flash_attention(q, k, v, causal=False):
    """q,k,v: [b, s, h, d] -> [b, s, h, d]. Blockwise online-softmax attention."""
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    # [b, s, h, d] -> [b*h, s, d]
    qh = jnp.swapaxes(q, 1, 2).reshape(b * h, s, d)
    kh = jnp.swapaxes(k, 1, 2).reshape(b * h, s, d)
    vh = jnp.swapaxes(v, 1, 2).reshape(b * h, s, d)

    n_q = s // _BLOCK_Q
    n_k = s // _BLOCK_K

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        q_blk = q_ref[...].astype(jnp.float32) * scale  # [BQ, d]

        def body(ki, carry):
            acc, m_i, l_i = carry
            k_blk = pl.load(k_ref, (pl.dslice(ki * _BLOCK_K, _BLOCK_K), slice(None))).astype(jnp.float32)
            v_blk = pl.load(v_ref, (pl.dslice(ki * _BLOCK_K, _BLOCK_K), slice(None))).astype(jnp.float32)
            scores = q_blk @ k_blk.T  # [BQ, BK]
            if causal:
                q_pos = qi * _BLOCK_Q + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_Q, _BLOCK_K), 0)
                k_pos = ki * _BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_Q, _BLOCK_K), 1)
                scores = jnp.where(q_pos >= k_pos, scores, -1e30)
            m_new = jnp.maximum(m_i, jnp.max(scores, axis=-1))
            p = jnp.exp(scores - m_new[:, None])
            alpha = jnp.exp(m_i - m_new)
            l_new = l_i * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + p @ v_blk
            return acc, m_new, l_new

        acc0 = jnp.zeros((_BLOCK_Q, d), jnp.float32)
        m0 = jnp.full((_BLOCK_Q,), -1e30, jnp.float32)
        l0 = jnp.zeros((_BLOCK_Q,), jnp.float32)
        if causal:
            upper = qi + 1  # only blocks up to the diagonal
            acc, m_i, l_i = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
        else:
            acc, m_i, l_i = jax.lax.fori_loop(0, n_k, body, (acc0, m0, l0))
        o_ref[...] = (acc / l_i[:, None]).astype(o_ref.dtype)

    from jax.experimental.pallas import BlockSpec

    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q),
        in_specs=[
            BlockSpec((None, _BLOCK_Q, d), lambda bh, qi: (bh, qi, 0)),
            BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
            BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=BlockSpec((None, _BLOCK_Q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), qh.dtype),
    )(qh, kh, vh)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
