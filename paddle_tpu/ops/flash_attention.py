"""Pallas flash-attention (fwd + custom-VJP bwd) for TPU.

No reference equivalent (the reference composes attention from matmuls,
python/paddle/nn/layer/transformer.py:83); this is a TPU-native addition following
the blockwise online-softmax (FlashAttention-2) recipe from
/opt/skills/guides/pallas_guide.md: a 3-D grid (batch*heads, q blocks, kv blocks)
streams one [128, d] K/V block through VMEM per step while (acc, m, l) persist in
VMEM scratch across the kv dimension — nothing scales with seq in VMEM, so 16k+
sequences fit. The forward also emits the per-row logsumexp; the backward
recomputes P = exp(S - L) blockwise (dq kernel and dk/dv kernel), never
materializing the [s, s] matrix in HBM.

Supported: head_dim % 64 == 0, seq % 128 == 0, fp32/bf16, seq >= 1024. Block
sizes adapt to seq (largest of 512/256/128 dividing it): 512-wide blocks keep
the MXU fed ([512, d] @ [d, 512] tiles) and cut grid-step overhead — measured
GPT-2-small full-train-step throughput at s=1024 on one v5e chip: 115.5k tok/s
(blk 512) vs 93.2k (blk 256) vs 63.1k (blk 128) vs 70.5k for XLA's fused
attention. Below s=1024 the [s, s] materialization XLA does is cheap enough
that flash doesn't pay. `interpret=True` runs the kernels on CPU.

Hand-rolled rather than importing jax.experimental.pallas.ops.tpu.flash_attention
deliberately: the framework owns its hot kernels end-to-end (same reason the
reference carries its own fused attention ops), the guide-driven implementation is
the template for further custom kernels, and upstream's experimental API/layout
has no stability promise. The planned ring-attention fusion landed in
distributed/long_context.py `ring_flash_attention_spmd`: these forward AND
backward kernels run per ring block (global-lse blockwise calls are exact).
"""
import functools
import math
import operator

import jax
import jax.numpy as jnp

_NEG = -1e30


def _block_for(s):
    """Largest MXU-friendly block (512/256/128) that tiles seq exactly.
    FLAGS_flash_attention_block forces a specific size for tuning sweeps."""
    from ..flags import get_flag

    forced = get_flag("flash_attention_block", 0)
    if forced:
        if forced not in (128, 256, 512) or s % forced:
            raise ValueError(
                f"FLAGS_flash_attention_block={forced} must be 128/256/512 "
                f"and divide seq {s}")
        return forced
    for blk in (512, 256, 128):
        if s % blk == 0:
            return blk
    raise ValueError(f"seq {s} not divisible by 128")


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# static audit manifest (analysis/pallas_audit.py, ISSUE 13)
# ---------------------------------------------------------------------------

#: representative supported configs: the s=1024 entry floor and the 16k
#: long-context windowed config, at the gpt2s head_dim
_AUDIT_CONFIGS = ((1024, 64), (16384, 64))


def audit_manifest():
    """Audit entries for the fwd/dq/dkv kernels — block sizes through
    the SAME _block_for the runtime uses (pure arithmetic)."""
    entries = []
    for dtype in ("float32", "bfloat16"):
        for s, d in _AUDIT_CONFIGS:
            blk = _block_for(s)
            row = [{"name": "q", "block": (blk, d), "dtype": dtype},
                   {"name": "k", "block": (blk, d), "dtype": dtype},
                   {"name": "v", "block": (blk, d), "dtype": dtype}]
            entries.append({
                "kernel": f"flash.fwd[s={s},d={d},{dtype}]",
                "op": "flash_fwd", "in_dtype": dtype,
                "acc_dtype": "float32", "matmul": True,
                "grid": {"seq_q": (s, blk), "seq_k": (s, blk)},
                "buffers": row + [
                    {"name": "o", "block": (blk, d), "dtype": dtype},
                    {"name": "lse", "block": (1, blk),
                     "dtype": "float32"},
                    {"name": "acc(scratch)", "block": (blk, d),
                     "dtype": "float32", "stream": False},
                    {"name": "m(scratch)", "block": (blk, 128),
                     "dtype": "float32", "stream": False},
                    {"name": "l(scratch)", "block": (blk, 128),
                     "dtype": "float32", "stream": False}]})
            entries.append({
                "kernel": f"flash.dq[s={s},d={d},{dtype}]",
                "op": "flash_dq", "in_dtype": dtype,
                "acc_dtype": "float32", "matmul": True,
                "grid": {"seq_q": (s, blk), "seq_k": (s, blk)},
                "buffers": row + [
                    {"name": "do", "block": (blk, d), "dtype": dtype},
                    {"name": "lse", "block": (1, blk),
                     "dtype": "float32"},
                    {"name": "delta", "block": (1, blk),
                     "dtype": "float32"},
                    {"name": "dq", "block": (blk, d), "dtype": dtype},
                    {"name": "dq_acc(scratch)", "block": (blk, d),
                     "dtype": "float32", "stream": False}]})
            entries.append({
                "kernel": f"flash.dkv[s={s},d={d},{dtype}]",
                "op": "flash_dkv", "in_dtype": dtype,
                "acc_dtype": "float32", "matmul": True,
                "grid": {"seq_q": (s, blk), "seq_k": (s, blk)},
                "buffers": row + [
                    {"name": "do", "block": (blk, d), "dtype": dtype},
                    {"name": "lse", "block": (1, blk),
                     "dtype": "float32"},
                    {"name": "delta", "block": (1, blk),
                     "dtype": "float32"},
                    {"name": "dk", "block": (blk, d), "dtype": dtype},
                    {"name": "dv", "block": (blk, d), "dtype": dtype},
                    {"name": "dk_acc(scratch)", "block": (blk, d),
                     "dtype": "float32", "stream": False},
                    {"name": "dv_acc(scratch)", "block": (blk, d),
                     "dtype": "float32", "stream": False}]})
    return entries


def supported(q_shape, dtype_str):
    """q_shape: (batch, seq, heads, head_dim)."""
    if len(q_shape) != 4:
        return False
    b, s, h, d = q_shape
    if not _on_tpu():
        return False
    if d % 64 != 0 or s % 128 != 0 or s < 1024:
        return False
    if dtype_str not in ("float32", "bfloat16"):
        return False
    return True


def _kv_index(causal, n_win=None):
    """K/V block map for (b, qi, ki) grids: on masked steps (causal ki > qi,
    or window ki < qi - n_win) alias a block already needed so no new DMA
    is issued."""
    if not causal:
        return lambda b, qi, ki: (b, ki, 0)
    if n_win is None:
        return lambda b, qi, ki: (b, jnp.minimum(ki, qi), 0)
    return lambda b, qi, ki: (b, jnp.clip(ki, jnp.maximum(qi - n_win, 0),
                                          qi), 0)


def _q_index(causal, n_win=None):
    """Q/dO block map for (b, ki, qi) grids: masked steps alias into the
    visible band [ki, ki + n_win]."""
    if not causal:
        return lambda b, ki, qi: (b, qi, 0)
    if n_win is None:
        return lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0)
    return lambda b, ki, qi: (b, jnp.clip(qi, ki, ki + n_win), 0)


def _lse_index(causal, n_win=None):
    if not causal:
        return lambda b, ki, qi: (b, 0, qi)
    if n_win is None:
        return lambda b, ki, qi: (b, 0, jnp.maximum(qi, ki))
    return lambda b, ki, qi: (b, 0, jnp.clip(qi, ki, ki + n_win))


def _causal_mask(qi, ki, scores, window=None):
    """Causal (and optionally sliding-window) score mask: keep
    k_pos <= q_pos, and with `window` also q_pos - k_pos < window."""
    bq, bk = scores.shape
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= (q_pos - k_pos) < window
    return jnp.where(keep, scores, _NEG)


def _n_win(window, blk):
    """Max block distance qi - ki with any visible position (conservative
    by at most one block; exact masking happens inside the kernel)."""
    return None if window is None else (window - 1 + blk - 1) // blk


# ---------------- forward kernel ---------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                causal, scale, n_k, d, blk, window=None, nwin=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros((blk, d), jnp.float32)
        m_ref[...] = jnp.full((blk, 128), _NEG, jnp.float32)
        l_ref[...] = jnp.zeros((blk, 128), jnp.float32)

    run = (ki <= qi) if causal else (ki >= 0)
    if nwin is not None:
        run &= (qi - ki) <= nwin

    @pl.when(run)
    def _step():
        q_blk = q_ref[...].astype(jnp.float32) * scale        # [BQ, d]
        k_blk = k_ref[...].astype(jnp.float32)                # [BK, d]
        v_blk = v_ref[...].astype(jnp.float32)
        scores = q_blk @ k_blk.T                              # [BQ, BK]
        if causal:
            scores = _causal_mask(qi, ki, scores, window)
        m_prev = m_ref[...]                                   # [BQ, 128]
        l_prev = l_ref[...]
        m_cur = jnp.broadcast_to(jnp.max(scores, -1, keepdims=True),
                                 (blk, 128))
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)                      # [BQ, 128]
        p = jnp.exp(scores - m_next[:, :1])                   # [BQ, BK]
        l_ref[...] = alpha * l_prev + jnp.broadcast_to(
            jnp.sum(p, -1, keepdims=True), (blk, 128))
        m_ref[...] = m_next
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + p @ v_blk

    @pl.when(ki == n_k - 1)
    def _flush():
        l = l_ref[:, :1]                                      # [BQ, 1]
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[...] = (m_ref[:, :1] + jnp.log(l)).reshape(1, blk)


def _flash_fwd(q3, k3, v3, causal, scale, interpret, window=None):
    """q3/k3/v3: [bh, s, d] -> (o [bh, s, d], lse [bh, s] f32). window:
    sliding-window causal attention (keep q_pos - k_pos < window)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import BlockSpec
    from jax.experimental.pallas import tpu as pltpu

    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    bh, s, d = q3.shape
    blk = _block_for(s)
    nwin = _n_win(window, blk)
    n_q, n_k = s // blk, s // blk
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale, n_k=n_k,
                          d=d, blk=blk, window=window, nwin=nwin),
        grid=(bh, n_q, n_k),
        in_specs=[
            BlockSpec((None, blk, d), lambda b, qi, ki: (b, qi, 0)),
            BlockSpec((None, blk, d), _kv_index(causal, nwin)),
            BlockSpec((None, blk, d), _kv_index(causal, nwin)),
        ],
        out_specs=[
            BlockSpec((None, blk, d), lambda b, qi, ki: (b, qi, 0)),
            BlockSpec((None, 1, blk), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, d), jnp.float32),
            pltpu.VMEM((blk, 128), jnp.float32),
            pltpu.VMEM((blk, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse[:, 0, :]


# ---------------- backward kernels -------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, causal, scale, n_k, d, blk, window=None,
               nwin=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros((blk, d), jnp.float32)

    run = (ki <= qi) if causal else (ki >= 0)
    if nwin is not None:
        run &= (qi - ki) <= nwin

    @pl.when(run)
    def _step():
        q_blk = q_ref[...].astype(jnp.float32) * scale
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        do_blk = do_ref[...].astype(jnp.float32)              # [BQ, d]
        lse = lse_ref[...].reshape(blk, 1)
        delta = delta_ref[...].reshape(blk, 1)
        scores = q_blk @ k_blk.T                              # [BQ, BK]
        if causal:
            scores = _causal_mask(qi, ki, scores, window)
        p = jnp.exp(scores - lse)                             # [BQ, BK]
        dp = do_blk @ v_blk.T
        ds = p * (dp - delta)
        dq_acc_ref[...] += ds @ k_blk

    @pl.when(ki == n_k - 1)
    def _flush():
        dq_ref[...] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                dk_acc_ref, dv_acc_ref, *, causal, scale, n_q, d, blk,
                window=None, nwin=None):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros((blk, d), jnp.float32)
        dv_acc_ref[...] = jnp.zeros((blk, d), jnp.float32)

    run = (qi >= ki) if causal else (qi >= 0)
    if nwin is not None:
        run &= (qi - ki) <= nwin

    @pl.when(run)
    def _step():
        q_blk = q_ref[...].astype(jnp.float32) * scale        # [BQ, d]
        k_blk = k_ref[...].astype(jnp.float32)                # [BK, d]
        v_blk = v_ref[...].astype(jnp.float32)
        do_blk = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...].reshape(blk, 1)
        delta = delta_ref[...].reshape(blk, 1)
        scores = q_blk @ k_blk.T                              # [BQ, BK]
        if causal:
            scores = _causal_mask(qi, ki, scores, window)
        p = jnp.exp(scores - lse)                             # [BQ, BK]
        dv_acc_ref[...] += p.T @ do_blk
        dp = do_blk @ v_blk.T
        ds = p * (dp - delta)
        dk_acc_ref[...] += ds.T @ q_blk  # q_blk carries the scale: dS^T (Q*scale)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, causal, scale, interpret,
               delta=None, window=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import BlockSpec
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q3.shape
    blk = _block_for(s)
    nwin = _n_win(window, blk)
    n_q, n_k = s // blk, s // blk
    if delta is None:  # ring callers precompute: o3/do3 are hop-invariant
        delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                        axis=-1)                              # [bh, s]
    lse2 = lse[:, None, :]                                    # [bh, 1, s]
    delta2 = delta[:, None, :]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale, n_k=n_k,
                          d=d, blk=blk, window=window, nwin=nwin),
        grid=(bh, n_q, n_k),
        in_specs=[
            BlockSpec((None, blk, d), lambda b, qi, ki: (b, qi, 0)),
            BlockSpec((None, blk, d), _kv_index(causal, nwin)),
            BlockSpec((None, blk, d), _kv_index(causal, nwin)),
            BlockSpec((None, blk, d), lambda b, qi, ki: (b, qi, 0)),
            BlockSpec((None, 1, blk), lambda b, qi, ki: (b, 0, qi)),
            BlockSpec((None, 1, blk), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_specs=BlockSpec((None, blk, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((blk, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse2, delta2)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale, n_q=n_q,
                          d=d, blk=blk, window=window, nwin=nwin),
        grid=(bh, n_k, n_q),
        in_specs=[
            BlockSpec((None, blk, d), _q_index(causal, nwin)),
            BlockSpec((None, blk, d), lambda b, ki, qi: (b, ki, 0)),
            BlockSpec((None, blk, d), lambda b, ki, qi: (b, ki, 0)),
            BlockSpec((None, blk, d), _q_index(causal, nwin)),
            BlockSpec((None, 1, blk), _lse_index(causal, nwin)),
            BlockSpec((None, 1, blk), _lse_index(causal, nwin)),
        ],
        out_specs=[
            BlockSpec((None, blk, d), lambda b, ki, qi: (b, ki, 0)),
            BlockSpec((None, blk, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, d), jnp.float32),
            pltpu.VMEM((blk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse2, delta2)
    return dq, dk, dv


# ---------------- public API (custom VJP over [b, s, h, d]) -------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q3, k3, v3, causal, interpret, window=None):
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, _ = _flash_fwd(q3, k3, v3, causal, scale, interpret, window=window)
    return o


def _flash_fwd_rule(q3, k3, v3, causal, interpret, window=None):
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, lse = _flash_fwd(q3, k3, v3, causal, scale, interpret, window=window)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd_rule(causal, interpret, window, res, do3):
    q3, k3, v3, o3, lse = res
    scale = 1.0 / math.sqrt(q3.shape[-1])
    dq, dk, dv = _flash_bwd(q3, k3, v3, o3, lse, do3, causal, scale,
                            interpret, window=window)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, interpret=False, window=None):
    """q,k,v: [b, s, h, d] -> [b, s, h, d]. Differentiable (custom VJP).

    window=W (requires causal=True) restricts attention to the last W
    tokens (Mistral-style sliding window): block pairs entirely outside
    the band are skipped — compute AND cache reads scale O(s * W) instead
    of O(s^2) for long sequences.

    The resolved FLAGS_flash_attention_block value joins the jit cache key
    (static `_blk`), so in-process set_flags sweeps retrace rather than
    silently reusing the old block's executable. Enclosing jits (e.g. a
    trainer's compiled train step) still bake the flag at THEIR build time —
    rebuild the trainer (or use a fresh process) when sweeping under one."""
    from ..flags import get_flag

    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if isinstance(window, bool):
            raise ValueError(f"window must be a positive int, got {window!r}")
        try:
            window = int(operator.index(window))  # accepts numpy ints
        except TypeError:
            raise ValueError(
                f"window must be a positive int, got {window!r}") from None
        if window < 1:
            raise ValueError(f"window must be a positive int, got {window!r}")
    return _flash_attention_jit(q, k, v, causal=causal, interpret=interpret,
                                window=window,
                                _blk=get_flag("flash_attention_block", 0))


@functools.partial(jax.jit, static_argnames=("causal", "interpret", "_blk",
                                             "window"))
def _flash_attention_jit(q, k, v, causal, interpret, _blk, window=None):
    b, s, h, d = q.shape
    qh = jnp.swapaxes(q, 1, 2).reshape(b * h, s, d)
    kh = jnp.swapaxes(k, 1, 2).reshape(b * h, s, d)
    vh = jnp.swapaxes(v, 1, 2).reshape(b * h, s, d)
    out = _flash(qh, kh, vh, causal, interpret, window)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
