"""TPP-style Pallas micro-kernel registry (FLAGS_tpp_kernels).

Tensor Processing Primitives (arXiv:2104.05755) argues the hot ops XLA
fuses badly want a SMALL vocabulary of composable blocked primitives —
not one hand kernel per op. This module is that vocabulary for the
framework, Pallas-lowered (interpret mode on CPU, the same switch as
ops/flash_attention.py).

Micro-kernels — each compiled per (op, dtype, block shape) and cached
in the registry:

- ``matmul``        blocked matmul-accumulate: (M/bm, N/bn, K/bk) grid,
                    fp32 VMEM accumulator persisting across the K
                    steps, optional fused input-activation and
                    bias+activation epilogue (the TPP "BRGEMM + unary")
- ``bias_act``      fused bias + activation over row blocks (VPU)
- ``softmax_rows``  blocked softmax row-pass (stable: fp32 row max/sum)
- ``masked_reduce`` masked row reduce (sum|max)

Ported ops — the fusion-hostile GPT hot spots beyond
flash-attention/NMS (docs/PERF.md "TPP registry"); both are
``jax.custom_vjp`` (Pallas forward, reference-math backward) so the
trainer differentiates through them:

- ``ln_matmul``  the layernorm -> matmul prologue: rows are normalized
  in fp32 INSIDE the matmul kernel's x-block load, so the normalized
  activation never round-trips HBM between the two ops
- ``fused_mlp``  the GPT MLP block: matmul+bias feeding a second
  matmul whose x blocks are activated (gelu) on load — the hidden
  activation is the only HBM-materialized intermediate

``gpt_block_mlp`` composes them for models/gpt.py: ln_matmul covers
ln2+fc1, the fused_mlp tail covers gelu+fc2.

Every op call is metered (``tpp_kernel_calls_total{op}``, counted at
trace time — the PR 2 chokepoint semantics: once per compiled program)
and registered in the device cost registry (``trace.costs``
site="tpp") with analytic FLOPs/bytes so the MFU report can attribute
TPP-ported work. The module is imported ONLY when FLAGS_tpp_kernels
routes a model through it (gate-pinned by tests/test_async_gate.py).
"""
import functools

import jax
import jax.numpy as jnp

from .. import monitor as _monitor
from ..trace import costs as _costs

__all__ = ["matmul", "bias_act", "softmax_rows", "masked_reduce",
           "ln_matmul", "fused_mlp", "gpt_block_mlp", "paged_attention",
           "paged_attention_ref", "registry_table", "pick_block",
           "supported_2d", "audit_manifest"]

_LN_EPS = 1e-5   # nn.LayerNorm's default epsilon (the only one GPT uses)

_CALLS = None


def _calls():
    global _CALLS
    if _CALLS is None:
        _CALLS = _monitor.counter(
            "tpp_kernel_calls_total",
            "TPP micro-kernel/port invocations by op (counted at trace "
            "time — once per compiled program, like the collective "
            "chokepoint meters)", labelnames=("op",))
    return _CALLS


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


#: candidate block edges, MXU/VPU-aligned first (128 is the MXU edge;
#: the smaller tails keep the tiny CI models on the kernel path in
#: interpret mode, where alignment affects nothing but tiling)
_BLOCK_EDGES = (256, 128, 64, 32, 16, 8)


def pick_block(dim):
    """Largest registry block edge dividing `dim` (None if indivisible —
    callers fall back to the dense path)."""
    for b in _BLOCK_EDGES:
        if dim % b == 0:
            return b
    return None


def supported_2d(m, k, n, dtype):
    """Can the registry tile an [m, k] @ [k, n] op? Returns the
    (bm, bn, bk) block shape, or None."""
    if str(dtype) not in ("float32", "bfloat16"):
        return None
    bm, bk, bn = pick_block(m), pick_block(k), pick_block(n)
    if bm is None or bk is None or bn is None:
        return None
    return (bm, bn, bk)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_REGISTRY = {}   # (op, dtype_str, block tuple) -> {"fn", "calls"}


def _kernel_entry(op, dtype, block, builder):
    key = (str(op), str(dtype), tuple(block))
    entry = _REGISTRY.get(key)
    if entry is None:
        entry = _REGISTRY[key] = {"fn": builder(), "calls": 0}
    return entry


def registry_table():
    """Snapshot of every built kernel: [{op, dtype, block, calls}] —
    the docs/PERF.md TPP registry table, live."""
    return [{"op": op, "dtype": dt, "block": list(blk),
             "calls": e["calls"]}
            for (op, dt, blk), e in sorted(_REGISTRY.items())]


def _note_call(entry, op, flops, nbytes):
    """Trace-time metering: count the call, land analytic FLOPs/bytes
    in the cost registry under site='tpp' (cumulative per op)."""
    entry["calls"] += 1
    if _monitor.is_enabled():
        _calls().labels(op=op).inc()
    _costs.record_manual("tpp", op, flops=flops, bytes_accessed=nbytes)


# ---------------------------------------------------------------------------
# static audit manifest (analysis/pallas_audit.py, ISSUE 13)
# ---------------------------------------------------------------------------

#: representative production shapes: the gpt2s hot path (hidden 768,
#: intermediate 3072, m = rows per kernel call). The manifest derives
#: blocks through the SAME pick_block/supported_2d the runtime uses, so
#: a block-table change flows straight into the lint-time budget check.
_AUDIT_SHAPES = ((512, 768, 3072), (512, 3072, 768))
_AUDIT_DTYPES = ("float32", "bfloat16")


def _matmul_entry(kernel, m, k, n, dtype, block, ln_prologue=False,
                  has_bias=True):
    bm, bn, bk = block
    bufs = [{"name": "x", "block": (bm, bk), "dtype": dtype}]
    if ln_prologue:
        bufs += [{"name": "gamma", "block": (1, bk), "dtype": dtype},
                 {"name": "beta", "block": (1, bk), "dtype": dtype}]
    bufs.append({"name": "w", "block": (bk, bn), "dtype": dtype})
    if has_bias:
        bufs.append({"name": "bias", "block": (1, bn), "dtype": dtype})
    bufs += [{"name": "out", "block": (bm, bn), "dtype": dtype},
             {"name": "acc(scratch)", "block": (bm, bn),
              "dtype": "float32", "stream": False}]
    return {"kernel": kernel, "op": kernel.split("[")[0],
            "in_dtype": dtype, "acc_dtype": "float32", "matmul": True,
            "grid": {"m": (m, bm), "n": (n, bn), "k": (k, bk)},
            "buffers": bufs}


def audit_manifest():
    """Declarative audit entries for every TPP kernel shape class —
    pure arithmetic mirroring the builders (nothing compiles)."""
    entries = []
    for dtype in _AUDIT_DTYPES:
        for m, k, n in _AUDIT_SHAPES:
            block = supported_2d(m, k, n, dtype)
            if block is None:
                continue
            entries.append(_matmul_entry(
                f"tpp.matmul[{m}x{k}x{n},{dtype}]", m, k, n, dtype,
                block))
        m, k, n = _AUDIT_SHAPES[0]
        bm, bn = pick_block(m), pick_block(n)
        # ln_matmul pins bk == k (LN row stats need the whole row)
        entries.append(_matmul_entry(
            f"tpp.ln_matmul[{m}x{k}x{n},{dtype}]", m, k, n, dtype,
            (bm, bn, k), ln_prologue=True))
        bm, bn = pick_block(m), pick_block(k)
        entries.append({
            "kernel": f"tpp.bias_act[{m}x{k},{dtype}]", "op": "bias_act",
            "in_dtype": dtype, "matmul": False,
            "grid": {"m": (m, bm), "n": (k, bn)},
            "buffers": [
                {"name": "x", "block": (bm, bn), "dtype": dtype},
                {"name": "bias", "block": (1, bn), "dtype": dtype},
                {"name": "out", "block": (bm, bn), "dtype": dtype}]})
        entries.append({
            "kernel": f"tpp.softmax_rows[{m}x{k},{dtype}]",
            "op": "softmax_rows", "in_dtype": dtype, "matmul": False,
            "grid": {"m": (m, bm)},
            "buffers": [
                {"name": "x", "block": (bm, k), "dtype": dtype},
                {"name": "out", "block": (bm, k), "dtype": dtype}]})
        entries.append({
            "kernel": f"tpp.masked_reduce[{m}x{k},{dtype}]",
            "op": "masked_reduce", "in_dtype": dtype, "matmul": False,
            "grid": {"m": (m, bm)},
            "buffers": [
                {"name": "x", "block": (bm, k), "dtype": dtype},
                {"name": "mask", "block": (bm, k), "dtype": "int32"},
                {"name": "out", "block": (bm, 1), "dtype": dtype}]})
    for B, H, hd, bs, maxb in _PAGED_AUDIT_SHAPES:
        for variant, page_dt in (("dense", "float32"), ("int8", "int8")):
            bufs = [
                {"name": "q", "block": (1, H, hd), "dtype": "float32"},
                {"name": "k_page", "block": (1, H, bs, hd),
                 "dtype": page_dt},
                {"name": "v_page", "block": (1, H, bs, hd),
                 "dtype": page_dt}]
            if variant == "int8":
                bufs += [{"name": "k_scales", "block": (1, H, bs, 1),
                          "dtype": "float32"},
                         {"name": "v_scales", "block": (1, H, bs, 1),
                          "dtype": "float32"}]
            bufs += [
                {"name": "out", "block": (1, H, hd), "dtype": "float32"},
                {"name": "m(scratch)", "block": (H, 1),
                 "dtype": "float32", "stream": False},
                {"name": "l(scratch)", "block": (H, 1),
                 "dtype": "float32", "stream": False},
                {"name": "acc(scratch)", "block": (H, hd),
                 "dtype": "float32", "stream": False}]
            entries.append({
                "kernel": f"tpp.paged_attention[{variant},B{B}xH{H}x"
                          f"{hd},bs{bs}x{maxb}]",
                "op": "paged_attention",
                "in_dtype": page_dt, "acc_dtype": "float32",
                "matmul": True,
                "grid": {"b": (B, 1), "j": (maxb, 1)},
                "buffers": bufs})
    return entries


# ---------------------------------------------------------------------------
# paged attention (the FLAGS_paged_kv decode kernel, ISSUE 18)
# ---------------------------------------------------------------------------

#: bundled paged_attention audit shapes: (B, H, hd, bs, maxb) — a
#: v5e-class serving point (128-lane head dim, 32-deep blocks so the
#: int8 page variant meets its 32-row sublane tile too)
_PAGED_AUDIT_SHAPES = ((16, 8, 128, 32, 16),)


def _paged_attention_kernel(tables_ref, lens_ref, *refs, bs, maxb, scale,
                            quantized):
    """One (b, j) grid step of the block-table decode attention: the
    scalar-prefetched table picked THIS j's physical frame (the K/V
    BlockSpec index_map reads tables_ref before the body runs), so the
    body only flash-accumulates one [KVh, bs, hd] block into the online
    softmax state (m/l/acc scratch, f32)."""
    import jax.experimental.pallas as pl

    idx = 0
    q_ref = refs[idx]; idx += 1
    k_ref = refs[idx]; idx += 1
    v_ref = refs[idx]; idx += 1
    ks_ref = vs_ref = None
    if quantized:
        ks_ref = refs[idx]; idx += 1
        vs_ref = refs[idx]; idx += 1
    o_ref = refs[idx]; idx += 1
    m_ref, l_ref, acc_ref = refs[idx], refs[idx + 1], refs[idx + 2]
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)             # [H, hd]
    k = k_ref[0].astype(jnp.float32)             # [KVh, bs, hd]
    v = v_ref[0].astype(jnp.float32)
    if quantized:                                # int8 pages: row codec
        k = k * ks_ref[0].astype(jnp.float32)
        v = v * vs_ref[0].astype(jnp.float32)
    s = jnp.einsum("hd,hcd->hc", q, k,
                   preferred_element_type=jnp.float32) * scale
    col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
    s = jnp.where(col < lens_ref[b], s, -jnp.inf)
    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # a fully-masked block keeps m at -inf; substitute 0 so the exps
    # below see finite-minus-finite (they all collapse to exp(-inf)=0)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    alpha = jnp.exp(m_prev - m_safe)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.einsum(
        "hc,hcd->hd", p, v, preferred_element_type=jnp.float32)
    m_ref[...] = m_new

    @pl.when(j == maxb - 1)
    def _writeback():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _build_paged_attention(dtype, shape_key, quantized):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    H, hd, bs, maxb = shape_key
    interpret = not _on_tpu()
    scale = 1.0 / (hd ** 0.5)

    def call(q, kp, vp, tables, lengths, k_scales=None, v_scales=None):
        B = q.shape[0]
        kern = functools.partial(
            _paged_attention_kernel, bs=bs, maxb=maxb, scale=scale,
            quantized=quantized)
        # the block table is the scalar-prefetch payload: the K/V specs'
        # index_map picks each step's PHYSICAL frame from it
        in_specs = [
            pl.BlockSpec((1, H, hd), lambda b, j, t, n: (b, 0, 0)),
            pl.BlockSpec((1, H, bs, hd),
                         lambda b, j, t, n: (t[b, j], 0, 0, 0)),
            pl.BlockSpec((1, H, bs, hd),
                         lambda b, j, t, n: (t[b, j], 0, 0, 0)),
        ]
        args = [q, kp, vp]
        if quantized:
            in_specs += [
                pl.BlockSpec((1, H, bs, 1),
                             lambda b, j, t, n: (t[b, j], 0, 0, 0)),
                pl.BlockSpec((1, H, bs, 1),
                             lambda b, j, t, n: (t[b, j], 0, 0, 0)),
            ]
            args += [k_scales, v_scales]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, maxb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, H, hd),
                                   lambda b, j, t, n: (b, 0, 0)),
            scratch_shapes=[pltpu.VMEM((H, 1), jnp.float32),
                            pltpu.VMEM((H, 1), jnp.float32),
                            pltpu.VMEM((H, hd), jnp.float32)],
        )
        return pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, H, hd), q.dtype),
            interpret=interpret,
        )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *args)

    return call


def paged_attention(q, kp, vp, tables, lengths, k_scales=None,
                    v_scales=None):
    """Block-table decode attention (one layer, one query per row).

    ``q`` [B, H, hd]; ``kp``/``vp`` [NB, H, bs, hd] physical KV frames;
    ``tables`` int [B, maxb] frame indices; ``lengths`` int [B] — row b
    attends columns ``0..lengths[b]-1`` of its logical cache. K/V blocks
    are gathered BY TABLE INDEX through scalar-prefetched BlockSpec
    index maps (never materializing the dense cache) and folded into an
    online-softmax f32 accumulator per row — the flash recipe over
    paged storage. With ``k_scales``/``v_scales`` ([NB, H, bs, 1] f32)
    the frames hold int8 pages (distributed/compress.py row codec) and
    dequantize on load; outputs then carry the codec's declared band vs
    the dense reference (:func:`paged_attention_ref` pins both paths)."""
    B, H, hd = q.shape
    NB, Hk, bs, hd_k = kp.shape
    if Hk != H or hd_k != hd:
        raise ValueError(
            f"paged_attention serves H == KVh (got q heads {H}, kv heads "
            f"{Hk}) and matching head dim (got {hd} vs {hd_k}) — grouped "
            "queries reshape outside the kernel")
    maxb = tables.shape[1]
    quantized = k_scales is not None
    if quantized != (v_scales is not None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    shape_key = (H, hd, bs, maxb)
    variant = "int8" if quantized else "dense"
    entry = _kernel_entry(
        f"paged_attention|{variant}", q.dtype, shape_key,
        lambda: _build_paged_attention(q.dtype, shape_key, quantized))
    item = jnp.dtype(q.dtype).itemsize
    page_item = 1 if quantized else jnp.dtype(kp.dtype).itemsize
    T = maxb * bs
    _note_call(entry, "paged_attention",
               4.0 * B * H * T * hd,
               (2 * B * H * hd * item              # q + out
                + 2 * B * maxb * H * bs * hd * page_item  # gathered pages
                + B * maxb * 4 + B * 4))           # tables + lengths
    return entry["fn"](q, kp, vp, tables, lengths, k_scales, v_scales)


def paged_attention_ref(q, kp, vp, tables, lengths, k_scales=None,
                        v_scales=None):
    """Pure-lax reference for :func:`paged_attention`: gather the pool
    through the tables into the dense layout, plain masked softmax
    attention in f32. The kernel must match within the declared band
    (f32 pages: online-softmax reassociation only; int8 pages add the
    row codec's quantization band)."""
    B, H, hd = q.shape
    maxb = tables.shape[1]
    bs = kp.shape[2]

    def dense(pool, scales):
        g = pool[tables].astype(jnp.float32)     # [B, maxb, H, bs, hd]
        if scales is not None:
            g = g * scales[tables].astype(jnp.float32)
        g = jnp.transpose(g, (0, 2, 1, 3, 4))
        return g.reshape(B, H, maxb * bs, hd)

    k = dense(kp, k_scales)
    v = dense(vp, v_scales)
    s = jnp.einsum("bhd,bhTd->bhT", q.astype(jnp.float32), k) \
        * (1.0 / (hd ** 0.5))
    cols = jnp.arange(maxb * bs)[None, None, :]
    s = jnp.where(cols < lengths[:, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - jnp.where(jnp.isfinite(m), m, 0.0))
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(denom == 0.0, 1.0, denom)
    return jnp.einsum("bhT,bhTd->bhd", p, v).astype(q.dtype)


# ---------------------------------------------------------------------------
# activations (used inside kernel bodies — elementwise, K-block safe)
# ---------------------------------------------------------------------------

_ACTS = ("none", "gelu", "gelu_tanh", "relu")


def _apply_act(x, act):
    if act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    if act == "relu":
        return jnp.maximum(x, 0.0)
    return x


def _check_act(act):
    if act not in _ACTS:
        raise ValueError(f"act must be one of {_ACTS}, got {act!r}")


# ---------------------------------------------------------------------------
# matmul-accumulate (+ optional LN prologue / input act / bias+act epilogue)
# ---------------------------------------------------------------------------


def _matmul_kernel(*refs, k_steps, has_bias, ln_prologue, in_act, act):
    """One (i, j, ki) grid step: acc += f(x_blk) @ w_blk, with f the
    optional LN-normalize or input activation; bias + epilogue act land
    on the final K step's writeback."""
    import jax.experimental.pallas as pl

    idx = 0
    x_ref = refs[idx]; idx += 1
    if ln_prologue:
        g_ref = refs[idx]; idx += 1
        b2_ref = refs[idx]; idx += 1
    w_ref = refs[idx]; idx += 1
    bias_ref = None
    if has_bias:
        bias_ref = refs[idx]; idx += 1
    o_ref = refs[idx]; idx += 1
    acc_ref = refs[idx]
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    if ln_prologue:
        # fp32 row stats over the FULL row (bk == K by construction)
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean((x - mu) * (x - mu), axis=-1, keepdims=True)
        x = (x - mu) * jax.lax.rsqrt(var + _LN_EPS)
        x = x * g_ref[...].astype(jnp.float32) \
            + b2_ref[...].astype(jnp.float32)
    x = _apply_act(x, in_act)
    acc_ref[...] += jnp.dot(x, w_ref[...].astype(jnp.float32),
                            preferred_element_type=jnp.float32)

    @pl.when(ki == k_steps - 1)
    def _epilogue():
        acc = acc_ref[...]
        if has_bias:
            acc = acc + bias_ref[...].astype(jnp.float32)
        o_ref[...] = _apply_act(acc, act).astype(o_ref.dtype)


def _build_matmul(dtype, block, has_bias, ln_prologue, in_act, act):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bm, bn, bk = block
    interpret = not _on_tpu()

    def call(*call_args):
        # kernel-order args: x [, gamma, beta], w [, bias]
        it = iter(call_args)
        x = next(it)
        gamma = beta = None
        if ln_prologue:
            gamma, beta = next(it), next(it)
        w = next(it)
        bias = next(it) if has_bias else None
        m, k = x.shape
        n = w.shape[1]
        k_steps = k // bk
        kern = functools.partial(_matmul_kernel, k_steps=k_steps,
                                 has_bias=has_bias,
                                 ln_prologue=ln_prologue,
                                 in_act=in_act, act=act)
        in_specs = [pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki))]
        args = [x]
        if ln_prologue:
            in_specs += [
                pl.BlockSpec((1, bk), lambda i, j, ki: (0, ki)),
                pl.BlockSpec((1, bk), lambda i, j, ki: (0, ki)),
            ]
            args += [gamma.reshape(1, k), beta.reshape(1, k)]
        in_specs.append(pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)))
        args.append(w)
        if has_bias:
            in_specs.append(pl.BlockSpec((1, bn),
                                         lambda i, j, ki: (0, j)))
            args.append(bias.reshape(1, n))
        return pl.pallas_call(
            kern,
            grid=(m // bm, n // bn, k_steps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
            out_shape=jax.ShapeDtypeStruct((m, n), dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            interpret=interpret,
        )(*args)

    return call


def matmul(x, w, bias=None, in_act="none", act="none", block=None,
           _op="matmul"):
    """Blocked matmul-accumulate: ``act(in_act(x) @ w + bias)``.
    x [m, k], w [k, n]; block=(bm, bn, bk) (auto-picked if None —
    raises when the shapes don't tile; check :func:`supported_2d`)."""
    _check_act(in_act), _check_act(act)
    m, k = x.shape
    n = w.shape[1]
    block = block or supported_2d(m, k, n, x.dtype)
    if block is None:
        raise ValueError(
            f"tpp.matmul cannot tile [{m},{k}]@[{k},{n}] {x.dtype} — "
            "gate on supported_2d() and fall back to the dense path")
    key_op = (f"{_op}|bias={bias is not None}|in={in_act}|ep={act}")
    entry = _kernel_entry(key_op, x.dtype, block, lambda: _build_matmul(
        x.dtype, block, bias is not None, False, in_act, act))
    item = jnp.dtype(x.dtype).itemsize
    _note_call(entry, _op, 2.0 * m * k * n,
               (m * k + k * n + m * n + (n if bias is not None else 0))
               * item)
    args = (x, w) + ((bias,) if bias is not None else ())
    return entry["fn"](*args)


# ---------------------------------------------------------------------------
# bias + activation (VPU row blocks)
# ---------------------------------------------------------------------------


def _bias_act_kernel(x_ref, b_ref, o_ref, *, act):
    x = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = _apply_act(x, act).astype(o_ref.dtype)


def _build_bias_act(dtype, block, act):
    from jax.experimental import pallas as pl

    bm, bn = block
    interpret = not _on_tpu()

    def call(x, bias):
        m, n = x.shape
        return pl.pallas_call(
            functools.partial(_bias_act_kernel, act=act),
            grid=(m // bm, n // bn),
            in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                      pl.BlockSpec((1, bn), lambda i, j: (0, j))],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((m, n), dtype),
            interpret=interpret,
        )(x, bias.reshape(1, n))

    return call


def bias_act(x, bias, act="gelu"):
    """Fused ``act(x + bias)`` over [bm, bn] blocks. x [m, n], bias [n]."""
    _check_act(act)
    m, n = x.shape
    bm, bn = pick_block(m), pick_block(n)
    if bm is None or bn is None:
        raise ValueError(f"tpp.bias_act cannot tile [{m},{n}]")
    entry = _kernel_entry(f"bias_act|{act}", x.dtype, (bm, bn),
                          lambda: _build_bias_act(x.dtype, (bm, bn), act))
    item = jnp.dtype(x.dtype).itemsize
    _note_call(entry, "bias_act", 2.0 * m * n, (2 * m * n + n) * item)
    return entry["fn"](x, bias)


# ---------------------------------------------------------------------------
# softmax row-pass
# ---------------------------------------------------------------------------


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    x = x - jnp.max(x, axis=-1, keepdims=True)
    ex = jnp.exp(x)
    o_ref[...] = (ex / jnp.sum(ex, axis=-1, keepdims=True)
                  ).astype(o_ref.dtype)


def _build_softmax(dtype, block):
    from jax.experimental import pallas as pl

    bm = block[0]
    interpret = not _on_tpu()

    def call(x):
        m, n = x.shape
        return pl.pallas_call(
            _softmax_kernel,
            grid=(m // bm,),
            in_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, n), dtype),
            interpret=interpret,
        )(x)

    return call


def softmax_rows(x):
    """Stable row softmax over [bm, N] blocks (full row per grid step;
    fp32 max/sum internally). x [m, n]."""
    m, n = x.shape
    bm = pick_block(m)
    if bm is None:
        raise ValueError(f"tpp.softmax_rows cannot tile {m} rows")
    entry = _kernel_entry("softmax_rows", x.dtype, (bm, n),
                          lambda: _build_softmax(x.dtype, (bm, n)))
    item = jnp.dtype(x.dtype).itemsize
    _note_call(entry, "softmax_rows", 5.0 * m * n, 2 * m * n * item)
    return entry["fn"](x)


# ---------------------------------------------------------------------------
# masked reduce
# ---------------------------------------------------------------------------


def _masked_reduce_kernel(x_ref, m_ref, o_ref, *, kind):
    x = x_ref[...].astype(jnp.float32)
    keep = m_ref[...] != 0
    if kind == "sum":
        o_ref[...] = jnp.sum(jnp.where(keep, x, 0.0), axis=-1,
                             keepdims=True).astype(o_ref.dtype)
    else:
        o_ref[...] = jnp.max(jnp.where(keep, x, -jnp.inf), axis=-1,
                             keepdims=True).astype(o_ref.dtype)


def _build_masked_reduce(dtype, block, kind):
    from jax.experimental import pallas as pl

    bm = block[0]
    interpret = not _on_tpu()

    def call(x, mask):
        m, n = x.shape
        return pl.pallas_call(
            functools.partial(_masked_reduce_kernel, kind=kind),
            grid=(m // bm,),
            in_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0)),
                      pl.BlockSpec((bm, n), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, 1), dtype),
            interpret=interpret,
        )(x, mask)

    return call


def masked_reduce(x, mask, kind="sum"):
    """Row-wise masked ``sum``/``max``: reduce x[i, j] over columns
    where mask[i, j] != 0. x [m, n] -> [m, 1]."""
    if kind not in ("sum", "max"):
        raise ValueError(f"kind must be sum|max, got {kind!r}")
    m, n = x.shape
    bm = pick_block(m)
    if bm is None:
        raise ValueError(f"tpp.masked_reduce cannot tile {m} rows")
    entry = _kernel_entry(f"masked_reduce|{kind}", x.dtype, (bm, n),
                          lambda: _build_masked_reduce(x.dtype, (bm, n),
                                                       kind))
    item = jnp.dtype(x.dtype).itemsize
    _note_call(entry, "masked_reduce", float(m * n),
               (2 * m * n + m) * item)
    return entry["fn"](x, mask.astype(jnp.int32))


# ---------------------------------------------------------------------------
# ported op: layernorm -> matmul prologue (ln_matmul)
# ---------------------------------------------------------------------------


def _ln_matmul_ref(x, gamma, beta, w, bias):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
    xn = (x32 - mu) * jax.lax.rsqrt(var + _LN_EPS) * gamma + beta
    return (xn @ w.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _ln_matmul_fwd_kernel(x, gamma, beta, w, bias):
    m, k = x.shape
    n = w.shape[1]
    bm, bn = pick_block(m), pick_block(n)
    block = (bm, bn, k)   # LN stats need the full row: one K step
    entry = _kernel_entry("ln_matmul", x.dtype, block,
                          lambda: _build_matmul(x.dtype, block, True,
                                                True, "none", "none"))
    item = jnp.dtype(x.dtype).itemsize
    _note_call(entry, "ln_matmul", 2.0 * m * k * n + 8.0 * m * k,
               (m * k + k * n + m * n + 2 * k + n) * item)
    return entry["fn"](x, gamma, beta, w, bias)


@jax.custom_vjp
def ln_matmul(x, gamma, beta, w, bias):
    """Fused layernorm -> matmul prologue: ``LN(x; gamma, beta) @ w +
    bias`` with the normalized rows living only in VMEM. Differentiable
    (reference-math backward). Shapes: x [m, k], w [k, n]; m and n must
    tile (:func:`supported_2d` with bk == k)."""
    return _ln_matmul_fwd_kernel(x, gamma, beta, w, bias)


def _ln_matmul_vfwd(x, gamma, beta, w, bias):
    return _ln_matmul_fwd_kernel(x, gamma, beta, w, bias), \
        (x, gamma, beta, w, bias)


def _ln_matmul_vbwd(res, g):
    _, vjp = jax.vjp(_ln_matmul_ref, *res)
    return vjp(g)


ln_matmul.defvjp(_ln_matmul_vfwd, _ln_matmul_vbwd)


def ln_matmul_supported(m, k, n, dtype):
    """Tiling gate for the ln_matmul port (bk is pinned to k)."""
    return (str(dtype) in ("float32", "bfloat16")
            and pick_block(m) is not None and pick_block(n) is not None)


# ---------------------------------------------------------------------------
# ported op: the GPT fused MLP block (fused_mlp)
# ---------------------------------------------------------------------------


def _mlp_ref(x, w1, b1, w2, b2, approx):
    h = jax.nn.gelu((x.astype(jnp.float32) @ w1.astype(jnp.float32)
                     + b1.astype(jnp.float32)), approximate=approx)
    return (h @ w2.astype(jnp.float32)
            + b2.astype(jnp.float32)).astype(x.dtype)


def _mlp_fwd_kernels(x, w1, b1, w2, b2, approx):
    act = "gelu_tanh" if approx else "gelu"
    # leg 1: x @ w1 + b1 (pre-activation hidden — the one HBM
    # intermediate); leg 2: gelu fused into the second matmul's x-block
    # load, projection + bias on the way out
    h = matmul(x, w1, bias=b1, _op="fused_mlp")
    return matmul(h, w2, bias=b2, in_act=act, _op="fused_mlp")


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_mlp(x, w1, b1, w2, b2, approx=False):
    """The GPT MLP block ``(gelu(x @ w1 + b1)) @ w2 + b2`` through two
    blocked kernels — gelu fused into the second matmul's block loads.
    Differentiable (reference-math backward). x [m, k]; both matmuls
    must tile (:func:`supported_2d`)."""
    return _mlp_fwd_kernels(x, w1, b1, w2, b2, approx)


def _mlp_vfwd(x, w1, b1, w2, b2, approx):
    return _mlp_fwd_kernels(x, w1, b1, w2, b2, approx), \
        (x, w1, b1, w2, b2)


def _mlp_vbwd(approx, res, g):
    _, vjp = jax.vjp(
        lambda x, w1, b1, w2, b2: _mlp_ref(x, w1, b1, w2, b2, approx),
        *res)
    return vjp(g)


fused_mlp.defvjp(_mlp_vfwd, _mlp_vbwd)


# ---------------------------------------------------------------------------
# the models/gpt.py hook
# ---------------------------------------------------------------------------


def gpt_block_mlp(x, ln, mlp):
    """The GPT block's MLP path ``fc2(gelu(fc1(LN(x))))`` through the
    two ported ops: ln_matmul covers ln2+fc1 (the layernorm->matmul
    prologue), the fused_mlp tail covers gelu+fc2. Takes the raw
    [b, s, h] array and the block's LayerNorm/GPTMLP layers; returns
    the [b, s, h] array, or None when the shapes/dtype don't tile (the
    caller falls back to the dense path)."""
    b, s, h = x.shape
    w1, b1 = mlp.fc1.weight._data, mlp.fc1.bias._data
    w2, b2 = mlp.fc2.weight._data, mlp.fc2.bias._data
    inter = w1.shape[1]
    m = b * s
    if not ln_matmul_supported(m, h, inter, x.dtype) \
            or supported_2d(m, inter, h, x.dtype) is None \
            or getattr(ln, "_epsilon", _LN_EPS) != _LN_EPS:
        return None
    act = "gelu_tanh" if getattr(mlp, "_gelu_approx", False) else "gelu"
    x2 = x.reshape(m, h)
    pre = ln_matmul(x2, ln.weight._data, ln.bias._data, w1, b1)
    out = _fused_tail(pre, w2, b2, act == "gelu_tanh")
    return out.reshape(b, s, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_tail(pre, w2, b2, approx):
    """gelu + projection half of the MLP block (the fused_mlp op
    applied after an ln_matmul prologue already produced the
    pre-activation hidden)."""
    act = "gelu_tanh" if approx else "gelu"
    return matmul(pre, w2, bias=b2, in_act=act, _op="fused_mlp")


def _fused_tail_ref(pre, w2, b2, approx):
    h = jax.nn.gelu(pre.astype(jnp.float32), approximate=approx)
    return (h @ w2.astype(jnp.float32)
            + b2.astype(jnp.float32)).astype(pre.dtype)


def _fused_tail_vfwd(pre, w2, b2, approx):
    act = "gelu_tanh" if approx else "gelu"
    return matmul(pre, w2, bias=b2, in_act=act, _op="fused_mlp"), \
        (pre, w2, b2)


def _fused_tail_vbwd(approx, res, g):
    _, vjp = jax.vjp(
        lambda pre, w2, b2: _fused_tail_ref(pre, w2, b2, approx), *res)
    return vjp(g)


_fused_tail.defvjp(_fused_tail_vfwd, _fused_tail_vbwd)
