"""Pallas greedy-NMS kernel for TPU (BASELINE.json config #5: detection post-proc).

Reference parity: the CUDA NMS kernels behind multiclass_nms
(paddle/fluid/operators/detection/multiclass_nms_op.cc) compute a pairwise-IoU bitmask
then greedily sweep it. TPU-native design: the whole problem (boxes sorted by score,
N <= ~4k) fits VMEM, so one kernel computes each row's IoU against all boxes with VPU
ops and runs the sequential greedy sweep in a fori_loop — zero HBM round-trips between
the O(N^2) IoU work and the O(N) suppression chain, where the XLA lax.scan fallback
re-reads the mask every step.

keep[i] = no kept j < i has IoU(i, j) > threshold (boxes pre-sorted by score desc).
"""
import functools

import jax
import jax.numpy as jnp

LANE = 128  # pad N to a lane multiple so [1, N] rows tile cleanly


def _nms_kernel(boxes_ref, thresh_ref, keep_ref, *, n_pad):
    """boxes_ref: [4, n_pad] f32 rows x1,y1,x2,y2 (score-desc order; pads are
    zero-area at the tail). keep_ref: [1, n_pad] int32.

    No dynamic indexing (unsupported in Mosaic lowering): box i's scalars are
    extracted with a lane-mask select + full reduction each sweep step — still
    O(N) VPU work per step, same order as the IoU row itself."""
    x1 = boxes_ref[0, :].reshape(1, n_pad)
    y1 = boxes_ref[1, :].reshape(1, n_pad)
    x2 = boxes_ref[2, :].reshape(1, n_pad)
    y2 = boxes_ref[3, :].reshape(1, n_pad)
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    thresh = thresh_ref[0, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n_pad), 1)

    keep_ref[...] = jnp.ones((1, n_pad), jnp.int32)

    def body(i, _):
        sel = lane == i

        def pick(row):
            return jnp.sum(jnp.where(sel, row, 0.0))

        bx1, by1, bx2, by2 = pick(x1), pick(y1), pick(x2), pick(y2)
        barea = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
        iw = jnp.maximum(jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0)
        inter = iw * ih
        iou = inter / jnp.maximum(area + barea - inter, 1e-9)  # [1, n_pad]

        kept = keep_ref[...]
        kept_i = jnp.sum(jnp.where(sel, kept, 0))
        # suppress every later box overlapping a *kept* box i
        supp = (iou > thresh) & (lane > i) & (kept_i > 0)
        keep_ref[...] = jnp.where(supp, 0, kept)
        return 0

    jax.lax.fori_loop(0, n_pad, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def nms_keep_mask_pallas(boxes, iou_threshold, interpret=False):
    """boxes: [N, 4] sorted by score desc. Returns keep mask [N] bool.

    Pads N up to a lane multiple; padded boxes are zero-area (IoU 0) so they
    never suppress real boxes.
    """
    from jax.experimental import pallas as pl

    n = boxes.shape[0]
    n_pad = ((n + LANE - 1) // LANE) * LANE
    boxes_p = jnp.zeros((n_pad, 4), jnp.float32).at[:n].set(
        boxes.astype(jnp.float32))
    thresh = jnp.full((1, 1), iou_threshold, jnp.float32)

    keep = pl.pallas_call(
        functools.partial(_nms_kernel, n_pad=n_pad),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(boxes_p.T, thresh)
    return keep[0, :n] > 0


# ---------------------------------------------------------------------------
# static audit manifest (analysis/pallas_audit.py, ISSUE 13)
# ---------------------------------------------------------------------------


def audit_manifest():
    """One entry at the supported() cap: the whole problem lives in VMEM
    (no grid streaming), so the audit checks the worst-case residency."""
    n_pad = 8192   # supported() upper bound, already lane-aligned
    return [{
        "kernel": f"nms.sweep[n={n_pad}]", "op": "nms",
        "in_dtype": "float32", "matmul": False,
        "grid": {"n": (n_pad, LANE)},
        "buffers": [
            {"name": "boxes", "block": (4, n_pad), "dtype": "float32",
             "stream": False},
            {"name": "thresh", "block": (1, 1), "dtype": "float32",
             "stream": False},
            {"name": "keep", "block": (1, n_pad), "dtype": "int32",
             "stream": False}]}]


_DISABLED = [False]  # session-wide negative cache after a lowering failure


def mark_unsupported():
    _DISABLED[0] = True


def supported(n_boxes):
    """VMEM budget: [n_pad, 4] boxes + a few [1, n_pad] rows — generous cap."""
    if _DISABLED[0]:
        return False
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        on_tpu = False
    return on_tpu and n_boxes <= 8192
