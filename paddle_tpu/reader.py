"""paddle.reader decorators (python/paddle/reader/decorator.py parity).

The fluid-era data pipeline composes plain python generators; nothing here
touches the device, so these are direct ports of the *semantics* (buffering
through queues/threads collapses to plain generators — the TPU input pipeline
proper lives in paddle_tpu.io.DataLoader)."""
import itertools
import random as _random

__all__ = ["cache", "map_readers", "buffered", "compose", "chain", "shuffle",
           "ComposeNotAligned", "firstn", "xmap_readers",
           "multiprocess_reader"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache all samples in memory on first pass."""
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        yield from all_data

    return cached


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def chained():
        for r in readers:
            yield from r()

    return chained


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if not check_alignment:
            # reference decorator.py: plain zip stops at the shortest reader
            for parts in zip(*rs):
                yield sum((make_tuple(p) for p in parts), ())
            return
        for parts in itertools.zip_longest(*rs):
            if any(p is None for p in parts):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(p) for p in parts), ())

    return composed


def buffered(reader, size):
    """Reference buffers through a thread+queue; the semantics (read-ahead of
    `size` samples) reduce to eager chunking for a single-host pipeline."""
    def buffered_reader():
        it = reader()
        while True:
            chunk = list(itertools.islice(it, size))
            if not chunk:
                return
            yield from chunk

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader. process_num/buffer_size are accepted for
    API parity; mapping runs in-process (XLA host callbacks own the threads)."""
    def xreader():
        for s in reader():
            yield mapper(s)

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    return chain(*readers)
