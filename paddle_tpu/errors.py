"""Structured error taxonomy.

Reference parity: paddle/fluid/platform/enforce.h (PADDLE_ENFORCE* macros) and
errors.{h,cc} / error_codes.proto error-code taxonomy. Python-side enforce raises typed
exceptions with the failing expression context instead of aborting.
"""


class EnforceNotMet(RuntimeError):
    pass


class InvalidArgumentError(ValueError):
    pass


class NotFoundError(KeyError):
    pass


class OutOfRangeError(IndexError):
    pass


class AlreadyExistsError(RuntimeError):
    pass


class PermissionDeniedError(RuntimeError):
    pass


class UnimplementedError(NotImplementedError):
    pass


class UnavailableError(RuntimeError):
    pass


class PreconditionNotMetError(RuntimeError):
    pass


class ExecutionTimeoutError(RuntimeError):
    pass


def enforce(cond, msg="", exc=EnforceNotMet):
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceNotMet(f"Expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg=""):
    if not a > b:
        raise EnforceNotMet(f"Expected {a!r} > {b!r}. {msg}")


def enforce_shape_match(shape_a, shape_b, msg=""):
    if list(shape_a) != list(shape_b):
        raise InvalidArgumentError(f"Shape mismatch {shape_a} vs {shape_b}. {msg}")
