"""Gradient compression for the collective chokepoint (docs/DISTRIBUTED.md).

EQuARX-style quantized all-reduce (arXiv:2506.17615): on TPU slices the
collective stream IS the scaling budget, and for data-parallel training
almost all of it is one op — the per-step gradient all-reduce. This module
shrinks that op's wire format to int8 while keeping the *accumulation* in
float32:

- :func:`quantize` / :func:`dequantize` — per-block symmetric int8 with a
  float32 block-max scale and **stochastic rounding** driven by a
  deterministic PRNG key (threaded from the train step's key, so a run is
  reproducible under ``paddle.seed`` and two ranks never share rounding
  noise);
- :func:`quantized_all_reduce` — the two-phase exchange: each rank
  quantizes its local tensor, the **reduce-scatter phase** moves int8
  shards (``lax.all_to_all`` of the quantized payload + scales), every
  rank **dequantizes and accumulates its shard in float32**, re-quantizes
  the reduced shard, and the **all-gather phase** moves int8 back out.
  Accumulation never happens in int8 — the only rounding is the two
  quantize steps, never a saturating integer sum;
- error feedback — :func:`quantized_all_reduce_ef` also returns the local
  quantize-dequantize round-trip so the caller can carry
  ``residual = input - roundtrip`` into the next step
  (``SpmdTrainer`` rides it on the optimizer-state pytree as
  ``__qar_residual__``): the quantization error is re-injected instead of
  lost, which is what keeps the loss curve on top of the fp32 one.

Non-finite safety: a NaN/Inf element poisons its block's *scale* (float32,
NaN-preserving), so the dequantized block comes back non-finite — a
poisoned step stays loud exactly like the uncompressed path, and the int8
payload (whose cast from NaN is undefined) never decides the result.

Byte accounting rides the collective chokepoint's discipline
(:func:`paddle_tpu.distributed.collective.record_compressed`):
``collective_bytes_total{op=...}`` counts the **wire** encoding,
``collective_bytes_saved_total{op=...}`` the fp32 bytes it displaced, and
a ``collective/quantized`` span tags each traced call. This module is
imported lazily — a trainer with ``FLAGS_quantized_allreduce`` and
``FLAGS_shard_weight_update`` unset never loads it
(tests/test_compress_gate.py pins the subprocess form).
"""
import jax
import jax.numpy as jnp

from .. import monitor as _monitor

__all__ = [
    "DEFAULT_BLOCK", "SUPPORTED_BITS", "quantize", "dequantize",
    "quantize_dequantize", "quantize_rows", "dequantize_rows",
    "quantized_all_reduce",
    "quantized_all_reduce_ef", "padded_size", "wire_bytes", "error_gauge",
]

#: quantization block length (elements sharing one float32 scale). 256
#: keeps the scale overhead at 4/256 ≈ 1.6% of the int8 payload while
#: staying fine-grained enough that one outlier only poisons its block.
DEFAULT_BLOCK = 256

#: wire formats this build supports. 8 = int8 payload; sub-byte packing
#: (4-bit nibbles) is future work — the flag validates loudly instead of
#: silently shipping fp32.
SUPPORTED_BITS = (8,)


def _check_bits(bits):
    if int(bits) not in SUPPORTED_BITS:
        raise ValueError(
            f"quantized all-reduce supports bits in {SUPPORTED_BITS} "
            f"(int8 wire format), got {bits!r}")
    return int(bits)


def padded_size(n, block=DEFAULT_BLOCK, world=1):
    """Elements after padding `n` up to a whole number of blocks per
    rank-shard: the padded length is a multiple of ``block * world`` so
    the reduce-scatter phase hands every rank whole blocks."""
    unit = int(block) * int(world)
    return -(-int(n) // unit) * unit


def wire_bytes(n, bits=8, block=DEFAULT_BLOCK, world=1):
    """Bytes of ONE quantized payload (int8 data + float32 block scales)
    for an `n`-element tensor — the chokepoint's per-op accounting unit
    (an fp32 all-reduce likewise counts its payload once, not per ring
    hop; see docs/DISTRIBUTED.md)."""
    bits = _check_bits(bits)
    padded = padded_size(n, block=block, world=world)
    return padded * bits // 8 + (padded // int(block)) * 4


# -- core quantize / dequantize -----------------------------------------------

def _stochastic_round(v, key):
    """Unbiased round: floor(v) + Bernoulli(frac(v)). E[out] == v."""
    lo = jnp.floor(v)
    frac = v - lo
    u = jax.random.uniform(key, v.shape, dtype=v.dtype)
    return lo + (frac > u).astype(v.dtype)


def quantize(flat, key, bits=8, block=DEFAULT_BLOCK):
    """Per-block symmetric stochastic quantize of a 1-D float32 array
    whose length is a multiple of `block`. Returns ``(q, scales)`` with
    ``q`` int8 of `flat`'s length and ``scales`` float32 of length
    ``len(flat) // block`` (the block-max / 127 step size). A zero block
    quantizes to exact zeros; a non-finite element makes its block's
    scale non-finite (loud on dequantize)."""
    bits = _check_bits(bits)
    qmax = float(2 ** (bits - 1) - 1)
    blocks = flat.reshape(-1, int(block)).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1) / qmax          # [nblocks]
    safe = jnp.where(scale > 0, scale, 1.0)
    q = _stochastic_round(blocks / safe[:, None], key)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    return q.reshape(-1), scale.astype(jnp.float32)


def dequantize(q, scales, block=DEFAULT_BLOCK):
    """Inverse of :func:`quantize`: int8 payload × its block scale."""
    return (q.astype(jnp.float32).reshape(-1, int(block))
            * scales[:, None].astype(jnp.float32)).reshape(-1)


def quantize_rows(x):
    """Per-last-axis-row symmetric int8 quantize for TRANSFER payloads
    (stage edges): returns ``(q, scales)`` with ``q`` int8 of `x`'s
    shape and ``scales`` float32 of shape ``x.shape[:-1] + (1,)`` —
    exactly the encoded form a ``quantizable`` ``HANDOFF_SCHEMA`` leaf
    declares (analysis/handoff_schema.py).

    Unlike :func:`quantize` (gradient reduction) this rounds to NEAREST,
    deterministically: a transfer is decoded once by one consumer, so
    unbiasedness across repetitions buys nothing, while determinism buys
    schedule-independent bit-exact replay (chaos drains, parity pins). A
    zero row encodes to exact zeros; a non-finite element poisons its
    row's scale — loud at decode, never silently clipped."""
    a = jnp.asarray(x)
    scale = (jnp.max(jnp.abs(a), axis=-1, keepdims=True)
             .astype(jnp.float32) / 127.0)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(a.astype(jnp.float32) / safe),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_rows`: int8 rows × their row scale,
    cast back to the payload's declared `dtype`. Zero-scale rows decode
    to exact zeros."""
    return (q.astype(jnp.float32) * scales.astype(jnp.float32)).astype(
        dtype)


def quantize_dequantize(x, key, bits=8, block=DEFAULT_BLOCK):
    """One local quantization round-trip (pad → quantize → dequantize →
    trim), preserving `x`'s shape; float32 result. This is what a
    world-size-1 'all-reduce' of the compressed path computes — callers
    see the real quantization error even without a mesh."""
    flat = jnp.asarray(x).astype(jnp.float32).ravel()
    n = flat.shape[0]
    padded = padded_size(n, block=block)
    flat = jnp.pad(flat, (0, padded - n))
    q, s = quantize(flat, key, bits=bits, block=block)
    return dequantize(q, s, block=block)[:n].reshape(jnp.shape(x))


# -- the two-phase quantized all-reduce ---------------------------------------

def _exchange_reduce(flat, axis_name, key, bits, block):
    """Phase 1 on a padded 1-D float32 array: quantize the local tensor,
    all_to_all the int8 shards + scales, dequant-accumulate this rank's
    shard in float32. Returns ``(shard_sum, local_roundtrip)`` — the
    rank's float32 slice of the cross-replica SUM, and the local
    dequantized round-trip (for error feedback)."""
    world = jax.lax.psum(1, axis_name)
    q, s = quantize(flat, jax.random.fold_in(key, jax.lax.axis_index(axis_name)),
                    bits=bits, block=block)
    local_rt = dequantize(q, s, block=block)
    if world == 1:
        return local_rt, local_rt
    shard = flat.shape[0] // world
    q_peers = jax.lax.all_to_all(q.reshape(world, shard), axis_name,
                                 split_axis=0, concat_axis=0)
    s_peers = jax.lax.all_to_all(s.reshape(world, shard // int(block)),
                                 axis_name, split_axis=0, concat_axis=0)
    # float32 accumulation of the dequantized peer shards — the int8
    # payload is never summed
    acc = jnp.sum(q_peers.astype(jnp.float32).reshape(world, -1, int(block))
                  * s_peers[:, :, None].astype(jnp.float32), axis=0)
    return acc.reshape(-1), local_rt


def _gather_full(shard_sum, axis_name, key, bits, block):
    """Phase 2: re-quantize the reduced shard and all-gather the int8
    form; every rank dequantizes the identical full result."""
    world = jax.lax.psum(1, axis_name)
    if world == 1:
        return shard_sum
    idx = jax.lax.axis_index(axis_name)
    q2, s2 = quantize(shard_sum,
                      jax.random.fold_in(jax.random.fold_in(key, idx), 1),
                      bits=bits, block=block)
    qg = jax.lax.all_gather(q2, axis_name, tiled=True)
    sg = jax.lax.all_gather(s2, axis_name, tiled=True)
    return dequantize(qg, sg, block=block)


def quantized_all_reduce_ef(x, axis_name, key, bits=8, block=DEFAULT_BLOCK,
                            mean=False, meter=None):
    """The full quantized all-reduce with the error-feedback hook:
    returns ``(reduced, local_roundtrip)`` — the float32 cross-replica
    SUM (or mean) of `x` in `x`'s shape, and ``dequantize(quantize(x))``
    so the caller can carry ``x - local_roundtrip`` as next step's
    residual. Must run under a mesh axis (shard_map/pmap/vmap) named
    `axis_name`. `meter` optionally names the op for the chokepoint's
    byte accounting (None = caller meters)."""
    bits = _check_bits(bits)
    world = jax.lax.psum(1, axis_name)
    flat = jnp.asarray(x).astype(jnp.float32).ravel()
    n = flat.shape[0]
    padded = padded_size(n, block=block, world=world)
    if meter:
        from . import collective as _coll

        _coll.record_compressed(
            meter, logical_nbytes=n * 4,
            wire_nbytes=wire_bytes(n, bits=bits, block=block, world=world))
    flat = jnp.pad(flat, (0, padded - n))
    shard_sum, local_rt = _exchange_reduce(flat, axis_name, key, bits, block)
    full = _gather_full(shard_sum, axis_name, key, bits, block)
    out = full[:n]
    if mean:
        out = out / world
    return out.reshape(jnp.shape(x)), local_rt[:n].reshape(jnp.shape(x))


def quantized_all_reduce(x, axis_name, key=None, bits=8,
                         block=DEFAULT_BLOCK, mean=False, meter=None):
    """Drop-in quantized ``psum``/``pmean`` over `axis_name` (the public
    form ROADMAP item 2 names): int8 wire format, float32 accumulation,
    stochastic rounding under `key` (derived from the global generator
    when omitted — pass a key under jit for per-step randomness).

    Differentiable with a straight-through estimator: the backward pass
    treats the op as the exact sum it approximates (cotangent passes
    through unchanged, matching ``psum``'s replicated-cotangent rule), so
    ``federated_sum``-style callers can opt in without losing their
    gradient."""
    bits = _check_bits(bits)
    if key is None:
        from ..core.generator import default_generator

        key = default_generator().fold_in(0x514152)   # "QAR"

    @jax.custom_vjp
    def _qar(v):
        out, _ = quantized_all_reduce_ef(v, axis_name, key, bits=bits,
                                         block=block, mean=mean, meter=meter)
        return out

    def _fwd(v):
        return _qar(v), None

    def _bwd(_, ct):
        return (ct,)

    _qar.defvjp(_fwd, _bwd)
    return _qar(jnp.asarray(x))


# -- lazy observability -------------------------------------------------------

_GAUGE = None


def error_gauge():
    """The ``quantize_error_norm`` gauge (lazy — no series until a
    compressed trainer actually fetches its banked error scalar)."""
    global _GAUGE
    if _GAUGE is None:
        _GAUGE = _monitor.gauge(
            "quantize_error_norm",
            "global L2 norm of the last step's gradient quantization "
            "error (the error-feedback residual that will be re-injected "
            "next step); fetched lazily via SpmdTrainer.stats() / "
            "quantize_error()")
    return _GAUGE
