"""fleet MultiSlotDataGenerator (python/paddle/distributed/fleet/data_generator/
data_generator.py parity): user subclasses generate_sample(); run_from_stdin /
run_from_memory emit MultiSlot-format lines the dataset/PS ingestion parses."""
import sys


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclasses implement generate_sample(line) -> iterator of "
            "(slot_name, values) lists")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, userdata):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            line_iter = self.generate_sample(line)
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                sys.stdout.write(self._gen_str(user_parsed_line))

    def run_from_memory(self):
        samples = []
        for user_parsed_line in self.generate_sample(None)():
            if user_parsed_line is None:
                continue
            samples.append(self._gen_str(user_parsed_line))
        for s in samples:
            sys.stdout.write(s)


class MultiSlotDataGenerator(DataGenerator):
    """Emits `slot:n v1 .. vn` per feature (int ids)."""

    def _gen_str(self, line):
        parts = []
        for name, values in line:
            parts.append(f"{len(values)}")
            parts.extend(str(int(v)) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Emits raw string tokens per slot (reference string variant)."""

    def _gen_str(self, line):
        parts = []
        for name, values in line:
            parts.append(f"{len(values)}")
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
