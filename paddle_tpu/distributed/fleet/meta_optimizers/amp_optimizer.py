"""AMP meta-optimizer (fleet/meta_optimizers/amp_optimizer.py parity).
On TPU: bf16 autocast needs no loss scaling; fp16 installs a scaled loss wrapper."""
from .meta_optimizer_base import MetaOptimizerBase


class AMPOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.amp

    def apply(self, trainer_kwargs, optimizer, strategy):
        cfg = strategy.amp_configs
        trainer_kwargs["amp_dtype"] = "float16" if cfg.use_pure_fp16 else cfg.dtype
        trainer_kwargs["amp_custom_white"] = list(cfg.custom_white_list)
        trainer_kwargs["amp_custom_black"] = list(cfg.custom_black_list)
        if cfg.dtype == "float16" or cfg.use_pure_fp16:
            trainer_kwargs["loss_scaling"] = cfg.init_loss_scaling
        return trainer_kwargs, optimizer
