"""Recompute meta-optimizer (fleet/meta_optimizers/recompute_optimizer.py parity);
activation checkpointing = jax.checkpoint on the forward (backward.py:725 analog)."""
from .meta_optimizer_base import MetaOptimizerBase


class RecomputeOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.recompute

    def apply(self, trainer_kwargs, optimizer, strategy):
        trainer_kwargs["recompute"] = True
        if strategy.recompute_configs.enable_offload:
            trainer_kwargs["remat_offload"] = True  # jax.checkpoint offload policy
        return trainer_kwargs, optimizer
