"""Recompute meta-optimizer (fleet/meta_optimizers/recompute_optimizer.py parity);
activation checkpointing = jax.checkpoint on the forward (backward.py:725 analog)."""
from .meta_optimizer_base import MetaOptimizerBase


class RecomputeOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.recompute

    def apply(self, trainer_kwargs, optimizer, strategy):
        from ...spmd import _REMAT_POLICIES

        trainer_kwargs["recompute"] = True
        cfg = strategy.recompute_configs
        if cfg.enable_offload:
            trainer_kwargs["remat_offload"] = True  # jax.checkpoint offload policy
        elif cfg.checkpoints:
            # reference checkpoints name TENSORS to save; the TPU analog is a
            # jax.checkpoint policy — accept a policy name in the list
            # (e.g. recompute_configs.checkpoints = ["dots"])
            named = [c for c in cfg.checkpoints if c in _REMAT_POLICIES]
            if named:
                trainer_kwargs["recompute_policy"] = named[0]
            else:
                import warnings

                # a reference-style tensor-name list would otherwise be
                # silently dropped (full remat, no signal)
                warnings.warn(
                    "recompute_configs.checkpoints entries "
                    f"{list(cfg.checkpoints)!r} name no known remat policy "
                    f"({sorted(_REMAT_POLICIES)}); reference-style tensor "
                    "names are not supported — falling back to full "
                    "rematerialization")
        return trainer_kwargs, optimizer
