"""Gradient merge meta-optimizer (fleet/meta_optimizers/gradient_merge_optimizer.py
parity) — k-step micro-batch accumulation via the trainer's lax.scan."""
from .meta_optimizer_base import MetaOptimizerBase


class GradientMergeOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.gradient_merge

    def apply(self, trainer_kwargs, optimizer, strategy):
        cfg = strategy.gradient_merge_configs
        trainer_kwargs["accumulate_steps"] = max(
            trainer_kwargs.get("accumulate_steps", 1), cfg.k_steps)
        trainer_kwargs["grad_merge_avg"] = cfg.avg
        return trainer_kwargs, optimizer
