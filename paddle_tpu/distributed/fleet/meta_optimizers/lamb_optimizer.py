"""LAMB meta-optimizer (fleet/meta_optimizers/lamb_optimizer.py parity)."""
from .meta_optimizer_base import MetaOptimizerBase


class LambOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.lamb

    def apply(self, trainer_kwargs, optimizer, strategy):
        from .... import optimizer as opt_mod

        if not isinstance(optimizer, opt_mod.Lamb):
            cfg = strategy.lamb_configs
            ex = set(cfg.exclude_from_weight_decay)
            optimizer = opt_mod.Lamb(
                learning_rate=optimizer._lr,
                lamb_weight_decay=cfg.lamb_weight_decay,
                parameters=optimizer._parameters,
                exclude_from_weight_decay_fn=(lambda p: p.name in ex) if ex else None,
            )
        return trainer_kwargs, optimizer
