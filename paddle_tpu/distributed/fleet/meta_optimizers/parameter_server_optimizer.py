"""Parameter-server meta-optimizer (fleet/meta_optimizers/parameter_server_optimizer.py
parity, selected by strategy.a_sync like the reference's strategy factory).

Wraps the user optimizer so that dense parameters live on the PS: after local
backward, gradients are pushed (sync push-pull, or queued via the async
Communicator) and fresh values are pulled back — the DownpourWorker dense flow.
Sparse tables are handled by PsEmbedding directly (runtime.py)."""
import numpy as np

from .meta_optimizer_base import MetaOptimizerBase


class ParameterServerOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return bool(getattr(strategy, "a_sync", False))

    def apply(self, trainer_kwargs, optimizer, strategy):
        # marker only at graph-build time; the worker runtime binds the client
        trainer_kwargs["ps_mode"] = True
        return trainer_kwargs, optimizer


class PsDenseOptimizer:
    """Worker-side dense optimizer: push grads / pull params per step.

    `parameters` are ordinary eager Params; each is assigned one dense table.
    The server applies the real update rule (tables.py _Rule), matching the
    reference where optimizer rules execute inside the table
    (table/depends/dense.h)."""

    def __init__(self, parameters, client, communicator=None, optimizer="sgd", lr=0.01,
                 table_id_base=0):
        self._parameters = list(parameters)
        self.client = client
        self.communicator = communicator
        self._table_ids = {}
        for i, p in enumerate(self._parameters):
            tid = table_id_base + i
            self._table_ids[id(p)] = tid
            client.create_dense_table(tid, tuple(p.shape), optimizer=optimizer, lr=lr,
                                      init=np.asarray(p._data, np.float32))
        # all workers start from server's (worker-0-initialized) values
        self.pull()

    def step(self):
        for p in self._parameters:
            if p.grad is None:
                continue
            tid = self._table_ids[id(p)]
            g = np.asarray(p.grad._data, np.float32)
            if self.communicator is not None and self.communicator.mode == "async":
                self.communicator.push_dense_async(tid, g)
            else:
                self.client.push_dense(tid, g)
        self.pull()

    def pull(self):
        import jax.numpy as jnp

        for p in self._parameters:
            tid = self._table_ids[id(p)]
            p._data = jnp.asarray(self.client.pull_dense(tid), dtype=p._data.dtype)

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad
