"""LocalSGD meta-optimizer.

Reference parity: fleet/meta_optimizers/localsgd_optimizer.py — train k local steps,
then average parameters across ranks instead of per-step grad allreduce
(distributed_strategy.proto:51-59 LocalSGDConfig / AdaptiveLocalSGDConfig).

TPU-native design: the trainer gets `localsgd_k`; the SPMD step skips the grad psum
(params become per-dp-shard "varying") and every k-th step pmean's the params.
Eager fallback: LocalSGDStepper wraps an optimizer for the dygraph path.
"""
import jax.numpy as jnp

from .meta_optimizer_base import MetaOptimizerBase


class LocalSGDOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.localsgd

    def apply(self, trainer_kwargs, optimizer, strategy):
        trainer_kwargs["localsgd_k"] = strategy.localsgd_configs.k_steps
        trainer_kwargs["localsgd_begin"] = strategy.localsgd_configs.begin_step
        return trainer_kwargs, optimizer


class AdaptiveLocalSGDOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.adaptive_localsgd

    def apply(self, trainer_kwargs, optimizer, strategy):
        trainer_kwargs["localsgd_k"] = strategy.adaptive_localsgd_configs.init_k_steps
        trainer_kwargs["localsgd_adaptive"] = True
        return trainer_kwargs, optimizer


class LocalSGDStepper:
    """Eager helper: call after optimizer.step(); averages params every k steps."""

    def __init__(self, parameters, k_steps=1, begin_step=1):
        self.parameters = list(parameters)
        self.k = k_steps
        self.begin = begin_step
        self._step = 0

    def step(self):
        self._step += 1
        if self._step >= self.begin and self._step % self.k == 0:
            from ... import collective as C
            from ... import env as _env

            n = _env.get_world_size()
            if n > 1 or C.in_spmd_context():
                for p in self.parameters:
                    out = C.all_reduce(p, op=C.ReduceOp.AVG)
                    if out is not p:
                        p._data = out._data
