"""Meta-optimizer stack.

Reference parity: python/paddle/distributed/fleet/meta_optimizers/ — each file rewrote
the Program (insert ops / split blocks); here each meta-optimizer is a *functional
transformer*: it takes (trainer_kwargs, optimizer, strategy) and returns updated ones.
fleet.build_trainer composes them in the reference's strategy-compiler order
(fleet/base/strategy_compiler.py).
"""
from .amp_optimizer import AMPOptimizer  # noqa: F401
from .dgc_optimizer import DGCMomentumOptimizer, DGCOptimizer  # noqa: F401
from .gradient_merge_optimizer import GradientMergeOptimizer  # noqa: F401
from .lamb_optimizer import LambOptimizer  # noqa: F401
from .lars_optimizer import LarsOptimizer  # noqa: F401
from .localsgd_optimizer import AdaptiveLocalSGDOptimizer, LocalSGDOptimizer  # noqa: F401
from .pipeline_optimizer import PipelineOptimizer  # noqa: F401
from .recompute_optimizer import RecomputeOptimizer  # noqa: F401
from .sharding_optimizer import ShardingOptimizer  # noqa: F401
from .parameter_server_optimizer import (  # noqa: F401
    ParameterServerOptimizer,
    PsDenseOptimizer,
)

META_OPTIMIZER_ORDER = [
    ParameterServerOptimizer,
    # strategy_compiler order: amp/recompute wrap compute; sharding/pipeline shape the
    # mesh; gradient-merge/localsgd/dgc shape the update; lamb/lars swap the rule
    AMPOptimizer,
    RecomputeOptimizer,
    ShardingOptimizer,
    PipelineOptimizer,
    GradientMergeOptimizer,
    LocalSGDOptimizer,
    DGCOptimizer,
    LambOptimizer,
    LarsOptimizer,
]


def apply_meta_optimizers(trainer_kwargs, optimizer, strategy):
    for cls in META_OPTIMIZER_ORDER:
        mo = cls()
        if mo.can_apply(strategy):
            trainer_kwargs, optimizer = mo.apply(trainer_kwargs, optimizer, strategy)
    return trainer_kwargs, optimizer
