"""Pipeline meta-optimizer (fleet/meta_optimizers/pipeline_optimizer.py:25 parity).
Sets micro-batch accumulation; stage placement is the Pipeline class
(distributed/pipeline.py) — 1F1B scheduling is the shard_map tick loop."""
from .meta_optimizer_base import MetaOptimizerBase


class PipelineOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.pipeline

    def apply(self, trainer_kwargs, optimizer, strategy):
        cfg = strategy.pipeline_configs
        trainer_kwargs["accumulate_steps"] = max(
            trainer_kwargs.get("accumulate_steps", 1), cfg.accumulate_steps)
        trainer_kwargs["pp_degree"] = cfg.pp_degree
        trainer_kwargs["schedule_mode"] = cfg.schedule_mode
        return trainer_kwargs, optimizer
