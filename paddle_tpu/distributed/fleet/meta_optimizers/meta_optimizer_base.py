"""Base meta-optimizer (fleet/meta_optimizers/meta_optimizer_base.py parity)."""


class MetaOptimizerBase:
    def can_apply(self, strategy):
        raise NotImplementedError

    def apply(self, trainer_kwargs, optimizer, strategy):
        """Return (updated trainer_kwargs, updated optimizer)."""
        return trainer_kwargs, optimizer
