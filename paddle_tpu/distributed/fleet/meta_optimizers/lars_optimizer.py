"""LARS meta-optimizer (fleet/meta_optimizers/lars_optimizer.py parity)."""
from .meta_optimizer_base import MetaOptimizerBase


class LarsOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.lars

    def apply(self, trainer_kwargs, optimizer, strategy):
        from .... import optimizer as opt_mod

        if not isinstance(optimizer, opt_mod.Lars):
            cfg = strategy.lars_configs
            optimizer = opt_mod.Lars(
                learning_rate=optimizer._lr,
                momentum=getattr(optimizer, "_momentum", 0.9),
                lars_coeff=cfg.lars_coeff,
                lars_weight_decay=cfg.lars_weight_decay,
                epsilon=cfg.epsilon,
                parameters=optimizer._parameters,
            )
        return trainer_kwargs, optimizer
