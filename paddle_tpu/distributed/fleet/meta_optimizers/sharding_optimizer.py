"""Sharding (ZeRO) meta-optimizer (fleet/meta_optimizers/sharding_optimizer.py:33
parity). The reference's _split_program/_prune_main_program/_add_broadcast_allreduce
(sharding_optimizer.py:161,224,308) become NamedSharding assignments in SpmdTrainer."""
from .meta_optimizer_base import MetaOptimizerBase


class ShardingOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.sharding

    def apply(self, trainer_kwargs, optimizer, strategy):
        cfg = strategy.sharding_configs
        trainer_kwargs["sharding_stage"] = cfg.sharding_stage
        if cfg.gradient_merge_acc_step > 1:
            trainer_kwargs["accumulate_steps"] = max(
                trainer_kwargs.get("accumulate_steps", 1), cfg.gradient_merge_acc_step)
        if cfg.offload:
            trainer_kwargs["state_offload"] = True  # optimizer state on host memory
        return trainer_kwargs, optimizer
