"""Deep Gradient Compression meta-optimizer.

Reference parity: fleet/meta_optimizers/dgc_optimizer.py +
operators/optimizers/dgc_momentum_op.cc (+ dgc_op.cc): top-k sparsification of grads
with local accumulation of the residual and momentum correction before allreduce
(DGCConfig proto:66-70 rampup/sparsity).

TPU-native design: a pure grad-transform (top-k mask + residual carry in optimizer
state) applied before the mesh psum — compressing what crosses DCN. Implemented as a
Momentum subclass whose functional state carries u (momentum) and v (residual).
"""
import jax
import jax.numpy as jnp

from ....optimizer.optimizer import Momentum
from .meta_optimizer_base import MetaOptimizerBase


class DGCMomentumOptimizer(Momentum):
    """dgc_momentum_op.cc parity: momentum correction + residual accumulation +
    top-k gradient sparsification."""

    def __init__(self, learning_rate, momentum=0.9, sparsity=0.999, rampup_begin_step=0,
                 parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        self._sparsity = float(sparsity)
        self._rampup_begin = rampup_begin_step
        super().__init__(learning_rate, momentum, parameters, use_nesterov, weight_decay, grad_clip)

    def _init_state(self, p):
        st = super()._init_state(p)
        st["dgc_u"] = jnp.zeros_like(p._data)
        st["dgc_v"] = jnp.zeros_like(p._data)
        return st

    def _rule(self, p, g, state, lr):
        m = self._momentum
        # momentum correction on the *local* gradient (DGC paper eq. 4)
        u = m * state["dgc_u"] + g
        v = state["dgc_v"] + u
        # top-k selection on |v|
        k = max(1, int(v.size * (1.0 - self._sparsity)))
        flat = jnp.abs(v).reshape(-1)
        thresh = jax.lax.top_k(flat, k)[0][-1]
        mask = (jnp.abs(v) >= thresh).astype(v.dtype)
        sparse_grad = v * mask
        # residuals stay local
        new_u = u * (1 - mask)
        new_v = v * (1 - mask)
        # sparse_grad is what a multi-rank run allreduces (here: applied directly)
        new_p = p - lr.astype(p.dtype) * sparse_grad
        return new_p, {"velocity": state["velocity"], "dgc_u": new_u, "dgc_v": new_v}


class DGCOptimizer(MetaOptimizerBase):
    def can_apply(self, strategy):
        return strategy.dgc

    def apply(self, trainer_kwargs, optimizer, strategy):
        cfg = strategy.dgc_configs
        if not isinstance(optimizer, DGCMomentumOptimizer):
            optimizer = DGCMomentumOptimizer(
                learning_rate=optimizer._lr,
                momentum=getattr(optimizer, "_momentum", 0.9),
                sparsity=cfg.sparsity[-1] if cfg.sparsity else 0.999,
                rampup_begin_step=cfg.rampup_begin_step,
                parameters=optimizer._parameters,
            )
        return trainer_kwargs, optimizer
