"""paddle.distributed.fleet parity (python/paddle/distributed/fleet/__init__.py)."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import Fleet, fleet  # noqa: F401
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from . import metrics  # noqa: F401

# module-level facade functions (fleet.init(...) style)
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
build_trainer = fleet.build_trainer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
worker_endpoints = fleet.worker_endpoints
def __getattr__(name):  # delegate everything else to the singleton (e.g. ps_runtime)
    return getattr(fleet, name)
