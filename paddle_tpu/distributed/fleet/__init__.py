"""paddle.distributed.fleet parity (python/paddle/distributed/fleet/__init__.py)."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import Fleet, fleet  # noqa: F401
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from . import metrics  # noqa: F401

# module-level facade functions (fleet.init(...) style)
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
build_trainer = fleet.build_trainer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
worker_endpoints = fleet.worker_endpoints
def __getattr__(name):  # delegate everything else to the singleton (e.g. ps_runtime)
    return getattr(fleet, name)
from .role_maker import Role  # noqa: F401,E402
from .data_generator import (  # noqa: F401,E402
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)


class UtilBase:
    """fleet.UtilBase parity: cross-worker helper utilities."""

    def __init__(self):
        from ..env import ParallelEnv

        self._env = ParallelEnv()

    def get_file_shard(self, files):
        """Split a file list across workers (contiguous shards, remainder to
        the leading workers — reference util_base get_file_shard)."""
        n = max(self._env.world_size, 1)
        i = self._env.rank
        base, rem = divmod(len(files), n)
        start = i * base + min(i, rem)
        return files[start: start + base + (1 if i < rem else 0)]

    def all_reduce(self, input, mode="sum"):
        import numpy as np

        if self._env.world_size <= 1:
            return np.asarray(input)
        from .. import collective as C
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        t = Tensor(jnp.asarray(np.asarray(input)))
        C.all_reduce(t, op=getattr(C.ReduceOp, mode.upper(), C.ReduceOp.SUM))
        return np.asarray(t._data)

    def barrier(self):
        from .. import collective

        collective.barrier()

    def print_on_rank(self, message, rank_id=0):
        if self._env.rank == rank_id:
            print(message)


util = UtilBase()
