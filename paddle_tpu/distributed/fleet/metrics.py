"""Fleet distributed metrics (python/paddle/distributed/fleet/metrics/metric.py parity:
sum/max/min/auc aggregated across workers via the collective backend)."""
import numpy as np

from ...core.tensor import Tensor
from .. import collective as C
from .. import env as _env


def _agg(value, op):
    if isinstance(value, Tensor):
        t = value
    else:
        t = Tensor(np.asarray(value))
    if _env.get_world_size() > 1 or C.in_spmd_context():
        t = C.all_reduce(t, op=op)
    return np.asarray(t._data)


def sum(value, scope=None, util=None):
    return _agg(value, C.ReduceOp.SUM)


def max(value, scope=None, util=None):
    return _agg(value, C.ReduceOp.MAX)


def min(value, scope=None, util=None):
    return _agg(value, C.ReduceOp.MIN)


def acc(correct, total, scope=None, util=None):
    c = _agg(correct, C.ReduceOp.SUM)
    t = _agg(total, C.ReduceOp.SUM)
    return float(c) / float(t) if float(t) else 0.0


def mae(abserr, total_ins_num, scope=None, util=None):
    e = _agg(abserr, C.ReduceOp.SUM)
    n = _agg(total_ins_num, C.ReduceOp.SUM)
    return float(e) / float(n)


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    e = _agg(sqrerr, C.ReduceOp.SUM)
    n = _agg(total_ins_num, C.ReduceOp.SUM)
    return (float(e) / float(n)) ** 0.5


def auc(stat_pos, stat_neg, scope=None, util=None):
    pos = _agg(stat_pos, C.ReduceOp.SUM)
    neg = _agg(stat_neg, C.ReduceOp.SUM)
    tot_pos = tot_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        np_ = tot_pos + pos[i]
        nn = tot_neg + neg[i]
        area += (np_ + tot_pos) * (nn - tot_neg) / 2.0
        tot_pos, tot_neg = np_, nn
    denom = tot_pos * tot_neg
    return float(area / denom) if denom else 0.0
