"""DistributedStrategy.

Reference parity: paddle/fluid/framework/distributed_strategy.proto:25-169 (every
parallelism toggle + nested *Config messages) and its Python property wrapper
fleet/base/distributed_strategy.py. Protobuf replaced by a plain dataclass tree —
same field names so user code ports 1:1.
"""
import copy
import dataclasses
from dataclasses import dataclass, field


@dataclass
class RecomputeConfig:  # proto:25-28
    # reference: tensor names to checkpoint. TPU mapping: a jax.checkpoint
    # policy name in this list ("dots"/"dots_no_batch"/"nothing"/
    # "everything") selects SpmdTrainer's recompute_policy instead.
    checkpoints: list = field(default_factory=list)
    enable_offload: bool = False
    checkpoint_shape: list = field(default_factory=list)


@dataclass
class ShardingConfig:  # proto:31-35
    segment_broadcast_MB: float = 32.0
    hybrid_dp: bool = False
    sharding_degree: int = 8
    sharding_stage: int = 2
    mp_degree: int = 1
    segment_anchors: list = field(default_factory=list)
    gradient_merge_acc_step: int = 1
    offload: bool = False


@dataclass
class AMPConfig:  # proto:37-49
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.8
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = field(default_factory=list)
    custom_black_list: list = field(default_factory=list)
    custom_black_varnames: list = field(default_factory=list)
    use_pure_fp16: bool = False
    use_fp16_guard: bool = True
    dtype: str = "bfloat16"  # TPU-native default


@dataclass
class LocalSGDConfig:  # proto:51-54
    k_steps: int = 1
    begin_step: int = 1


@dataclass
class AdaptiveLocalSGDConfig:  # proto:56-59
    init_k_steps: int = 1
    begin_step: int = 1


@dataclass
class GradientMergeConfig:  # proto:61-64
    k_steps: int = 1
    avg: bool = True


@dataclass
class DGCConfig:  # proto:66-70
    rampup_begin_step: int = 0
    rampup_step: int = 1
    sparsity: list = field(default_factory=lambda: [0.999])


@dataclass
class LambConfig:  # proto:72-75
    lamb_weight_decay: float = 0.01
    exclude_from_weight_decay: list = field(default_factory=list)


@dataclass
class LarsConfig:
    lars_coeff: float = 0.001
    lars_weight_decay: float = 0.0005
    epsilon: float = 0.0
    exclude_from_weight_decay: list = field(default_factory=list)


@dataclass
class PipelineConfig:  # proto:120-124
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"
    pp_degree: int = 1


@dataclass
class AsyncConfig:  # proto:106-118
    k_steps: int = -1
    max_merge_var_num: int = 1
    send_queue_size: int = 16
    independent_recv_thread: bool = False
    thread_pool_size: int = 1
    send_wait_times: int = 1
    runtime_split_send_recv: bool = False
    launch_barrier: bool = True
    heter_worker_device_guard: str = "cpu"
    lr_decay_steps: int = 10


@dataclass
class HybridConfig:
    dp_degree: int = -1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1  # sequence parallel (beyond reference)


@dataclass
class TensorParallelConfig:
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


class DistributedStrategy:
    """fleet/base/distributed_strategy.py parity (proto:126-169 field set)."""

    def __init__(self):
        # execution/build (proto:84-104) — on TPU these are XLA's job; kept as inert
        self.build_strategy = None
        self.execution_strategy = None
        # main toggles (proto:126-169)
        self.amp = False
        self.amp_configs = AMPConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.localsgd = False
        self.localsgd_configs = LocalSGDConfig()
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = AdaptiveLocalSGDConfig()
        self.dgc = False
        self.dgc_configs = DGCConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = GradientMergeConfig()
        self.lars = False
        self.lars_configs = LarsConfig()
        self.lamb = False
        self.lamb_configs = LambConfig()
        self.a_sync = False
        self.a_sync_configs = AsyncConfig()
        self.hybrid_configs = HybridConfig()
        self.tensor_parallel = False
        self.tensor_parallel_configs = TensorParallelConfig()
        self.elastic = False  # proto:137 (flag only in reference too)
        self.auto = False
        self.nccl_comm_num = 1  # inert on TPU (no rings)
        self.fuse_all_reduce_ops = True  # XLA fuses; inert
        self.fuse_grad_size_in_MB = 32
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.heter_ccl_mode = False
        self.cudnn_exhaustive_search = False  # no cuDNN on TPU
        self.sync_nccl_allreduce = True
        self.without_graph_optimization = False

    def _set_config(self, holder, configs):
        if dataclasses.is_dataclass(holder):
            for k, v in configs.items():
                if hasattr(holder, k):
                    setattr(holder, k, v)
        return holder

    def __setattr__(self, name, value):
        # accept dict assignment to *_configs like the reference property setters
        if name.endswith("_configs") and isinstance(value, dict) and name in self.__dict__:
            self._set_config(self.__dict__[name], value)
            return
        object.__setattr__(self, name, value)

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on})"
