"""fleetrun launcher.

Reference parity: python/paddle/distributed/fleet/launch.py:334 launch() /
:208 launch_collective, and launch_utils.py:457-464 — spawns one process per
device/host rank with the PADDLE_TRAINER_* env protocol.

TPU-native design: on TPU one process drives all local chips (single-controller JAX),
so `--nproc_per_node` defaults to 1; multi-HOST launches export the coordination
address consumed by jax.distributed.initialize (env.init_distributed). The same env
names are kept so reference scripts port unchanged:
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS /
  PADDLE_CURRENT_ENDPOINT.

Usage: python -m paddle_tpu.distributed.fleet.launch --ips host1,host2 train.py args…
"""
import argparse
import os
import signal
import subprocess
import sys


def _parse_args(argv=None):
    p = argparse.ArgumentParser("fleetrun")
    p.add_argument("--ips", default="127.0.0.1", help="comma-separated host list")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1: single-controller JAX drives all chips)")
    p.add_argument("--start_port", type=int, default=6070)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--backend", default="xla", help="accepted for compat (nccl->xla)")
    p.add_argument("--server_num", type=int, default=0, help="PS servers (ps mode)")
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_env(ips, start_port, nproc_per_node, rank):
    hosts = ips.split(",")
    endpoints = []
    for h in hosts:
        for i in range(nproc_per_node):
            endpoints.append(f"{h}:{start_port + i}")
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(len(endpoints)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_LOCAL_RANK": str(rank % nproc_per_node),
        "FLAGS_selected_tpus": str(rank % nproc_per_node),
    }


def launch_collective(args):
    """launch.py:208 parity: spawn local worker processes, wire env, wait, propagate
    failures (kill the gang on first death — the reference's watchdog behavior)."""
    hosts = args.ips.split(",")
    local_host_rank = 0  # index of this host in --ips (single-host default)
    n_local = args.nproc_per_node
    procs = []
    log_dir = args.log_dir
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for local_rank in range(n_local):
        rank = local_host_rank * n_local + local_rank
        env = dict(os.environ)
        env.update(get_cluster_env(args.ips, args.start_port, n_local, rank))
        cmd = [sys.executable, args.training_script] + args.training_script_args
        out = open(os.path.join(log_dir, f"workerlog.{local_rank}"), "w") if log_dir else None
        procs.append((subprocess.Popen(cmd, env=env, stdout=out, stderr=subprocess.STDOUT if out else None), out))

    exit_code = 0
    try:
        alive = True
        while alive:
            alive = False
            for p, _ in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    exit_code = ret
                    for q, _ in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    alive = False
                    break
            if alive:
                import time

                time.sleep(0.5)
    finally:
        for p, out in procs:
            if p.poll() is None:
                p.wait()
            if out:
                out.close()
    return exit_code


def launch_ps(args):
    """launch.py:260 parity (launch_ps): spawn --server_num PS servers and
    --worker_num trainers on this host with the PADDLE_PSERVERS_IP_PORT_LIST /
    TRAINING_ROLE env protocol (fleet/launch_utils.py)."""
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    n_servers = args.server_num
    n_workers = args.worker_num if (args.worker_num or 0) > 0 else args.nproc_per_node
    server_eps = ",".join(f"127.0.0.1:{free_port()}" for _ in range(n_servers))
    log_dir = args.log_dir
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    procs = []

    def spawn(role, idx, extra_env, tag):
        env = dict(os.environ)
        env.update({
            "PADDLE_PSERVERS_IP_PORT_LIST": server_eps,
            "PADDLE_TRAINERS_NUM": str(n_workers),
            "TRAINING_ROLE": role,
        })
        env.update(extra_env)
        cmd = [sys.executable, args.training_script] + args.training_script_args
        out = open(os.path.join(log_dir, f"{tag}.{idx}"), "w") if log_dir else None
        procs.append((subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT if out else None), out))

    for i in range(n_servers):
        ip, port = server_eps.split(",")[i].rsplit(":", 1)
        spawn("PSERVER", i, {"PADDLE_PSERVER_ID": str(i), "POD_IP": ip,
                             "PADDLE_PORT": port}, "serverlog")
    for i in range(n_workers):
        spawn("TRAINER", i, {"PADDLE_TRAINER_ID": str(i)}, "workerlog")

    exit_code = 0
    try:
        # workers are the tail of `procs`; servers exit when a worker stops them
        for p, _ in procs[n_servers:]:
            ret = p.wait()
            if ret != 0:
                exit_code = ret
    finally:
        for p, out in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
                p.wait()
            if out:
                out.close()
    return exit_code


def launch():
    args = _parse_args()
    if args.server_num > 0:
        sys.exit(launch_ps(args))
    sys.exit(launch_collective(args))


if __name__ == "__main__":
    launch()
