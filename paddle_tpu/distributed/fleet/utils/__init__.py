from .fs import FS, FSFileExistsError, FSFileNotExistsError, HDFSClient, LocalFS  # noqa: F401
