"""Filesystem abstraction for fleet checkpointing.

Reference parity: python/paddle/distributed/fleet/utils/fs.py — abstract `FS` with
LocalFS and HDFSClient implementations (ls_dir, is_dir/is_file/is_exist, upload,
download, mkdirs, delete, mv, touch, cat, need_upload_download) used by
auto-checkpoint and dataset shuffling; the C++ side is framework/io/fs.cc (shell-out
to `hadoop fs`). TPU build keeps the same shell-out design for HDFS — it is the
portable path and carries no JVM binding dependency.
"""
import os
import shutil
import subprocess


class FSFileExistsError(RuntimeError):
    pass


class FSFileNotExistsError(RuntimeError):
    pass


class FS:
    """Abstract interface (reference fs.py FS)."""

    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path, exist_ok=True):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def need_upload_download(self):
        return False

    def cat(self, path):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py LocalFS)."""

    def ls_dir(self, path):
        """-> (dirs, files), names only (reference convention)."""
        if not self.is_exist(path):
            return [], []
        entries = sorted(os.listdir(path))
        dirs = [e for e in entries if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries if not os.path.isdir(os.path.join(path, e))]
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FSFileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path):
            if not exist_ok:
                raise FSFileExistsError(path)
            return
        d = os.path.dirname(path)
        if d:
            self.mkdirs(d)
        open(path, "a").close()

    def upload(self, local_path, fs_path):  # local == fs
        if os.path.abspath(local_path) != os.path.abspath(fs_path):
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        if os.path.abspath(local_path) != os.path.abspath(fs_path):
            shutil.copy(fs_path, local_path)

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read()


class HDFSClient(FS):
    """`hadoop fs` shell-out client (reference fs.py HDFSClient / C++ io/fs.cc).

    hadoop_home/configs mirror the reference ctor; every operation execs
    `{hadoop}/bin/hadoop fs <cmd>`. Raises a clear error when no hadoop binary
    is present (zero-egress images ship none).
    """

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        self._configs = configs or {}
        self._timeout_s = time_out / 1000.0
        bin_cand = (os.path.join(self._hadoop_home, "bin", "hadoop")
                    if self._hadoop_home else "hadoop")
        self._bin = bin_cand if (shutil.which(bin_cand)
                                 or os.path.exists(bin_cand)) else None

    def available(self):
        return self._bin is not None

    def _run(self, *args, check=True, binary=False):
        if self._bin is None:
            raise RuntimeError(
                "HDFSClient needs a hadoop binary (set hadoop_home= or "
                "HADOOP_HOME); none found on this host")
        cmd = [self._bin, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            res = subprocess.run(cmd, capture_output=True, text=not binary,
                                 timeout=self._timeout_s)
        except subprocess.TimeoutExpired as e:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} timed out after "
                f"{self._timeout_s}s") from e
        if check and res.returncode != 0:
            err = res.stderr if not binary else res.stderr.decode(
                "utf-8", "replace")
            raise RuntimeError(f"hadoop fs {' '.join(args)} failed: {err}")
        return res

    def ls_dir(self, path):
        res = self._run("-ls", path, check=False)
        dirs, files = [], []
        for line in res.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_dir(self, path):
        return self._run("-test", "-d", path, check=False).returncode == 0

    def is_file(self, path):
        return self._run("-test", "-f", path, check=False).returncode == 0

    def is_exist(self, path):
        return self._run("-test", "-e", path, check=False).returncode == 0

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def mv(self, src, dst, overwrite=False):
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FSFileExistsError(dst)
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path):
            if not exist_ok:
                raise FSFileExistsError(path)
            return
        self._run("-touchz", path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True

    def cat(self, path):
        # binary capture: checkpoints are pickled/encrypted bytes — text-mode
        # newline translation would corrupt them (LocalFS.cat returns bytes too)
        return self._run("-cat", path, binary=True).stdout
