"""The fleet facade.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py (Fleet:63,
init:130, distributed_optimizer:598, minimize:1075) + the meta-optimizer composition
(meta_optimizer_factory.py / strategy_compiler.py).

TPU-native design: fleet.minimize / fleet.distributed_optimizer compose *functional*
meta-optimizers: instead of rewriting a ProgramDesc (sharding_optimizer.py:161
_split_program etc.), each enabled strategy contributes configuration to one
SpmdTrainer (sharding -> state shardings; recompute -> jax.checkpoint;
gradient_merge -> micro-batch scan; amp -> bf16 autocast; lamb/lars -> optimizer swap).
The dygraph path (fleet.distributed_model) wraps DataParallel.
"""
from ... import optimizer as opt_mod
from .. import env as _env
from ..mesh import build_mesh, get_mesh, set_mesh
from ..parallel import DataParallel, init_parallel_env
from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._is_collective = True
        self._user_defined_optimizer = None
        self._inited = False

    # -- init ------------------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        """fleet_base.py:130 parity."""
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective=is_collective)
        self._is_collective = is_collective
        self._strategy = strategy or DistributedStrategy()
        # PS mode (is_collective=False) rendezvouses over the PS RPC tier, not
        # the jax.distributed coordination service (reference: PS init skips NCCL)
        if is_collective and _env.get_world_size() > 1:
            init_parallel_env()
        if is_collective:
            self._apply_mesh()
        self._inited = True
        return self

    def _apply_mesh(self):
        """Build the hybrid mesh from strategy.hybrid_configs (dp/mp/pp/sharding)."""
        import jax

        hc = self._strategy.hybrid_configs if self._strategy else None
        n = len(jax.devices())
        if hc and (hc.mp_degree > 1 or hc.pp_degree > 1 or hc.sep_degree > 1):
            mp, pp, sep = hc.mp_degree, hc.pp_degree, hc.sep_degree
            dp = hc.dp_degree if hc.dp_degree > 0 else max(1, n // (mp * pp * sep))
            shape = (dp, pp, sep, mp)
            names = ("dp", "pp", "sp", "mp")
            set_mesh(build_mesh(shape, names))
        else:
            set_mesh(build_mesh((n,), ("dp",)))

    # -- info ------------------------------------------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .. import collective as C

        C.barrier()

    # -- dygraph path ----------------------------------------------------------
    def distributed_model(self, model):
        """fleet_base.py distributed_model parity (dygraph DDP wrap)."""
        if _env.get_world_size() > 1:
            return DataParallel(model)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """fleet_base.py:598 parity — returns a wrapper whose minimize/step applies
        the enabled meta-optimizer stack."""
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        return FleetOptimizer(optimizer, self._strategy, self)

    # -- static-ish path: build a sharded trainer -------------------------------
    def build_trainer(self, layer, optimizer=None, loss_fn=None, **overrides):
        """Compose the meta-optimizer stack into one SpmdTrainer (TPU-native
        equivalent of fleet.minimize graph rewriting)."""
        from ..spmd import SpmdTrainer
        from .meta_optimizers import apply_meta_optimizers

        s = self._strategy
        optimizer = optimizer or self._user_defined_optimizer
        if hasattr(optimizer, "_inner"):  # unwrap FleetOptimizer
            optimizer = optimizer._inner
        kw = dict(sharding_stage=0, recompute=False, accumulate_steps=1)
        kw, optimizer = apply_meta_optimizers(kw, optimizer, s)
        kw.update(overrides)
        pp_degree = kw.pop("pp_degree", 1)
        if pp_degree and pp_degree > 1:
            # PipelineOptimizer parity: split the model into sections and train
            # through the scheduled pipeline (reference section_worker.cc:98-141)
            from ..pipeline import PipelineTrainer

            if not hasattr(layer, "pipeline_split"):
                raise ValueError(
                    "strategy.pipeline needs a model with pipeline_split(pp) "
                    "-> (pre, stages, post_loss); GPTForCausalLM implements it")
            unconsumed = [k for k, bad in (
                ("amp_dtype", kw.get("amp_dtype") is not None),
                ("sharding_stage", kw.get("sharding_stage", 0) > 0),
                ("recompute", kw.get("recompute", False)),
                ("loss_fn", loss_fn is not None),
            ) if bad]
            if unconsumed:
                import warnings

                warnings.warn(
                    f"pipeline trainer does not consume {unconsumed}; the "
                    "model's post_loss section defines the loss, and amp/"
                    "sharding/recompute do not yet compose with pp_degree>1")
            pre, stages, post = layer.pipeline_split(pp_degree)
            n_micro = max(kw.get("accumulate_steps", 1), pp_degree)
            return PipelineTrainer(
                pre, stages, post, optimizer, mesh=get_mesh(),
                n_micro=n_micro,
                schedule_mode=kw.get("schedule_mode", "1F1B"))
        return SpmdTrainer(layer, optimizer, loss_fn, mesh=get_mesh(), **kw)

    # -- PS mode (distributed/ps: host tables + TCP RPC) -----------------------
    @property
    def ps_runtime(self):
        """Lazily-built TheOnePs runtime (fleet/runtime/the_one_ps.py parity)."""
        if getattr(self, "_ps_runtime", None) is None:
            from ..ps.runtime import TheOnePs

            self._ps_runtime = TheOnePs(role_maker=self._role_maker,
                                        strategy=self._strategy)
        return self._ps_runtime

    def init_worker(self):
        if not self._is_collective:
            self.ps_runtime.init_worker()

    def init_server(self, *args, **kwargs):
        self.ps_runtime.make_server()

    def run_server(self):
        self.ps_runtime.run_server()

    def stop_worker(self):
        if getattr(self, "_ps_runtime", None) is not None:
            self._ps_runtime.stop_worker()

    def save_inference_model(self, executor, dirname, feeded_var_names, target_vars,
                             main_program=None, export_for_deployment=True):
        pass

    def save_persistables(self, executor, dirname, main_program=None, mode=0):
        pass


class FleetOptimizer:
    """Wrapper returned by fleet.distributed_optimizer (meta-optimizer stack applied
    at minimize time)."""

    def __init__(self, inner, strategy, fleet):
        self._inner = inner
        self._strategy = strategy
        self._fleet = fleet
        self.user_defined_strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        # dygraph DDP: grads already allreduced via hooks
        self._inner.step()

    def clear_grad(self):
        self._inner.clear_grad()

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        s = self._strategy
        if s.amp:
            # loss scaling handled by GradScaler in dygraph; here grads exist already
            pass
        loss.backward()
        if _env.get_world_size() > 1:
            from .. import collective as C

            n = _env.get_world_size()
            for p in self._inner._parameters:
                if p.grad is not None:
                    C.all_reduce(p.grad)
                    p.grad._data = p.grad._data / n
        self._inner.step()
        return None, [(p, p.grad) for p in self._inner._parameters]


fleet = Fleet()
