"""Role makers (python/paddle/distributed/fleet/base/role_maker.py parity:
PaddleCloudRoleMaker:528 reads the PADDLE_* env protocol; UserDefinedRoleMaker)."""
import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        raise NotImplementedError

    def worker_num(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if training_role == "PSERVER" else Role.WORKER

    def worker_index(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def worker_num(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        if eps:
            return len(eps.split(","))
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def server_num(self):
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        return len(eps.split(",")) if eps else 0

    def node_num(self):
        return max(1, self.worker_num())

    def get_trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._kwargs = kwargs

    def worker_index(self):
        return self._kwargs.get("current_id", super().worker_index())

    def worker_num(self):
        return self._kwargs.get("worker_num", super().worker_num())
