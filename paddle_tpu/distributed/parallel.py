"""Dygraph data parallel.

Reference parity: python/paddle/distributed/parallel.py (init_parallel_env:57) and
fluid/dygraph/parallel.py:322 DataParallel + imperative/reducer.cc:293 (bucketed
grad allreduce on ready-hooks).

TPU-native design: no Reducer buckets — per-parameter grad hooks call the mesh/process
allreduce; under the jitted SPMD path (spmd.data_parallel) gradients are psum'ed by XLA
inside the step, which is the perf path and needs no hooks at all.
"""
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import collective as C
from . import env as _env


def init_parallel_env():
    """distributed/parallel.py:57 parity -> jax.distributed.initialize."""
    _env.init_distributed()
    return _env.ParallelEnv()


def get_rank():
    return _env.get_rank()


def get_world_size():
    return _env.get_world_size()


class DataParallel(Layer):
    """paddle.DataParallel parity (fluid/dygraph/parallel.py:322)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self._nranks = _env.get_world_size()
        self._group = group
        if self._nranks > 1:
            self._register_grad_hooks()

    def _register_grad_hooks(self):
        nranks = self._nranks

        def make_hook():
            def hook(grad):
                out = C.all_reduce(grad, op=C.ReduceOp.SUM, group=self._group)
                return Tensor(out._data / nranks) if out is not None else grad

            return hook

        for p in self._layers.parameters():
            if getattr(p, "trainable", True):
                p.register_hook(make_hook())

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        if self._nranks <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                C.all_reduce(p.grad, op=C.ReduceOp.SUM, group=self._group)
                p.grad._data = p.grad._data / self._nranks

    # delegate everything else to the wrapped layer
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity — fork one python process per device/host rank."""
    import multiprocessing as mp
    import os

    if nprocs in (-1, None):
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env_patch = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
        }

        def target(rank=rank, env_patch=env_patch):
            os.environ.update(env_patch)
            func(*args)

        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs
