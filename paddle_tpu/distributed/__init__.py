"""paddle.distributed parity (python/paddle/distributed/__init__.py)."""
from . import collective  # noqa: F401
from . import env  # noqa: F401
from . import fleet  # noqa: F401
from . import mesh  # noqa: F401
from . import spmd  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    get_rank,
    get_world_size,
    is_initialized,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    spmd_context,
    in_spmd_context,
    wait,
)
from .env import ParallelEnv  # noqa: F401
from .parallel import DataParallel, init_parallel_env, spawn  # noqa: F401
from .split import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    collect_spmd_specs,
    split,
)
from . import ps  # noqa: F401,E402
