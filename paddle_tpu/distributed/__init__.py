"""paddle.distributed parity (python/paddle/distributed/__init__.py)."""
from . import collective  # noqa: F401
from . import env  # noqa: F401
from . import fleet  # noqa: F401
from . import mesh  # noqa: F401
from . import spmd  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    get_rank,
    get_world_size,
    is_initialized,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    spmd_context,
    in_spmd_context,
    wait,
)
from .env import ParallelEnv  # noqa: F401
from .parallel import DataParallel, init_parallel_env, spawn  # noqa: F401
from .split import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    collect_spmd_specs,
    split,
)
from . import ps  # noqa: F401,E402
from ..io.multislot import InMemoryDataset, QueueDataset  # noqa: F401,E402


def all_gather_object(object_list, obj, group=None):
    """paddle.distributed.all_gather_object parity: gather arbitrary picklable
    objects from every rank. Single-process groups (the common local case)
    append the object directly; multi-process uses the collective all_gather
    over a pickled uint8 buffer."""
    import pickle

    import numpy as np

    from . import collective as C
    from .env import ParallelEnv

    world = ParallelEnv().world_size
    if world <= 1:
        object_list.append(obj)
        return
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # length-prefix so ranks can unpickle despite padding to the max size
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    n = np.array([payload.size], np.int64)
    sizes = []
    C.all_gather(sizes, Tensor(jnp.asarray(n)), group=group)
    max_n = int(max(int(np.asarray(s._data)[0]) for s in sizes))
    padded = np.zeros(max_n, np.uint8)
    padded[: payload.size] = payload
    gathered = []
    C.all_gather(gathered, Tensor(jnp.asarray(padded)), group=group)
    for s, g in zip(sizes, gathered):
        k = int(np.asarray(s._data)[0])
        object_list.append(pickle.loads(np.asarray(g._data)[:k].tobytes()))
from .ps.tables import CountFilterEntry, ProbabilityEntry  # noqa: F401,E402
