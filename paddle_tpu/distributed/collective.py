"""Collective communication API.

Reference parity: python/paddle/distributed/collective.py (all_reduce, all_gather,
broadcast, reduce, scatter, barrier, send/recv) backed by operators/collective/c_*
NCCL kernels (c_allreduce_op.h:109-131 ring-id lookup + ncclAllReduce).

TPU-native design: two execution contexts —
 1. SPMD (inside shard_map/pjit over a Mesh): collectives are jax.lax primitives on a
    named axis; XLA schedules them on ICI. This is the performance path; "ring_id"/
    "group" maps to the axis name.
 2. Eager multi-process: jax.experimental.multihost_utils (process_allgather etc.) over
    the jax.distributed coordination service — functional parity for host-side code.
Single-process eager collectives are identities (world_size == 1), matching the
reference's behavior when nranks == 1 (collective ops skip NCCL).
No stream-sync ops exist: XLA orders collectives (c_sync_*_stream -> no-op).
"""
import contextlib
import time

import numpy as np
import jax
import jax.numpy as jnp

from .. import monitor as _monitor
from ..trace import costs as _costs  # noqa: F401  (imports the module)
from .. import trace as _trace
from ..core.tensor import Tensor
from ..testing import failpoints as _fp
from . import env as _env

_SPMD_AXIS = []  # stack of axis names active under spmd_context


def _stat(kind, x):
    """Count one collective API call (+payload bytes) under the HLO-family
    names analysis/collectives.py uses, so the monitor's runtime counters
    and the static collective-count pass read through one vocabulary.
    List/tuple payloads sum over their elements, so the byte count for one
    logical collective is the same whichever argument form the caller
    used. Also the chokepoint where the `collective/call` failpoint fires —
    a fault injected here surfaces as a failed collective to the caller."""
    _fp.failpoint("collective/call")
    if isinstance(x, (list, tuple)):
        nbytes = sum(_monitor.tensor_nbytes(v) for v in x)
    else:
        nbytes = _monitor.tensor_nbytes(x)
    _monitor.record_collective(kind, nbytes)
    if _trace.is_enabled():
        # instantaneous span tagged with the payload size: host-side
        # API-call accounting (a call inside a jit trace records once per
        # TRACE), inheriting trace/parent ids from any enclosing span
        now = time.perf_counter_ns()
        _trace.emit("collective/" + kind, now, now,
                    subsystem="collective", parent=_trace.current_span(),
                    bytes=nbytes)


def record_compressed(kind, logical_nbytes, wire_nbytes):
    """Chokepoint accounting for a WIRE-COMPRESSED collective (the
    quantized reduce family, docs/DISTRIBUTED.md): like :func:`_stat` it
    fires the ``collective/call`` failpoint and counts the call, but
    ``collective_bytes_total{op=kind}`` gets the bytes that actually
    cross the interconnect (int8 payload + scales) while the fp32 bytes
    the encoding displaced land in ``collective_bytes_saved_total{op}``.
    For uncompressed ops wire == logical and :func:`_stat` is unchanged —
    the PR 2 meaning of every existing series is preserved. Emits a
    ``collective/quantized`` span carrying both numbers."""
    _fp.failpoint("collective/call")
    _monitor.record_collective(
        kind, int(wire_nbytes),
        saved_bytes=max(0, int(logical_nbytes) - int(wire_nbytes)))
    if _trace.is_enabled():
        now = time.perf_counter_ns()
        _trace.emit("collective/quantized", now, now,
                    subsystem="collective", parent=_trace.current_span(),
                    op=kind, bytes=int(wire_nbytes),
                    logical_bytes=int(logical_nbytes))


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Process-group facade; on TPU a group IS a mesh axis name."""

    def __init__(self, axis_name="dp", ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks
        self.id = id

    @property
    def nranks(self):
        if self.ranks:
            return len(self.ranks)
        return _env.get_world_size()


_DEFAULT_GROUP = Group("dp", id=0)


def new_group(ranks=None, backend=None, axis_name=None):
    return Group(axis_name or "dp", ranks=ranks, id=np.random.randint(1 << 30))


@contextlib.contextmanager
def spmd_context(axis_name):
    """Mark that we are inside a shard_map/pmap body for `axis_name`."""
    _SPMD_AXIS.append(axis_name)
    try:
        yield
    finally:
        _SPMD_AXIS.pop()


def in_spmd_context():
    return bool(_SPMD_AXIS)


def _axis(group):
    if group is not None and isinstance(group, Group):
        return group.axis_name
    if _SPMD_AXIS:
        return _SPMD_AXIS[-1]
    return "dp"


def _unary_collective(x, spmd_fn, eager_multi_fn=None):
    if isinstance(x, Tensor):
        from ..core.dispatch import apply

        if in_spmd_context():
            return apply(spmd_fn, x)
        if _env.get_world_size() > 1 and eager_multi_fn is not None:
            return eager_multi_fn(x)
        return x  # world_size == 1: identity
    # raw array (used inside user shard_map bodies)
    return spmd_fn(x)


def _compress_bits(compress):
    """Normalize the all_reduce/client_reduce `compress` opt-in: None/0/
    False = off; True = int8; an int = that wire width (validated by the
    compress module)."""
    if not compress:
        return None
    return 8 if compress is True else int(compress)


def _compressed_reduce(x, op, axis_name, bits, kind, key=None,
                       placed=False, leading=False):
    """The chokepoint's compressed path (ROADMAP item 2). Three
    placements, mirroring the uncompressed ops:

    - `placed` — inside a shard_map/client_map body on a named axis: the
      payload goes through :func:`compress.quantized_all_reduce` (int8
      wire, float32 accumulation, straight-through gradient);
    - `leading` — server-side clients-leading array (client_reduce's
      eager FedAvg form): each leading slice pays one quantize-dequantize
      round-trip (its simulated wire trip) before the float32 axis-0
      reduce;
    - neither — eager world-size-1 'all-reduce': identity semantics, but
      the caller opted into the wire format, so the one local
      quantization round-trip is applied — the error a mesh would see is
      visible (and testable) on a laptop too.

    SUM/AVG only, float payloads only — anything else must stay exact and
    raises instead of silently shipping fp32."""
    from . import compress as _compress

    if op not in (ReduceOp.SUM, "sum", ReduceOp.AVG, "avg"):
        raise ValueError(
            f"compressed reduce supports SUM/AVG, got {op!r} "
            "(MAX/MIN/PROD have no meaningful quantized accumulation)")
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        raise ValueError(
            f"compressed reduce needs a float payload, got "
            f"{data.dtype} (integer reductions must stay exact)")
    mean = op in (ReduceOp.AVG, "avg")
    if placed:
        # per-op payload accounting; the block*world shard padding the
        # traced exchange adds is not visible here (axis size is only
        # known under the trace) — a slight under-count for payloads
        # that aren't world-shard multiples
        wire = _compress.wire_bytes(int(data.size), bits=bits)
        fn = lambda v: _compress.quantized_all_reduce(
            v, axis_name, key=key, bits=bits, mean=mean)
    elif leading:
        # each leading row is an independent payload (its own blocks +
        # scales) — meter the sum of the per-row encodings
        rows_n = int(data.shape[0]) if data.ndim else 1
        row_sz = int(data.size) // max(rows_n, 1)
        wire = rows_n * _compress.wire_bytes(row_sz, bits=bits)

        def fn(v, _key=key if key is not None else _eager_quant_key()):
            rows = [
                _compress.quantize_dequantize(
                    v[i], jax.random.fold_in(_key, i), bits=bits)
                for i in range(v.shape[0])]
            stacked = jnp.stack(rows)
            return jnp.mean(stacked, 0) if mean else jnp.sum(stacked, 0)
    elif _env.get_world_size() > 1:
        # raise BEFORE any metering/failpoint: an op that never runs
        # must not count as a completed quantized collective
        raise NotImplementedError(
            "compressed eager multi-process all_reduce is not implemented "
            "— compression targets the SPMD/ICI path (docs/DISTRIBUTED.md)")
    else:
        wire = _compress.wire_bytes(int(data.size), bits=bits)
        fn = lambda v: _compress.quantize_dequantize(
            v, key if key is not None else _eager_quant_key(), bits=bits)
    record_compressed(kind, logical_nbytes=_monitor.tensor_nbytes(x),
                      wire_nbytes=wire)
    if isinstance(x, Tensor):
        from ..core.dispatch import apply

        return apply(fn, x)
    return fn(jnp.asarray(x))


_EAGER_QUANT_SEQ = [0]


def _eager_quant_key():
    """Per-call stochastic-rounding key for eager compressed reduces:
    seeded from the global generator (deterministic under paddle.seed)
    and advanced per call so repeated reduces never share rounding
    noise."""
    from ..core.generator import default_generator

    _EAGER_QUANT_SEQ[0] += 1
    return default_generator().fold_in(0x514152 + _EAGER_QUANT_SEQ[0])


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               compress=None):
    ax = _axis(group)
    bits = _compress_bits(compress)
    if bits is not None:
        out = _compressed_reduce(tensor, op, ax, bits, "quantized_all_reduce",
                                 placed=in_spmd_context())
        if isinstance(tensor, Tensor) and isinstance(out, Tensor) \
                and out is not tensor:
            # paddle all_reduce is in-place on the tensor — including
            # the world-size-1 compressed form, whose quantization
            # round-trip must land in the caller's tensor (a caller
            # ignoring the return value sees the same lossy wire format
            # it would see on a mesh)
            tensor._data = out._data
            tensor._node = out._node
            return tensor
        return out
    _stat("all-reduce", tensor)

    def spmd(v):
        if op in (ReduceOp.SUM, "sum"):
            return jax.lax.psum(v, ax)
        if op in (ReduceOp.MAX, "max"):
            return jax.lax.pmax(v, ax)
        if op in (ReduceOp.MIN, "min"):
            return jax.lax.pmin(v, ax)
        if op in (ReduceOp.AVG, "avg"):
            return jax.lax.pmean(v, ax)
        if op in (ReduceOp.PROD, "prod"):
            return jnp.exp(jax.lax.psum(jnp.log(v), ax))
        raise ValueError(op)

    def eager_multi(t):
        from jax.experimental import multihost_utils

        g = multihost_utils.process_allgather(t._data)
        if op in (ReduceOp.SUM, "sum"):
            red = g.sum(0)
        elif op in (ReduceOp.MAX, "max"):
            red = g.max(0)
        elif op in (ReduceOp.MIN, "min"):
            red = g.min(0)
        elif op in (ReduceOp.AVG, "avg"):
            red = g.mean(0)
        else:
            red = g.prod(0)
        if isinstance(t, Tensor):
            t._data = jnp.asarray(red)
            return t
        return Tensor(red)

    out = _unary_collective(tensor, spmd, eager_multi)
    if isinstance(tensor, Tensor) and isinstance(out, Tensor) and out is not tensor and in_spmd_context():
        # paddle all_reduce is in-place on the tensor
        tensor._data = out._data
        tensor._node = out._node
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    ax = _axis(group)
    _stat("all-gather", tensor)
    if in_spmd_context():
        from ..core.dispatch import apply

        out = apply(lambda v: jax.lax.all_gather(v, ax), tensor)
        if tensor_list is not None:
            n = out.shape[0]
            for i in range(n):
                tensor_list.append(out[i])
        return out
    if _env.get_world_size() > 1:
        from jax.experimental import multihost_utils

        g = multihost_utils.process_allgather(tensor._data if isinstance(tensor, Tensor) else tensor)
        outs = [Tensor(g[i]) for i in range(g.shape[0])]
        if tensor_list is not None:
            tensor_list.extend(outs)
        return Tensor(jnp.asarray(g))
    if tensor_list is not None:
        tensor_list.append(tensor)
    return tensor


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)
    _stat("reduce-scatter", tensor_or_tensor_list)
    from ..core.dispatch import apply

    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat

        src = concat(list(src), axis=0)
    if in_spmd_context():
        out = apply(lambda v: jax.lax.psum_scatter(v, ax, tiled=True), src)
        if tensor is not None:
            tensor._data = out._data
            tensor._node = out._node
            return tensor
        return out
    if tensor is not None and src is not tensor:
        tensor._data = (src._data if isinstance(src, Tensor) else jnp.asarray(src))
        return tensor
    return src


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)
    _stat("all-gather", tensor)  # the SPMD broadcast lowers via all_gather
    if in_spmd_context():
        from ..core.dispatch import apply

        # broadcast = select rank src's value: all_gather then index (XLA optimizes)
        return apply(lambda v: jax.lax.all_gather(v, ax)[src], tensor)
    if _env.get_world_size() > 1:
        from jax.experimental import multihost_utils

        val = multihost_utils.broadcast_one_to_all(
            tensor._data if isinstance(tensor, Tensor) else tensor,
            is_source=_env.get_rank() == src,
        )
        if isinstance(tensor, Tensor):
            tensor._data = jnp.asarray(val)
            return tensor
        return Tensor(val)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on mesh collectives a reduce == all_reduce (result replicated; dst keeps it)
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if in_spmd_context():
        from ..core.dispatch import apply
        from ..tensor.manipulation import stack

        stacked = stack(tensor_list, axis=0) if tensor_list else tensor

        def fn(v):
            idx = jax.lax.axis_index(ax)
            return jax.lax.dynamic_index_in_dim(v, idx, axis=0, keepdims=False)

        out = apply(fn, stacked)
        if tensor is not None:
            tensor._data = out._data
            tensor._node = out._node
            return tensor
        return out
    if tensor_list:
        val = tensor_list[_env.get_rank() % len(tensor_list)]
        tensor._data = val._data
        return tensor
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group)
    _stat("all-to-all", in_tensor_list)
    from ..core.dispatch import apply
    from ..tensor.manipulation import stack

    if in_spmd_context():
        x = stack(list(in_tensor_list), axis=0) if isinstance(in_tensor_list, (list, tuple)) else in_tensor_list
        out = apply(lambda v: jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=False), x)
        if out_tensor_list is not None:
            for i in range(out.shape[0]):
                out_tensor_list.append(out[i])
        return out
    if out_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
        out_tensor_list.extend(in_tensor_list)
    return in_tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    """send_v2 parity. In SPMD, point-to-point is ppermute (used by pipeline)."""
    ax = _axis(group)
    _stat("collective-permute", tensor)
    if in_spmd_context():
        from ..core.dispatch import apply

        n = jax.lax.psum(1, ax)
        return apply(lambda v: jax.lax.ppermute(v, ax, [(i, dst) for i in range(n)]), tensor)
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)
    _stat("collective-permute", tensor)
    if in_spmd_context():
        from ..core.dispatch import apply

        n = jax.lax.psum(1, ax)
        out = apply(lambda v: jax.lax.ppermute(v, ax, [(src, i) for i in range(n)]), tensor)
        tensor._data = out._data
        tensor._node = out._node
    return tensor


def p2p_shift(x, axis_name, shift=1):
    """Ring shift (ppermute) — the building block of ring attention and 1F1B."""
    _stat("collective-permute", x)
    idx_pairs = None

    def fn(v):
        n = jax.lax.psum(1, axis_name)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(v, axis_name, perm)

    if isinstance(x, Tensor):
        from ..core.dispatch import apply

        return apply(fn, x)
    return fn(x)


def client_reduce(x, op=ReduceOp.SUM, axis_name="clients", placed=True,
                  kind="federated_sum", compress=None, compress_key=None):
    """The federated MapReduce reduce chokepoint (paddle_tpu.federated).

    Every cross-client aggregation funnels through here so it inherits the
    collective discipline for free: byte metering
    (``collective_bytes_total{op=federated_*}``), the ``collective/call``
    failpoint, an instantaneous ``collective/<op>`` span, and — once the
    EQuARX-style quantized reduces land (ROADMAP item 2) — whatever
    compression the chokepoint grows. Two placements:

    - ``placed=True`` — inside a ``client_map`` body (a vmap/shard_map axis
      named `axis_name` is in scope): lowers to ``jax.lax.psum``/``pmean``/
      ... on the named axis, which XLA differentiates and, when the clients
      axis is sharded over a mesh, schedules as a real cross-device reduce;
    - ``placed=False`` — server-side on a clients-leading array: reduces
      axis 0 (the eager FedAvg aggregation path).

    Like every collective here, a call inside a jit trace is counted once
    per TRACE (host-side accounting). ``compress=8`` (or ``True``) opts a
    placed SUM/AVG into the int8 quantized reduce — the EQuARX-style wire
    format the trainer's FLAGS_quantized_allreduce uses, with the same
    straight-through gradient, metered as
    ``collective_bytes_total{op=kind}`` wire bytes +
    ``collective_bytes_saved_total{op=kind}``."""
    bits = _compress_bits(compress)
    if bits is not None:
        return _compressed_reduce(x, op, axis_name, bits, kind,
                                  key=compress_key, placed=placed,
                                  leading=not placed)
    _stat(kind, x)

    def named(v):
        if op in (ReduceOp.SUM, "sum"):
            return jax.lax.psum(v, axis_name)
        if op in (ReduceOp.MAX, "max"):
            return jax.lax.pmax(v, axis_name)
        if op in (ReduceOp.MIN, "min"):
            return jax.lax.pmin(v, axis_name)
        if op in (ReduceOp.AVG, "avg"):
            return jax.lax.pmean(v, axis_name)
        raise ValueError(f"client_reduce: unsupported op {op!r}")

    def leading(v):
        v = jnp.asarray(v)
        if op in (ReduceOp.SUM, "sum"):
            return jnp.sum(v, axis=0)
        if op in (ReduceOp.MAX, "max"):
            return jnp.max(v, axis=0)
        if op in (ReduceOp.MIN, "min"):
            return jnp.min(v, axis=0)
        if op in (ReduceOp.AVG, "avg"):
            return jnp.mean(v, axis=0)
        raise ValueError(f"client_reduce: unsupported op {op!r}")

    fn = named if placed else leading
    if isinstance(x, Tensor):
        from ..core.dispatch import apply

        return apply(fn, x)
    return fn(x)


def barrier(group=None):
    if in_spmd_context():
        return
    if _env.get_world_size() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def get_rank(group=None):
    return _env.get_rank()


def get_world_size(group=None):
    return _env.get_world_size()


def is_initialized():
    return _env.is_initialized()


def get_group(id=0):
    return _DEFAULT_GROUP


def wait(tensor, group=None, use_calc_stream=True):
    """c_sync_*_stream parity: XLA orders collectives — block for API compat."""
    if isinstance(tensor, Tensor) and hasattr(tensor._data, "block_until_ready"):
        tensor._data.block_until_ready()
    return tensor


def destroy_process_group(group=None):
    pass


# ---- SyncBatchNorm functional (used by nn.SyncBatchNorm under SPMD) -----------
def sync_batch_norm(x, running_mean, running_var, weight, bias, training, momentum,
                    epsilon, data_format):
    from ..core.dispatch import apply

    ax = _axis(None)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    def fn(v, w, b):
        m = jax.lax.pmean(jnp.mean(v, axis=reduce_axes), ax)
        var = jax.lax.pmean(jnp.mean(v * v, axis=reduce_axes), ax) - m * m
        out = (v - m.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        return out * w.reshape(shape) + b.reshape(shape)

    return apply(fn, x, weight, bias)
