"""Elastic auto-resume supervisor (FLAGS_elastic; docs/DISTRIBUTED.md
"Elastic training").

Production fleets lose and gain slices; a preemption mid-step must not
turn a dp8 run into a dead run. This module closes the loop the
checkpoint layer opened: :class:`ElasticSupervisor` wraps a train loop
and wires three existing recovery mechanisms into one retry-with-backoff
policy —

- the PR 4 :class:`CheckpointSaver` corrupt-fallback walk-back
  (incubate/checkpoint/auto_checkpoint.py): the newest READABLE
  checkpoint wins, unreadable ones are evicted loudly;
- the topology-aware restore (distributed/spmd.py
  ``restore_train_state``): the checkpoint's ``shard_specs`` leaf lets
  it land on a DIFFERENT dp factorization, so the supervisor resumes on
  a shrunken mesh when the original shape is gone — [dp, shard] moments
  re-laid bit-exactly, ``__qar_residual__`` EF residuals folded;
- the PR 7 blackbox flight recorder: every recovery writes a crash
  bundle (when the recorder is armed) and a ring note naming the
  reason, the failed step, and the replacement mesh — recoveries are
  attributable, never silent.

Every recovery also lands in ``elastic_resume_total{reason}`` (lazy —
the family only exists once something was actually recovered) and, under
``FLAGS_perf_ledger``, a ledger row at site ``elastic/resume`` so
recovery cost shows up in the cross-run ledger next to step time.

This module is manifest-lazy (analysis/import_graph.py LAZY_MODULES):
with ``FLAGS_elastic`` unset nothing imports it and a plain trainer is
byte-identical to the pre-elastic build (tests/test_elastic_gate.py).
"""
import contextlib
import time

import numpy as np

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox
from ..testing import failpoints as _fp

__all__ = ["ElasticSupervisor"]

_ELASTIC_RESUME = None  # lazy elastic_resume_total — shared family with
#                         stage.py's stage_replace call site (the
#                         registry is get-or-create by name)


def _note_resume(reason):
    global _ELASTIC_RESUME
    if not _monitor.is_enabled():
        return
    if _ELASTIC_RESUME is None:
        _ELASTIC_RESUME = _monitor.counter(
            "elastic_resume_total",
            "elastic recoveries by reason (failpoint | nonfinite | crash "
            "from the supervisor's resume path, stage_replace from MPMD "
            "stage rebinding); zero unless FLAGS_elastic machinery "
            "actually recovered something",
            labelnames=("reason",))
    _ELASTIC_RESUME.labels(reason=reason).inc()


def _classify(exc):
    if isinstance(exc, _fp.FailpointError):
        return "failpoint"
    if isinstance(exc, FloatingPointError):
        return "nonfinite"
    return "crash"


class ElasticSupervisor:
    """Retry-with-backoff auto-resume around a step loop.

    ::

        saver = CheckpointSaver(ckpt_dir)
        sup = ElasticSupervisor(
            build_trainer,                      # mesh -> SpmdTrainer
            saver,
            mesh_factories=[full_mesh_or_none,  # preference order;
                            shrunken_mesh],     # None = shape is gone
            checkpoint_interval=1)
        losses = sup.run(batches)               # indexable batch tuples

    ``build_trainer(mesh)`` constructs a fresh trainer on the given
    mesh; ``mesh_factories`` is walked in preference order on every
    (re)build — a factory returning ``None`` means that topology is
    currently infeasible (its slice was preempted), so recovery falls
    through to the next, shrunken, shape. The restored checkpoint
    reshards onto whatever factorization won (``shard_specs``).

    A step that raises consumes one retry: the failure is classified
    (``failpoint`` — an injected :class:`FailpointError` —, ``nonfinite``
    or ``crash``), bundled/noted, and the loop resumes from the newest
    readable checkpoint, replaying any steps since it. Retries beyond
    ``max_retries`` re-raise the original error. Each attempt sleeps
    ``backoff_s * attempt`` and passes the registered ``elastic/resume``
    failpoint (so retry exhaustion is itself chaos-testable).
    """

    def __init__(self, build_trainer, saver, mesh_factories,
                 checkpoint_interval=1, max_retries=3, backoff_s=0.0):
        if not _flags.get_flag("elastic", False):
            raise RuntimeError(
                "ElasticSupervisor requires FLAGS_elastic=1 — the flag "
                "is structural (it keys the trainer's executables) and "
                "gates this module's import (docs/DISTRIBUTED.md)")
        if not mesh_factories:
            raise ValueError("mesh_factories must name at least one "
                             "candidate topology")
        self.build_trainer = build_trainer
        self.saver = saver
        self.mesh_factories = list(mesh_factories)
        self.checkpoint_interval = max(1, int(checkpoint_interval))
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.trainer = None
        self.recoveries = []   # [{reason, step, mesh, downtime_ms}]
        # goodput accountant (FLAGS_goodput, ISSUE 20): consumed at
        # construction like the trainer's copy — the recovery leg books
        # `resume_backoff` (with the nested checkpoint load / reshard
        # booking their own buckets); disarmed, one `is not None`
        self._goodput = None
        if _flags.get_flag("goodput", False):
            from ..monitor import goodput as _goodput

            self._goodput = _goodput

    def _next_mesh(self):
        for factory in self.mesh_factories:
            mesh = factory()
            if mesh is not None:
                return mesh
        raise RuntimeError(
            "no feasible mesh: every mesh_factories candidate returned "
            "None (all topologies preempted)")

    def _resume(self, trainer):
        """Restore the newest readable checkpoint (corrupt-fallback
        walk-back built into the saver) onto `trainer`; returns the next
        step index to run."""
        state, meta = self.saver.load_checkpoint()
        if state is None:
            return 0
        trainer.set_state_dict(state)
        return int((meta or {}).get("step", -1)) + 1

    def run(self, batches):
        """Drive ``trainer.train_step(*batches[i])`` over every batch,
        checkpointing every ``checkpoint_interval`` steps and auto-
        resuming on failure. Returns the loss trajectory (one float per
        batch index; replayed steps overwrite, so the trajectory is the
        one the SURVIVING lineage trained)."""
        mesh = self._next_mesh()
        self.trainer = self.build_trainer(mesh)
        step = self._resume(self.trainer)
        losses = {}
        retries = 0
        n = len(batches)
        while step < n:
            try:
                loss = self.trainer.train_step(*batches[step])
                losses[step] = float(
                    np.asarray(getattr(loss, "_data", loss)))
                if (step + 1) % self.checkpoint_interval == 0:
                    self.saver.save_checkpoint(self.trainer.state_dict(),
                                               meta={"step": step})
                step += 1
            except Exception as exc:   # noqa: BLE001 — classified below
                reason = _classify(exc)
                retries += 1
                if retries > self.max_retries:
                    raise
                t_fail = time.perf_counter()
                _blackbox.note("elastic_resume", reason=reason,
                               step=step, retries=retries,
                               error=f"{type(exc).__name__}: {exc}")
                if _blackbox.is_enabled():
                    # PR 7 crash bundle: ring + providers + env, the
                    # post-mortem that names THIS recovery
                    _blackbox.dump("crash", site="elastic/resume",
                                   extra={"reason": reason, "step": step,
                                          "retries": retries})
                _fp.failpoint("elastic/resume")
                if self._goodput is not None:
                    self._goodput.count("resume")
                # the whole recovery leg is `resume_backoff`; the
                # checkpoint load and any cross-topology re-layout inside
                # _resume nest their own ckpt_restore/reshard buckets,
                # pausing this one (exclusive attribution)
                with (self._goodput.bucket("resume_backoff")
                      if self._goodput is not None
                      else contextlib.nullcontext()):
                    if self.backoff_s:
                        time.sleep(self.backoff_s * retries)
                    mesh = self._next_mesh()
                    self.trainer = self.build_trainer(mesh)
                    step = self._resume(self.trainer)
                downtime_ms = (time.perf_counter() - t_fail) * 1e3
                _note_resume(reason)
                rec = {"reason": reason, "step": step,
                       "mesh": tuple(mesh.shape.values()),
                       "downtime_ms": downtime_ms}
                self.recoveries.append(rec)
                _blackbox.note("elastic_resumed", **rec)
                if _flags.get_flag("perf_ledger", False):
                    from ..monitor import perfledger as _perfledger

                    # force=True: every recovery lands a row;
                    # check=False: downtime is out-of-distribution by
                    # nature, it must not poison step-time baselines
                    _perfledger.get_ledger().on_step(
                        "elastic/resume",
                        {"downtime_ms": downtime_ms,
                         "retries": retries, "resume_step": step},
                        mesh=mesh, force=True, check=False)
        return [losses[i] for i in sorted(losses)]
