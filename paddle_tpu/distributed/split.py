"""Tensor (model) parallel building blocks.

Reference parity: python/paddle/distributed/collective.py:566 `split` (cases :581-605),
_parallel_linear:492, _parallel_embedding:526 — row/column-parallel Linear and parallel
Embedding with gather/allreduce.

TPU-native design: the layers carry a PartitionSpec for their weights (axis 'mp');
under SpmdTrainer/pjit, XLA partitions the matmuls and inserts the psum/all_gather the
reference builds manually with c_allreduce/c_concat ops. In eager single-process mode
they behave as ordinary layers (full weights).
"""
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer


class ColumnParallelLinear(Layer):
    """operation 'linear' with axis=1 in distributed.split (weight cols sharded)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, name=None, mp_axis="mp"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight.spmd_spec = P(None, mp_axis)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        if self.bias is not None:
            self.bias.spmd_spec = P(mp_axis)
        self.gather_output = gather_output

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """operation 'linear' with axis=0 (weight rows sharded; output psum'ed by XLA)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, name=None, mp_axis="mp"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight.spmd_spec = P(mp_axis, None)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """operation 'embedding' in distributed.split (vocab rows sharded)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, name=None, mp_axis="mp"):
        super().__init__()
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight.spmd_spec = P(mp_axis, None)

    def forward(self, x):
        return F.embedding(x, self.weight)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity (collective.py:566): returns a parallel layer
    applied to x. On TPU `num_partitions` must equal the 'mp' mesh-axis size (checked
    at trainer build)."""
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f, weight_attr, bias_attr is not False, gather_out)
        else:
            layer = RowParallelLinear(in_f, out_f, weight_attr, bias_attr is not False)
        return layer(x)
    if operation == "embedding":
        n, d = size
        layer = VocabParallelEmbedding(n, d, weight_attr)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r}")


def collect_spmd_specs(layer):
    """Gather {param_name: PartitionSpec} from layers built with parallel specs."""
    out = {}
    for n, p in layer.named_parameters():
        spec = getattr(p, "spmd_spec", None)
        if spec is not None:
            out[n] = spec
    return out
