"""Mixture-of-Experts with expert parallelism (GShard/Switch-style).

No reference equivalent — SURVEY.md §2.3 (last row) records expert parallelism as
ABSENT in thisjiang/Paddle and requires the TPU build to exceed the reference here.

TPU-native design (not a port of any CUDA MoE):
- gating/dispatch/combine are einsums over a *static* capacity axis, so every shape is
  fixed at trace time and XLA tiles the expert FFN matmuls onto the MXU as one batched
  [E, tokens_per_expert, d] x [E, d, dff] contraction;
- expert parallelism = `shard_map` over the 'ep' mesh axis with two
  `jax.lax.all_to_all`s (tokens -> owning expert rank and back), the ICI-native
  equivalent of the NCCL alltoall a GPU MoE would use;
- the load-balance auxiliary loss is the GShard loss: E * sum_e(frac_tokens_e * mean_prob_e).

All functions here are pure jnp functions over raw arrays (usable under jit/vjp);
`paddle_tpu.nn.MoELayer` wraps them for the Layer API.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def compute_capacity(num_tokens, num_experts, k, capacity_factor, multiple_of=4):
    """Static per-shard expert capacity: ceil(k*T/E * factor), padded up."""
    cap = int(math.ceil(num_tokens * k / num_experts * capacity_factor))
    cap = max(multiple_of, ((cap + multiple_of - 1) // multiple_of) * multiple_of)
    return min(cap, num_tokens)


def topk_gating(logits, k, capacity):
    """Top-k gating with static capacity.

    logits: [T, E]. Returns (combine [T, E, C] f32, dispatch [T, E, C] bool, aux_loss).

    Tokens beyond an expert's capacity (in token order, higher-priority choice first —
    the GShard policy) are dropped for that expert; combine weights are the top-k
    softmax probabilities renormalized over the *kept* choices.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)  # [T, k]

    counts = jnp.zeros((E,), jnp.int32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    dispatch = jnp.zeros((T, E, capacity), bool)
    kept_prob_sum = jnp.zeros((T,), jnp.float32)

    for j in range(k):
        idx_j = topi[:, j]  # [T]
        mask_j = jax.nn.one_hot(idx_j, E, dtype=jnp.int32)  # [T, E]
        # position of each token in its chosen expert's queue (this choice level)
        pos_in_expert = jnp.cumsum(mask_j, axis=0) - 1 + counts[None, :]  # [T, E]
        pos_j = jnp.sum(pos_in_expert * mask_j, axis=1)  # [T]
        keep = pos_j < capacity
        counts = counts + jnp.sum(mask_j, axis=0)
        onehot_pos = jax.nn.one_hot(pos_j, capacity, dtype=jnp.float32)  # [T, C]
        sel = (mask_j.astype(jnp.float32) * keep[:, None].astype(jnp.float32))  # [T, E]
        disp_j = sel[:, :, None] * onehot_pos[:, None, :]  # [T, E, C]
        dispatch = dispatch | (disp_j > 0)
        combine = combine + topv[:, j][:, None, None] * disp_j
        kept_prob_sum = kept_prob_sum + topv[:, j] * keep.astype(jnp.float32)

    # renormalize combine weights over kept choices
    denom = jnp.where(kept_prob_sum > 0, kept_prob_sum, 1.0)
    combine = combine / denom[:, None, None]

    # GShard load-balance loss over the top-1 assignment
    frac_tokens = jnp.mean(jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(frac_tokens * mean_prob)
    return combine, dispatch, aux_loss


def expert_ffn(xe, w1, b1, w2, b2, activation=jax.nn.gelu):
    """Batched per-expert FFN. xe: [E, C, d]; w1: [E, d, f]; w2: [E, f, d]."""
    h = jnp.einsum("ecd,edf->ecf", xe, w1) + b1[:, None, :]
    h = activation(h)
    return jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]


def moe_dense(x, gate_w, w1, b1, w2, b2, k=2, capacity_factor=2.0,
              activation=jax.nn.gelu):
    """Single-shard MoE: x [T, d] through E experts. Returns (out [T, d], aux_loss)."""
    T, d = x.shape
    E = gate_w.shape[1]
    capacity = compute_capacity(T, E, k, capacity_factor)
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    combine, dispatch, aux = topk_gating(logits, k, capacity)
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E, C, d]
    ye = expert_ffn(xe, w1, b1, w2, b2, activation)
    out = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye)
    return out.astype(x.dtype), aux


def moe_spmd(x, gate_w, w1, b1, w2, b2, k=2, capacity_factor=2.0,
             activation=jax.nn.gelu, axis_name="ep"):
    """Expert-parallel MoE body for use inside shard_map.

    x: [T_local, d] this rank's tokens. w1/b1/w2/b2 hold only this rank's local
    experts ([E_local, ...]); gate_w is replicated [d, E_total]. Tokens are routed to
    the rank owning their expert with all_to_all over `axis_name` and routed back
    after the expert FFN.
    """
    ep = jax.lax.psum(1, axis_name)
    T, d = x.shape
    E = gate_w.shape[1]
    E_local = w1.shape[0]
    assert E_local * ep == E, "experts must shard evenly over the ep axis"
    capacity = compute_capacity(T, E, k, capacity_factor)

    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    combine, dispatch, aux = topk_gating(logits, k, capacity)

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E, C, d]
    # group by owning rank and exchange: [ep, E_local, C, d] -> rows from every rank
    xe = xe.reshape(ep, E_local, capacity, d)
    xe = jax.lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=0, tiled=False)
    # now axis 0 = source rank; fold into the capacity axis per local expert
    xe = jnp.moveaxis(xe, 0, 1).reshape(E_local, ep * capacity, d)

    ye = expert_ffn(xe, w1, b1, w2, b2, activation)

    ye = jnp.moveaxis(ye.reshape(E_local, ep, capacity, d), 1, 0)
    ye = jax.lax.all_to_all(ye, axis_name, split_axis=0, concat_axis=0, tiled=False)
    ye = ye.reshape(E, capacity, d)

    out = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye).astype(x.dtype)
    return out, jax.lax.pmean(aux, axis_name)


def expert_parallel_moe(x, gate_w, w1, b1, w2, b2, mesh, k=2, capacity_factor=2.0,
                        activation=jax.nn.gelu, axis_name="ep"):
    """shard_map wrapper: x [T, d] sharded on tokens, experts sharded over `axis_name`.

    Returns (out [T, d], aux_loss scalar). Differentiable.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:  # older jax keeps it under experimental
        from jax.experimental.shard_map import shard_map as _sm
    body = functools.partial(moe_spmd, k=k, capacity_factor=capacity_factor,
                             activation=activation, axis_name=axis_name)
    fn = _sm(
        body, mesh=mesh,
        in_specs=(P(axis_name, None), P(None, None),
                  P(axis_name, None, None), P(axis_name, None),
                  P(axis_name, None, None), P(axis_name, None)),
        out_specs=(P(axis_name, None), P()),
    )
    return fn(x, gate_w, w1, b1, w2, b2)
