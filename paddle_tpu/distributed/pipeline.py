"""Pipeline parallelism.

Reference parity: PipelineOptimizer (fleet/meta_optimizers/pipeline_optimizer.py:25)
splits the program into device-guard sections; PipelineTrainer + SectionWorker run
micro-batches with the 1F1B schedule (framework/section_worker.cc:98-141, schedule
comment :129); P2P via send_v2/recv_v2 ops.

TPU-native design: the model is a list of stage Layers; the whole pipeline is ONE
shard_map over the 'pp' mesh axis. Every rank holds its stage's params; activations
move between ranks with ppermute each tick. The schedule is the classic pipelined loop
(n_micro + n_stages - 1 ticks): tick t gives rank r micro-batch (t - r) — i.e. GPipe
filling/draining expressed as a lax.fori_loop; XLA overlaps the ppermute with compute.
Gradient = jax.grad through the whole scanned schedule (no hand-written 1F1B backward —
autodiff produces the reverse schedule mechanically).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tape import global_tape
from ..core.tensor import Tensor


def _smap(f, mesh, in_specs, out_specs):
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


class PipelineStage:
    """One stage = a pure fn(params, x) -> y derived from a Layer."""

    def __init__(self, layer):
        self.layer = layer

    def pure(self, params, x):
        named = dict(self.layer.named_parameters())
        saved = {n: t._data for n, t in named.items()}
        try:
            for n, v in params.items():
                named[n]._data = v
            with global_tape().pause():
                out = self.layer(Tensor(x))
            return out._data if isinstance(out, Tensor) else out
        finally:
            for n, t in named.items():
                t._data = saved[n]


def _stack_stage_params(stages):
    """Stack per-stage param pytrees along a leading 'pp' axis (stages must be
    structurally identical, like transformer blocks)."""
    names = [n for n, _ in stages[0].layer.named_parameters()]
    stacked = {}
    for n in names:
        arrs = [dict(s.layer.named_parameters())[n]._data for s in stages]
        stacked[n] = jnp.stack(arrs, axis=0)
    return stacked


class Pipeline:
    """1F1B/GPipe pipeline over the 'pp' mesh axis (homogeneous stages).

    loss_head(params_head, y, label) -> scalar runs on the last rank.
    """

    def __init__(self, stages, mesh, axis_name="pp", n_micro=None):
        assert len(stages) == mesh.shape[axis_name], "one stage per pp rank"
        self.stages = [PipelineStage(s) for s in stages]
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_stages = len(stages)
        self.n_micro = n_micro or self.n_stages
        self.stage_fn = self.stages[0].pure  # homogeneous structure

    def forward_fn(self):
        """Returns pure fn(stacked_params, x_micro[b...]) -> y (final stage output),
        to be wrapped in shard_map by the caller or used via run()."""
        ax = self.axis_name
        n_stage = self.n_stages
        n_micro = self.n_micro
        stage_fn = self.stage_fn

        def spmd(params_sharded, x_all):
            # params_sharded: leading pp dim is the local shard (size 1) -> strip it
            # x_all: [n_micro, mb, ...] — replicated input micro-batches
            params_my = {k: v[0] for k, v in params_sharded.items()}
            r = jax.lax.axis_index(ax)
            n_ticks = n_micro + n_stage - 1
            y_shape = x_all.shape[1:]

            def _vary(arr):
                # mark carry init as device-varying over 'pp' (shard_map vma typing)
                try:
                    return jax.lax.pcast(arr, (ax,), to="varying")
                except (AttributeError, TypeError):
                    return jax.lax.pvary(arr, (ax,))

            buf = _vary(jnp.zeros_like(x_all[0]))  # activation held by this rank
            outs = _vary(jnp.zeros((n_micro,) + y_shape, x_all.dtype))
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

            def tick(t, carry):
                buf, outs = carry
                mb_idx = t - r  # micro-batch this rank works on at tick t
                active = (mb_idx >= 0) & (mb_idx < n_micro)
                # rank 0 ingests a fresh micro-batch; others use what arrived
                x_in = jnp.where(
                    r == 0,
                    x_all[jnp.clip(t, 0, n_micro - 1)],
                    buf,
                )
                y = stage_fn(params_my, x_in)
                y = jnp.where(active, y, jnp.zeros_like(y))
                # last rank records its finished micro-batch
                outs = jnp.where(
                    (r == n_stage - 1) & active,
                    outs.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                    outs,
                )
                # send activation to next rank
                buf_next = jax.lax.ppermute(y, ax, perm)
                return buf_next, outs

            _, outs = jax.lax.fori_loop(0, n_ticks, tick, (buf, outs))
            # only the last rank recorded nonzero outputs -> psum replicates them
            return jax.lax.psum(outs, ax)

        return spmd

    def run(self, x):
        """Forward the full batch through the pipeline; returns final-stage outputs."""
        ax = self.axis_name
        params = _stack_stage_params(self.stages)
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        mb = x.shape[0] // self.n_micro
        x_micro = x.reshape((self.n_micro, mb) + x.shape[1:])
        spmd = self.forward_fn()
        param_specs = {k: P(ax) for k in params}
        mapped = _smap(spmd, self.mesh, in_specs=(param_specs, P()), out_specs=P())
        outs = mapped(params, x_micro)
        return Tensor(outs.reshape((self.n_micro * mb,) + outs.shape[2:]))
