"""Pipeline parallelism.

Reference parity: PipelineOptimizer (fleet/meta_optimizers/pipeline_optimizer.py:25)
splits the program into device-guard sections; PipelineTrainer + SectionWorker run
micro-batches with the 1F1B schedule (framework/section_worker.cc:98-141, schedule
comment :129); P2P via send_v2/recv_v2 ops.

TPU-native design: the model is a list of stage Layers; the whole pipeline is ONE
shard_map over the 'pp' mesh axis. Every rank holds its stage's params; activations
move between ranks with ppermute each tick. The schedule is the classic pipelined loop
(n_micro + n_stages - 1 ticks): tick t gives rank r micro-batch (t - r) — i.e. GPipe
filling/draining expressed as a lax.fori_loop; XLA overlaps the ppermute with compute.
Gradient = jax.grad through the whole scanned schedule (no hand-written 1F1B backward —
autodiff produces the reverse schedule mechanically).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import flags as _flags
from ..core.tape import global_tape
from ..core.tensor import Tensor
from .spmd import _pvary as _vary   # the ONE device-varying carry helper

#: The stage-boundary transfer edge (ISSUE 13; docs/ANALYSIS.md
#: "Declaring a transfer edge"): what one pipeline rank's ppermute hands
#: the next rank every tick. The static auditor
#: (analysis/handoff_schema.py) AST-extracts this literal and pins its
#: fingerprint in tests/handoff_baseline.json; PipelineTrainer validates
#: its stage activation against the same declaration at build time
#: (``mb`` binds to the micro-batch rows, ``...`` covers the stage's
#: feature dims, ``$act`` the activation dtype). ROADMAP 3's MPMD
#: stage-program abstraction types its transfer edges with exactly this
#: payload form.
HANDOFF_SCHEMA = {
    "edge": "pipeline_stage",
    "producer": ("paddle_tpu/distributed/pipeline.py::"
                 "PipelineTrainer._pipelined"),
    "consumer": ("paddle_tpu/distributed/pipeline.py::"
                 "PipelineTrainer.train_step"),
    "runtime_checked": True,
    "doc": "one micro-batch of stage activations, carried rank->rank by "
           "the ppermute ring each schedule tick",
    "payload": {
        "activation": {"shape": ("mb", "..."), "dtype": "$act",
                       "layout": "[micro_batch, *stage_features]"},
    },
}


def _smap(f, mesh, in_specs, out_specs):
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _pure_call(layer, params, *args):
    """Call `layer` as a pure function of a params dict (name -> array)."""
    from ..core.functional import functional_state

    with functional_state(layer, params), global_tape().pause():
        out = layer(*[Tensor(a) if not isinstance(a, Tensor) else a for a in args])
        return out._data if isinstance(out, Tensor) else out


class PipelineStage:
    """One stage = a pure fn(params, x) -> y derived from a Layer."""

    def __init__(self, layer):
        self.layer = layer

    def pure(self, params, x):
        return _pure_call(self.layer, params, x)


def _stack_stage_params(stages):
    """Stack per-stage param pytrees along a leading 'pp' axis (stages must be
    structurally identical, like transformer blocks)."""
    layers = [getattr(s, "layer", s) for s in stages]
    names = [n for n, _ in layers[0].named_parameters()]
    stacked = {}
    for n in names:
        arrs = [dict(l.named_parameters())[n]._data for l in layers]
        stacked[n] = jnp.stack(arrs, axis=0)
    return stacked


class Pipeline:
    """1F1B/GPipe pipeline over the 'pp' mesh axis (homogeneous stages).

    loss_head(params_head, y, label) -> scalar runs on the last rank.
    """

    def __init__(self, stages, mesh, axis_name="pp", n_micro=None):
        assert len(stages) == mesh.shape[axis_name], "one stage per pp rank"
        self.stages = [PipelineStage(s) for s in stages]
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_stages = len(stages)
        self.n_micro = n_micro or self.n_stages
        self.stage_fn = self.stages[0].pure  # homogeneous structure

    def forward_fn(self):
        """Returns pure fn(stacked_params, x_micro[b...]) -> y (final stage output),
        to be wrapped in shard_map by the caller or used via run()."""
        ax = self.axis_name
        n_stage = self.n_stages
        n_micro = self.n_micro
        stage_fn = self.stage_fn

        def spmd(params_sharded, x_all):
            # params_sharded: leading pp dim is the local shard (size 1) -> strip it
            # x_all: [n_micro, mb, ...] — replicated input micro-batches
            params_my = {k: v[0] for k, v in params_sharded.items()}
            r = jax.lax.axis_index(ax)
            n_ticks = n_micro + n_stage - 1
            y_shape = x_all.shape[1:]

            # mark carry inits as device-varying over 'pp' (the module-
            # level _vary: shard_map vma typing, identity fallback)
            buf = _vary(jnp.zeros_like(x_all[0]), ax)  # rank-held activation
            outs = _vary(jnp.zeros((n_micro,) + y_shape, x_all.dtype), ax)
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

            def tick(t, carry):
                buf, outs = carry
                mb_idx = t - r  # micro-batch this rank works on at tick t
                active = (mb_idx >= 0) & (mb_idx < n_micro)
                # rank 0 ingests a fresh micro-batch; others use what arrived
                x_in = jnp.where(
                    r == 0,
                    x_all[jnp.clip(t, 0, n_micro - 1)],
                    buf,
                )
                y = stage_fn(params_my, x_in)
                y = jnp.where(active, y, jnp.zeros_like(y))
                # last rank records its finished micro-batch
                outs = jnp.where(
                    (r == n_stage - 1) & active,
                    outs.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                    outs,
                )
                # send activation to next rank
                buf_next = jax.lax.ppermute(y, ax, perm)
                return buf_next, outs

            _, outs = jax.lax.fori_loop(0, n_ticks, tick, (buf, outs))
            # only the last rank recorded nonzero outputs -> psum replicates them
            return jax.lax.psum(outs, ax)

        return spmd

    def run(self, x):
        """Forward the full batch through the pipeline; returns final-stage outputs."""
        ax = self.axis_name
        params = _stack_stage_params(self.stages)
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        mb = x.shape[0] // self.n_micro
        x_micro = x.reshape((self.n_micro, mb) + x.shape[1:])
        spmd = self.forward_fn()
        param_specs = {k: P(ax) for k in params}
        mapped = _smap(spmd, self.mesh, in_specs=(param_specs, P()), out_specs=P())
        outs = mapped(params, x_micro)
        return Tensor(outs.reshape((self.n_micro * mb,) + outs.shape[2:]))


# ---------------------------------------------------------------------------
# Pipeline *training* — fwd + bwd + optimizer across stages
# ---------------------------------------------------------------------------

class PipelineTrainer:
    """Pipeline-parallel TRAINING over a pp(×dp) mesh — one jitted step.

    Reference parity: PipelineTrainer + SectionWorker's micro-batch schedule
    (framework/section_worker.cc:98-141) and PipelineOptimizer's program split
    (fleet/meta_optimizers/pipeline_optimizer.py:25). There, each device runs a
    program section and grads flow stage-to-stage via send_v2/recv_v2.

    TPU-native design (GSPMD-style "pipelining as collective permute"): the model
    is (pre, stages, post_loss) — embedding, N structurally identical stage
    layers, and a head+loss layer. Stage params are STACKED on a leading axis
    sharded over 'pp'; the GPipe fill/drain schedule (n_micro + n_stages - 1
    ticks, rank r works micro-batch t - r at tick t) is a lax.scan whose
    activations move between ranks with ppermute inside a shard_map that is
    manual over 'pp' and automatic over 'dp' (XLA inserts the dp grad psum).
    The backward schedule is autodiff's reversal of the forward scan — a drain/
    fill mirror, mechanically correct without hand-written 1F1B send/recv.

    Memory profile (honest note): reverse-mode through the scanned schedule
    retains O(n_ticks) per-tick residuals — the GPipe profile, not true 1F1B's
    O(n_stages). schedule_mode='1F1B' reclaims that headroom the TPU way:
    jax.checkpoint on each stage tick drops intra-stage residuals and recomputes
    them in the backward sweep, bounding live memory to the scan carries
    (one activation per tick) — the same peak-memory class 1F1B targets.
    schedule_mode='F-then-B' keeps all residuals (fastest, most memory).

    `pre` and `post_loss` params are replicated over pp (every rank computes
    them; only rank 0's / the psum'd last-rank path carries gradients — XLA
    dead-code-eliminates the rest).
    """

    def __init__(self, pre, stages, post_loss, optimizer, mesh=None,
                 pp_axis="pp", dp_axis="dp", n_micro=None,
                 schedule_mode="1F1B", donate=True, stage_param_specs=None,
                 stage_meshes=None, compress=None):
        """stage_param_specs: optional {stage_param_name: PartitionSpec}
        (collect_spmd_specs of one stage) adding a TENSOR-PARALLEL axis under
        the pipeline: stacked stage params shard P('pp', *spec) and XLA's
        sharding propagation inserts the mp collectives inside each stage
        tick (the shard_map is manual over pp only; dp/mp stay automatic) —
        3-axis pp x dp x mp hybrid parallelism.

        stage_meshes / compress apply only under FLAGS_mpmd
        (distributed/stage.py): an explicit per-stage mesh list (unequal
        device counts allowed) and int8 edge quantization (compress=8) for
        the activation edges. With the flag unset both must stay None —
        passing them is a config error, not a silent no-op."""
        from .mesh import get_mesh

        from .split import collect_spmd_specs

        self.mesh = mesh or get_mesh()
        assert pp_axis in self.mesh.axis_names, f"mesh needs a '{pp_axis}' axis"
        self.stage_param_specs = dict(stage_param_specs or {})
        if self.stage_param_specs:
            known = {n for n, _ in stages[0].named_parameters()}
            unknown = sorted(set(self.stage_param_specs) - known)
            if unknown:
                raise ValueError(
                    f"stage_param_specs names no stage-0 params: {unknown} "
                    "— pass collect_spmd_specs(stages[0]) (stage-local "
                    "names), not full-model paths")
        # pre/post tensor-parallel specs (vocab-parallel embedding, split lm
        # head — the largest GPT tensors) apply automatically when present
        self.pre_param_specs = collect_spmd_specs(pre)
        self.post_param_specs = collect_spmd_specs(post_loss)
        self.pre = pre
        self.stage_layers = list(stages)
        self.post_loss = post_loss
        self.optimizer = optimizer
        self.pp_axis = pp_axis
        self.dp_axis = dp_axis if dp_axis in self.mesh.axis_names else None
        self.n_stages = self.mesh.shape[pp_axis]
        assert len(self.stage_layers) == self.n_stages, \
            f"{len(self.stage_layers)} stages for pp={self.n_stages}"
        self.n_micro = n_micro or self.n_stages
        self.schedule_mode = schedule_mode
        self.donate = donate
        self._compiled = None
        self._edge_checked = False

        # stage params must be uniformly trainable across stages (they are one
        # stacked array) — a per-stage freeze cannot be expressed, so reject it
        stacked = _stack_stage_params(self.stage_layers)
        stage0_named = dict(self.stage_layers[0].named_parameters())
        for i, s in enumerate(self.stage_layers[1:], start=1):
            for n, p in s.named_parameters():
                if getattr(p, "trainable", True) != getattr(
                        stage0_named[n], "trainable", True):
                    raise ValueError(
                        f"stage {i} param '{n}' trainable flag differs from "
                        "stage 0; stacked pipeline stages must be uniformly "
                        "trainable — freeze the same params on every stage")
        self.params, self.frozen = {}, {}
        for n, p in pre.named_parameters():
            dst = self.params if getattr(p, "trainable", True) else self.frozen
            dst["pre::" + n] = p._data
        for n, v in stacked.items():
            trainable = getattr(stage0_named[n], "trainable", True)
            (self.params if trainable else self.frozen)["stage::" + n] = v
        for n, p in post_loss.named_parameters():
            dst = self.params if getattr(p, "trainable", True) else self.frozen
            dst["post::" + n] = p._data
        self.opt_state = optimizer.functional_init(self.params)
        self._place_state()
        # MPMD stage-program runtime (distributed/stage.py): the flag is
        # consumed HERE — the armed trainer builds per-stage programs and
        # typed edges over the state placed above, so a post-construction
        # toggle raises (_mpmd_active) instead of silently switching
        # schedulers mid-run. Only the armed path imports the module.
        self._mpmd = bool(_flags.get_flag("mpmd", False))
        self._mpmd_runner = None
        if not self._mpmd and (stage_meshes is not None
                               or compress is not None):
            raise ValueError(
                "stage_meshes/compress are MPMD edge options "
                "(distributed/stage.py) — set FLAGS_mpmd before "
                "constructing the trainer")
        if self._mpmd:
            from . import stage as _stage_mod

            self._mpmd_runner = _stage_mod.MpmdPipelineRunner(
                self, stage_meshes=stage_meshes, compress=compress)

    def _mpmd_active(self):
        """FLAGS_mpmd was consumed at construction (the stage programs
        and edges are built then); a post-construction toggle is loud
        instead of silently swapping schedulers. One get_flag + compare
        when disarmed."""
        m = bool(_flags.get_flag("mpmd", False))
        if m != self._mpmd:
            raise RuntimeError(
                "FLAGS_mpmd changed after this PipelineTrainer was "
                "constructed; the stage programs and transfer edges are "
                "built at __init__ — build a new PipelineTrainer under "
                "the new flag value")
        return self._mpmd

    def numerics_fetch(self):
        """Numerics-telescope drain hook (testing/parity.py lockstep
        harness). The pipeline step doesn't thread the telescope — same
        carve-out as localsgd/DGC — so there is never anything to
        fetch."""
        return None

    # -- sharding placement ----------------------------------------------------
    def _sharding_for(self, name):
        grp, local = name.split("::", 1)
        if grp == "stage":
            spec = self.stage_param_specs.get(local)
            if spec is not None:
                # stacked stage param: leading pp dim + the stage-local
                # tensor-parallel spec on the remaining dims
                return NamedSharding(self.mesh, P(self.pp_axis, *spec))
            return NamedSharding(self.mesh, P(self.pp_axis))
        spec = (self.pre_param_specs if grp == "pre"
                else self.post_param_specs).get(local)
        if spec is not None and all(
                ax is None or ax in self.mesh.axis_names
                for d in spec for ax in
                ((d,) if not isinstance(d, tuple) else d)):
            return NamedSharding(self.mesh, P(*spec))
        return NamedSharding(self.mesh, P())

    def _place_state(self):
        from .spmd import owned_device_put

        self.p_shardings = {k: self._sharding_for(k) for k in self.params}
        self.params = {k: owned_device_put(v, self.p_shardings[k])
                       for k, v in self.params.items()}
        self.f_shardings = {k: self._sharding_for(k) for k in self.frozen}
        self.frozen = {k: jax.device_put(v, self.f_shardings[k])
                       for k, v in self.frozen.items()}
        self.s_shardings, new_state = {}, {}
        for pname, st in self.opt_state.items():
            if pname == "__step__":
                self.s_shardings[pname] = NamedSharding(self.mesh, P())
                new_state[pname] = owned_device_put(st, self.s_shardings[pname])
                continue
            sub_sh, sub = {}, {}
            for k, v in st.items():
                sh = (self._sharding_for(pname)
                      if hasattr(v, "ndim") and v.ndim > 0
                      else NamedSharding(self.mesh, P()))
                sub_sh[k] = sh
                sub[k] = owned_device_put(v, sh)
            self.s_shardings[pname] = sub_sh
            new_state[pname] = sub
        self.opt_state = new_state

    # -- the scheduled pipeline forward ---------------------------------------
    def _pipelined(self, stage_params, h_micro):
        """[n_micro, mb, ...] -> final-stage outputs [n_micro, mb, ...]."""
        ax = self.pp_axis
        n_stage, n_micro = self.n_stages, self.n_micro
        stage0 = self.stage_layers[0]
        base_fn = functools.partial(_pure_call, stage0)
        fn = jax.checkpoint(base_fn) if self.schedule_mode == "1F1B" else base_fn

        def spmd(params_sh, x_all):
            params_my = {k: v[0] for k, v in params_sh.items()}
            r = jax.lax.axis_index(ax)
            n_ticks = n_micro + n_stage - 1
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            buf0 = _vary(jnp.zeros_like(x_all[0]), ax)

            def tick(buf, t):
                mb_idx = t - r
                active = (mb_idx >= 0) & (mb_idx < n_micro)
                x_in = jnp.where(r == 0, x_all[jnp.clip(t, 0, n_micro - 1)], buf)
                y = fn(params_my, x_in)
                y = jnp.where(active, y, jnp.zeros_like(y))
                y_out = jnp.where(r == n_stage - 1, y, jnp.zeros_like(y))
                return jax.lax.ppermute(y, ax, perm), y_out

            _, ys = jax.lax.scan(tick, buf0, jnp.arange(n_ticks))
            # last rank finishes micro-batch m at tick m + n_stage - 1
            outs = ys[n_stage - 1:n_stage - 1 + n_micro]
            return jax.lax.psum(outs, ax)  # replicate from the last rank

        specs = {k: P(ax) for k in stage_params}
        try:
            mapped = jax.shard_map(spmd, mesh=self.mesh, in_specs=(specs, P()),
                                   out_specs=P(), axis_names={ax})
        except (AttributeError, TypeError):  # older jax: full-manual shard_map
            if self.stage_param_specs:
                import warnings

                warnings.warn(
                    "this jax lacks shard_map auto axes: the full-manual "
                    "fallback replicates stage params over the tensor-"
                    "parallel axis, dropping stage_param_specs sharding")
            mapped = _smap(spmd, self.mesh, in_specs=(specs, P()), out_specs=P())
        return mapped(stage_params, h_micro)

    # -- jitted train step ------------------------------------------------------
    def _build(self):
        pre, post = self.pre, self.post_loss

        def split_tree(flat, frozen):
            t = {"pre": {}, "stage": {}, "post": {}}
            for k, v in {**frozen, **flat}.items():
                grp, name = k.split("::", 1)
                t[grp][name] = v
            return t

        def step(params, opt_state, frozen, lr, x_micro, y_micro):
            def loss_fn(flat):
                t = split_tree(flat, frozen)
                h = jax.vmap(lambda xi: _pure_call(pre, t["pre"], xi))(x_micro)
                outs = self._pipelined(t["stage"], h)
                losses = jax.vmap(
                    lambda oi, yi: _pure_call(post, t["post"], oi, yi)
                )(outs, y_micro)
                return jnp.mean(losses.astype(jnp.float32))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_state = self.optimizer.functional_apply(
                params, grads, opt_state, lr=lr)
            return loss, new_params, new_state

        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(
            self.mesh, P(None, self.dp_axis) if self.dp_axis else P())
        donate = (0, 1) if self.donate else ()
        return jax.jit(
            step,
            in_shardings=(self.p_shardings, dict(self.s_shardings),
                          self.f_shardings, repl, batch_sh, batch_sh),
            out_shardings=(repl, self.p_shardings, dict(self.s_shardings)),
            donate_argnums=donate,
        )

    def train_step(self, x, y):
        """x, y: full batch [B, ...]; B must divide by n_micro (and dp on the
        micro-batch dim). Returns the mean loss over all micro-batches."""
        x = x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
        y = y._data if isinstance(y, Tensor) else jnp.asarray(np.asarray(y))
        assert x.shape[0] % self.n_micro == 0, \
            f"batch {x.shape[0]} not divisible by n_micro={self.n_micro}"
        mb = x.shape[0] // self.n_micro
        x_micro = x.reshape((self.n_micro, mb) + x.shape[1:])
        y_micro = y.reshape((self.n_micro, mb) + y.shape[1:])
        if not self._edge_checked:
            self._validate_stage_edge(x_micro)
        if self._mpmd_active():
            loss = self._mpmd_runner.train_step(x_micro, y_micro)
            self.optimizer._step_count += 1
            return Tensor(loss)
        if self._compiled is None:
            self._compiled = self._build()
        lr = jnp.asarray(self.optimizer.get_lr(), dtype=jnp.float32)
        loss, self.params, self.opt_state = self._compiled(
            self.params, self.opt_state, self.frozen, lr, x_micro, y_micro)
        self.optimizer._step_count += 1
        return Tensor(loss)

    def _validate_stage_edge(self, x_micro):
        """Typed transfer edge (ISSUE 13): shape-infer one micro-batch
        through `pre` (eval_shape — nothing executes) and validate the
        activation the ppermute ring will carry against HANDOFF_SCHEMA —
        the same declaration the static auditor extracts and baselines.
        Runs once per trainer; raises HandoffMismatch naming the leaf."""
        from ..analysis import handoff_schema as _hs

        pre_params = {k.split("::", 1)[1]: v
                      for k, v in {**self.frozen, **self.params}.items()
                      if k.startswith("pre::")}
        act = jax.eval_shape(
            lambda p, xi: _pure_call(self.pre, p, xi), pre_params,
            jax.ShapeDtypeStruct(tuple(x_micro.shape[1:]), x_micro.dtype))
        # "$act" binds to the STAGES' compute dtype (their first floating
        # param), not to the payload's own dtype — the check must be able
        # to fail when `pre` hands the ring an activation the stacked
        # stage programs do not compute in
        stage_dt = next(
            (str(v.dtype) for k, v in {**self.params, **self.frozen}.items()
             if k.startswith("stage::")
             and jnp.issubdtype(v.dtype, jnp.floating)), str(act.dtype))
        _hs.validate(HANDOFF_SCHEMA, {"activation": act},
                     dims={"mb": int(x_micro.shape[1])},
                     dtypes={"act": stage_dt})
        self._edge_checked = True

    def sync_to_layer(self):
        """Write trained params back into pre/stages/post Layer tensors.

        Copies (never aliases) the trainer's arrays: the jitted step donates
        self.params, so handing those buffers to the Layer would let the next
        train_step invalidate the Layer's eager tensors."""
        pre_named = dict(self.pre.named_parameters())
        post_named = dict(self.post_loss.named_parameters())
        stage_named = [dict(s.named_parameters()) for s in self.stage_layers]
        for k, v in self.params.items():
            grp, name = k.split("::", 1)
            if grp == "pre":
                pre_named[name]._data = jnp.asarray(jax.device_get(v))
            elif grp == "post":
                post_named[name]._data = jnp.asarray(jax.device_get(v))
            else:
                host = jax.device_get(v)
                for i, named in enumerate(stage_named):
                    named[name]._data = jnp.asarray(host[i])

    # -- checkpoint / resume ---------------------------------------------------
    def state_dict(self):
        """Host-side checkpoint of the pipeline train state (stacked stage
        params + pre/post params + optimizer moments + step counters + LR
        scheduler); restore with set_state_dict for bit-exact resume."""
        from .spmd import gather_train_state

        return gather_train_state(self.params, self.opt_state,
                                  self.optimizer)

    def set_state_dict(self, state):
        from .spmd import restore_train_state

        self.params, self.opt_state = restore_train_state(
            state, self.p_shardings, self.s_shardings, self.optimizer)
