"""Long-context sequence/context parallelism: ring attention + Ulysses.

No reference equivalent — SURVEY.md §5 records SP/CP as ABSENT in thisjiang/Paddle
(sequence length there is scaled only via recompute/pipeline). These are TPU-native
additions required by the build plan (SURVEY.md §2.3 last row, §7 step 7):

- ring attention: sequence-sharded Q stays resident; K/V blocks rotate around the ICI
  ring with jax.lax.ppermute while a running (max, sum, acc) online-softmax merges each
  block — memory O(seq/N), compute overlapped with the rotation.
- Ulysses: all_to_all swaps the sharded axis from sequence to heads before standard
  attention and back after — cheap on ICI, needs heads % sp == 0.

Both are pure functions over raw arrays meant to be called inside shard_map bodies
(axis name 'sp'); `ring_attention`/`ulysses_attention` wrap them for Layer use.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .spmd import _pvary as _vary   # the ONE device-varying carry helper


# the one source of truth for sequence-parallel attention impl names
# (GPTConfig validates against this same tuple)
VALID_SP_IMPLS = ("ring", "ring_flash", "ulysses", "ulysses_flash")


def _block_attn(q, k, v, scale, causal_mask=None):
    """Plain softmax stats for one K/V block: returns (acc, m, l)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal_mask is not None:
        s = jnp.where(causal_mask, s, -1e30)
    m = jnp.max(s, axis=-1)  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return acc, m, l


def ring_attention_spmd(q, k, v, axis_name="sp", causal=False):
    """Blockwise ring attention inside shard_map.

    q,k,v: [batch, seq_shard, heads, head_dim] (this rank's sequence shard).
    Rotates K/V around the ring; merges blocks with online softmax.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape

    def mask_for(block_rank):
        if not causal:
            return None
        # global positions: q at idx*sq + i ; k at block_rank*sq + j
        qi = idx * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        kj = block_rank * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        return (qi >= kj)[None, None]  # [1,1,q,k]

    def body(i, carry):
        k_blk, v_blk, acc, m_run, l_run = carry
        src_rank = (idx - i) % n  # which rank's K/V we now hold
        blk_acc, m_blk, l_blk = _block_attn(q, k_blk, v_blk, scale, mask_for(src_rank))
        m_new = jnp.maximum(m_run, m_blk)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_blk - m_new)
        l_new = l_run * alpha + l_blk * beta
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + blk_acc * beta.transpose(0, 2, 1)[..., None]
        # rotate K/V to the next rank (ride the ICI ring)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_next, v_next, acc, m_new, l_new

    acc0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32), axis_name)
    m0 = _vary(jnp.full((b, h, sq), -1e30, jnp.float32), axis_name)
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32), axis_name)
    _, _, acc, m_fin, l_fin = jax.lax.fori_loop(
        0, n, body, (k.astype(jnp.float32), v.astype(jnp.float32), acc0, m0, l0)
    )
    out = acc / l_fin.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ring attention with per-block Pallas flash kernels (forward AND backward).
# ---------------------------------------------------------------------------

def _fold_heads(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _unfold_heads(x3, b, h):
    bh, s, d = x3.shape
    return jnp.swapaxes(x3.reshape(b, h, s, d), 1, 2)


def _ring_flash_fwd(q, k, v, axis_name, causal, interpret):
    """Ring forward with flash-kernel blocks: returns (out [b,sq,h,d],
    lse [b*h, sq] f32 — the GLOBAL row logsumexp, exactly what the flash
    backward kernels need per ring pair)."""
    from ..ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q3 = _fold_heads(q)

    def pair(k3, v3, src):
        if not causal:
            return fa._flash_fwd(q3, k3, v3, False, scale, interpret)
        return jax.lax.switch(
            jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2)),
            (lambda: fa._flash_fwd(q3, k3, v3, False, scale, interpret),
             lambda: fa._flash_fwd(q3, k3, v3, True, scale, interpret),
             lambda: (jnp.zeros_like(q3),
                      jnp.full((b * h, sq), -1e30, jnp.float32))))

    def body(i, carry):
        k3_blk, v3_blk, o_run, lse_run = carry
        src = (idx - i) % n
        o_blk, lse_blk = pair(k3_blk, v3_blk, src)
        # merge normalized per-block outputs via logsumexp weights:
        # sum_i o_i * exp(lse_i - lse_tot) == acc_tot / l_tot
        lse_new = jnp.logaddexp(lse_run, lse_blk)
        o_run = (o_run * jnp.exp(lse_run - lse_new)[..., None]
                 + o_blk.astype(jnp.float32)
                 * jnp.exp(lse_blk - lse_new)[..., None])
        perm = [(r, (r + 1) % n) for r in range(n)]
        return (jax.lax.ppermute(k3_blk, axis_name, perm),
                jax.lax.ppermute(v3_blk, axis_name, perm), o_run, lse_new)

    o0 = _vary(jnp.zeros((b * h, sq, d), jnp.float32), axis_name)
    lse0 = _vary(jnp.full((b * h, sq), -1e30, jnp.float32), axis_name)
    # fold heads ONCE; the ring carries [b*h, sq, d] blocks (ppermute is
    # layout-agnostic), avoiding per-hop transpose copies
    _, _, o_fin, lse_fin = jax.lax.fori_loop(
        0, n, body, (_fold_heads(k), _fold_heads(v), o0, lse0))
    return _unfold_heads(o_fin, b, h).astype(q.dtype), lse_fin


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention_spmd(q, k, v, axis_name="sp", causal=False,
                              interpret=False):
    """Ring attention whose per-block math runs the Pallas flash kernels
    (ops/flash_attention.py) instead of materializing [sq, sq] score
    blocks: per-rank memory O(sq * blk) in the kernel, O(sq) merge state.
    Differentiable — the custom VJP re-rotates K/V and calls the flash
    BACKWARD kernels per ring pair with the global (out, lse, dout), whose
    row-local form makes per-pair calls exact contributions to the global
    softmax gradient; dK/dV partial sums ride the ring with their block
    and arrive home after n hops. The flash-fusion step the r2 kernel
    docstring planned. interpret=True runs the kernels on CPU (tests)."""
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, interpret)
    return out


def _rf_fwd(q, k, v, axis_name, causal, interpret):
    out, lse = _ring_flash_fwd(q, k, v, axis_name, causal, interpret)
    return out, (q, k, v, out, lse)


def _rf_bwd(axis_name, causal, interpret, res, g):
    from ..ops import flash_attention as fa

    q, k, v, out, lse = res
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q3, o3 = _fold_heads(q), _fold_heads(out)
    do3 = _fold_heads(g).astype(q3.dtype)
    # delta = rowsum(dO * O) is hop-invariant: compute once for all pairs
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)

    def pair_bwd(k3, v3, src):
        def run(causal_flag):
            return fa._flash_bwd(q3, k3, v3, o3, lse, do3, causal_flag,
                                 scale, interpret, delta=delta)
        if not causal:
            return run(False)
        return jax.lax.switch(
            jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2)),
            (lambda: run(False), lambda: run(True),
             lambda: (jnp.zeros_like(q3), jnp.zeros_like(q3),
                      jnp.zeros_like(q3))))

    def body(i, carry):
        k3_blk, v3_blk, dk_acc, dv_acc, dq_run = carry
        src = (idx - i) % n
        dq_c, dk_c, dv_c = pair_bwd(k3_blk, v3_blk, src)
        dq_run = dq_run + dq_c.astype(jnp.float32)
        # dK/dV partial sums belong to the block currently held: they
        # rotate WITH it and are complete when the block arrives home
        dk_acc = dk_acc + dk_c.astype(jnp.float32)
        dv_acc = dv_acc + dv_c.astype(jnp.float32)
        perm = [(r, (r + 1) % n) for r in range(n)]
        rot = lambda x: jax.lax.ppermute(x, axis_name, perm)
        return rot(k3_blk), rot(v3_blk), rot(dk_acc), rot(dv_acc), dq_run

    z3 = lambda: _vary(jnp.zeros((b * h, sq, d), jnp.float32), axis_name)
    _, _, dk_fin, dv_fin, dq_fin = jax.lax.fori_loop(
        0, n, body, (_fold_heads(k), _fold_heads(v), z3(), z3(), z3()))
    return (_unfold_heads(dq_fin, b, h).astype(q.dtype),
            _unfold_heads(dk_fin, b, h).astype(k.dtype),
            _unfold_heads(dv_fin, b, h).astype(v.dtype))


ring_flash_attention_spmd.defvjp(_rf_fwd, _rf_bwd)


def ulysses_attention_spmd(q, k, v, axis_name="sp", causal=False,
                           use_flash=False, interpret=False):
    """Ulysses (DeepSpeed-style) attention inside shard_map.

    Input: [batch, seq_shard, heads, head_dim] sequence-sharded.
    all_to_all -> [batch, seq_full, heads_shard, head_dim], full attention locally,
    all_to_all back. Needs heads % sp_size == 0. With use_flash the local
    attention runs the differentiable Pallas flash kernel (full-seq must be
    a multiple of 128, head_dim of 64) instead of materializing [s, s].
    """
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [b, s/n, h, d] -> [b, s, h/n, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scale = 1.0 / math.sqrt(q.shape[-1])
    if use_flash:
        from ..ops import flash_attention as fa

        b, h_loc = qh.shape[0], qh.shape[2]  # _flash derives its own scale
        o3 = fa._flash(_fold_heads(qh), _fold_heads(kh), _fold_heads(vh),
                       causal, interpret)
        return heads_to_seq(_unfold_heads(o3, b, h_loc)).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
    if causal:
        sq = s.shape[-2]
        mask = jnp.tril(jnp.ones((sq, sq), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return heads_to_seq(out).astype(q.dtype)


def sequence_parallel_attention(q, k, v, mesh, impl="ring", causal=False,
                                axis_name="sp", interpret=False):
    """Convenience wrapper: shard_map over the 'sp' axis of `mesh` on seq
    dim 1. impl: 'ring' (einsum blocks), 'ring_flash' (Pallas flash-kernel
    blocks — per-shard seq must be a multiple of 128), 'ulysses', or
    'ulysses_flash' (local attention through the flash kernel — FULL seq
    must be a multiple of 128). interpret applies to the *_flash impls
    (CPU kernel interpretation; auto-on off-TPU)."""
    from jax.sharding import NamedSharding

    try:
        from jax import shard_map as _sm

        def smap(f, **kw):
            return _sm(f, **kw)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm

        smap = _sm

    if impl.endswith("_flash") and not interpret:
        # off-TPU the kernels only run interpreted — auto-enable so models
        # configured with a *_flash impl work on the CPU test mesh
        from ..ops.flash_attention import _on_tpu

        interpret = not _on_tpu()
    if impl == "ring":
        body = functools.partial(ring_attention_spmd, axis_name=axis_name,
                                 causal=causal)
    elif impl == "ring_flash":
        body = functools.partial(ring_flash_attention_spmd,
                                 axis_name=axis_name, causal=causal,
                                 interpret=interpret)
    elif impl in ("ulysses", "ulysses_flash"):
        body = functools.partial(ulysses_attention_spmd,
                                 axis_name=axis_name, causal=causal,
                                 use_flash=impl == "ulysses_flash",
                                 interpret=interpret)
    else:
        raise ValueError(
            f"impl must be one of {'|'.join(VALID_SP_IMPLS)}, got {impl!r}")
    spec = P(None, axis_name, None, None)
    kw = {}
    if impl.endswith("_flash"):
        # pallas_call's out_shape carries no vma typing; skip the check
        kw["check_vma"] = False
    try:
        mapped = smap(body, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, **kw)
    except TypeError:
        # older jax spells the knob check_rep; keep the check off when
        # the flash body's pallas_call outputs carry no vma typing
        try:
            mapped = smap(body, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec,
                          **({"check_rep": False} if kw else {}))
        except TypeError:  # no replication-check knob in this jax at all
            mapped = smap(body, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return mapped(q, k, v)


def full_attention_reference(q, k, v, causal=False):
    """Unsharded reference for tests."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
