"""Long-context sequence/context parallelism: ring attention + Ulysses.

No reference equivalent — SURVEY.md §5 records SP/CP as ABSENT in thisjiang/Paddle
(sequence length there is scaled only via recompute/pipeline). These are TPU-native
additions required by the build plan (SURVEY.md §2.3 last row, §7 step 7):

- ring attention: sequence-sharded Q stays resident; K/V blocks rotate around the ICI
  ring with jax.lax.ppermute while a running (max, sum, acc) online-softmax merges each
  block — memory O(seq/N), compute overlapped with the rotation.
- Ulysses: all_to_all swaps the sharded axis from sequence to heads before standard
  attention and back after — cheap on ICI, needs heads % sp == 0.

Both are pure functions over raw arrays meant to be called inside shard_map bodies
(axis name 'sp'); `ring_attention`/`ulysses_attention` wrap them for Layer use.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _block_attn(q, k, v, scale, causal_mask=None):
    """Plain softmax stats for one K/V block: returns (acc, m, l)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal_mask is not None:
        s = jnp.where(causal_mask, s, -1e30)
    m = jnp.max(s, axis=-1)  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return acc, m, l


def ring_attention_spmd(q, k, v, axis_name="sp", causal=False):
    """Blockwise ring attention inside shard_map.

    q,k,v: [batch, seq_shard, heads, head_dim] (this rank's sequence shard).
    Rotates K/V around the ring; merges blocks with online softmax.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape

    def mask_for(block_rank):
        if not causal:
            return None
        # global positions: q at idx*sq + i ; k at block_rank*sq + j
        qi = idx * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        kj = block_rank * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        return (qi >= kj)[None, None]  # [1,1,q,k]

    def body(i, carry):
        k_blk, v_blk, acc, m_run, l_run = carry
        src_rank = (idx - i) % n  # which rank's K/V we now hold
        blk_acc, m_blk, l_blk = _block_attn(q, k_blk, v_blk, scale, mask_for(src_rank))
        m_new = jnp.maximum(m_run, m_blk)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_blk - m_new)
        l_new = l_run * alpha + l_blk * beta
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + blk_acc * beta.transpose(0, 2, 1)[..., None]
        # rotate K/V to the next rank (ride the ICI ring)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_next, v_next, acc, m_new, l_new

    def _vary(x):
        # mark carry init as device-varying over the ring axis (shard_map vma typing)
        try:
            return jax.lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            return jax.lax.pvary(x, (axis_name,))

    acc0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    m0 = _vary(jnp.full((b, h, sq), -1e30, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32))
    _, _, acc, m_fin, l_fin = jax.lax.fori_loop(
        0, n, body, (k.astype(jnp.float32), v.astype(jnp.float32), acc0, m0, l0)
    )
    out = acc / l_fin.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention_spmd(q, k, v, axis_name="sp", causal=False):
    """Ulysses (DeepSpeed-style) attention inside shard_map.

    Input: [batch, seq_shard, heads, head_dim] sequence-sharded.
    all_to_all -> [batch, seq_full, heads_shard, head_dim], full attention locally,
    all_to_all back. Needs heads % sp_size == 0.
    """
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [b, s/n, h, d] -> [b, s, h/n, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
    if causal:
        sq = s.shape[-2]
        mask = jnp.tril(jnp.ones((sq, sq), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return heads_to_seq(out).astype(q.dtype)


def sequence_parallel_attention(q, k, v, mesh, impl="ring", causal=False, axis_name="sp"):
    """Convenience wrapper: shard_map over the 'sp' axis of `mesh` on seq dim 1."""
    from jax.sharding import NamedSharding

    try:
        from jax import shard_map as _sm

        def smap(f, **kw):
            return _sm(f, **kw)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm

        smap = _sm

    fn = ring_attention_spmd if impl == "ring" else ulysses_attention_spmd
    spec = P(None, axis_name, None, None)
    body = functools.partial(fn, axis_name=axis_name, causal=causal)
    mapped = smap(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return mapped(q, k, v)


def full_attention_reference(q, k, v, causal=False):
    """Unsharded reference for tests."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
