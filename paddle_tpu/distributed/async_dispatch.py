"""Async double-buffered step dispatch (FLAGS_async_dispatch).

jax already dispatches device work asynchronously; what serializes a
train loop is the HOST — per-step verdict fetches, batch marshalling,
admission bookkeeping — standing between one dispatch and the next.
This module holds the host-side machinery the flag arms
(docs/PERF.md):

- :class:`StepHandle` — the lazy step result ``SpmdTrainer.train_step``
  returns under the flag. It IS a :class:`~paddle_tpu.core.tensor.Tensor`
  (the loss), so every existing caller keeps working; ``result()``
  blocks for the device value and ``scheduled_step`` names the schedule
  position the step was dispatched at.
- the ``async_*`` metric families (created here, lazily, so a
  flags-unset process never grows the series) and the blackbox provider
  table, so a crash/stall bundle records how deep the in-flight
  deferred-verdict window was when the process wedged.

The deferred-verdict ledger itself lives on the trainer
(``SpmdTrainer._pending_verdicts``): the non-async path defers the
guard fetch by ONE step (docs/PERF.md "deferred guard") without ever
importing this module — gate-pinned by tests/test_async_gate.py.
"""
import numpy as np

from .. import monitor as _monitor
from ..core.tensor import Tensor

__all__ = ["StepHandle", "window_depth_gauge", "verdict_fetch_counter",
           "blackbox_table"]

_DEPTH_G = None
_FETCH_C = None


def window_depth_gauge(site="trainer"):
    """``async_window_depth{site}`` — pending deferred verdicts at the
    moment of a drain (how far the host ran ahead of the device's
    verdicts). Labeled so ``monitor.reset()`` drops the children and the
    family reads empty again (the metrics_dump --async missing-series
    contract)."""
    global _DEPTH_G
    if _DEPTH_G is None:
        _DEPTH_G = _monitor.gauge(
            "async_window_depth",
            "deferred non-finite-guard verdicts in flight when a drain "
            "fetched them (FLAGS_async_dispatch; docs/PERF.md)",
            labelnames=("site",))
    return _DEPTH_G.labels(site=site)


def verdict_fetch_counter(site="trainer"):
    """``async_verdict_fetch_total{site}`` — host syncs spent on guard
    verdicts: ONE per drain, covering up to FLAGS_async_window steps."""
    global _FETCH_C
    if _FETCH_C is None:
        _FETCH_C = _monitor.counter(
            "async_verdict_fetch_total",
            "deferred guard-verdict drains (each fetches every pending "
            "verdict in one device_get; <= 1 per FLAGS_async_window "
            "steps on the steady-state async path)",
            labelnames=("site",))
    return _FETCH_C.labels(site=site)


class StepHandle(Tensor):
    """Lazy train-step result: a Tensor wrapping the (async-dispatched)
    device loss, plus the step's schedule identity. Materializing it in
    any Tensor way (``float()``, ``.numpy()``, ``np.asarray``) blocks
    for the device value — fetch at a window boundary, not per step."""

    def __init__(self, loss_data, scheduled_step, trainer=None):
        super().__init__(loss_data)
        #: optimizer schedule position this step was dispatched at
        self.scheduled_step = int(scheduled_step)
        self._trainer = trainer

    def result(self):
        """Block for the loss AND drain any pending guard verdicts (so
        a deferred FloatingPointError surfaces here, not on an unrelated
        later call). Returns the loss as a float."""
        if self._trainer is not None:
            self._trainer.guard_sync()
        return float(np.asarray(self._data))


def blackbox_table(trainer):
    """The trainer's async-dispatch state for a blackbox dump bundle:
    how deep the deferred-verdict window was when the process wedged."""
    return {
        "window": trainer._async_window,
        "pending": len(trainer._pending_verdicts),
        "max_depth": trainer._window_max_depth,
        "verdict_fetches": trainer._verdict_fetches,
        "nonfinite_skipped_total": trainer._nonfinite_total,
        "nonfinite_streak": trainer._nonfinite_streak,
        "prefetch_hits": trainer._prefetch_hits,
    }
