"""Device mesh management — the Place/ring-id world replaced by jax.sharding.Mesh.

Reference parity: NCCLCommContext's ring-id -> communicator map
(platform/collective_helper.h:67) becomes named mesh axes; process groups become
sub-meshes. Axis naming convention across the framework:
  'dp' data parallel | 'sharding' ZeRO | 'mp' tensor/model parallel |
  'pp' pipeline | 'sp' sequence/context parallel | 'ep' expert parallel.
"""
import contextlib

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_CURRENT_MESH = [None]


def build_mesh(mesh_shape=None, axis_names=None, devices=None):
    """Build a Mesh over the available devices (default: 1-axis 'dp' over all)."""
    devs = devices if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devs),)
        axis_names = axis_names or ("dp",)
    axis_names = tuple(axis_names)
    arr = np.array(devs).reshape(tuple(mesh_shape))
    return Mesh(arr, axis_names)


def set_mesh(mesh):
    _CURRENT_MESH[0] = mesh
    return mesh


def get_mesh():
    if _CURRENT_MESH[0] is None:
        _CURRENT_MESH[0] = build_mesh()
    return _CURRENT_MESH[0]


@contextlib.contextmanager
def mesh_scope(mesh):
    old = _CURRENT_MESH[0]
    _CURRENT_MESH[0] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _CURRENT_MESH[0] = old


def sharding(*spec, mesh=None):
    return NamedSharding(mesh or get_mesh(), P(*spec))


def replicated(mesh=None):
    return NamedSharding(mesh or get_mesh(), P())
