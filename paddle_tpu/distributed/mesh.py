"""Device mesh management — the Place/ring-id world replaced by jax.sharding.Mesh.

Reference parity: NCCLCommContext's ring-id -> communicator map
(platform/collective_helper.h:67) becomes named mesh axes; process groups become
sub-meshes. Axis naming convention across the framework:
  'dp' data parallel | 'sharding' ZeRO | 'mp' tensor/model parallel |
  'pp' pipeline | 'sp' sequence/context parallel | 'ep' expert parallel |
  'clients' federated MapReduce (paddle_tpu.federated, docs/FEDERATED.md).
"""
import contextlib

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_CURRENT_MESH = [None]


def build_mesh(mesh_shape=None, axis_names=None, devices=None):
    """Build a Mesh over the available devices (default: 1-axis 'dp' over all)."""
    devs = devices if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devs),)
        axis_names = axis_names or ("dp",)
    axis_names = tuple(axis_names)
    arr = np.array(devs).reshape(tuple(mesh_shape))
    return Mesh(arr, axis_names)


def set_mesh(mesh):
    _CURRENT_MESH[0] = mesh
    return mesh


def get_mesh():
    if _CURRENT_MESH[0] is None:
        _CURRENT_MESH[0] = build_mesh()
    return _CURRENT_MESH[0]


@contextlib.contextmanager
def mesh_scope(mesh):
    old = _CURRENT_MESH[0]
    _CURRENT_MESH[0] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _CURRENT_MESH[0] = old


def client_mesh(n_clients, inner_shape=(), inner_names=(), devices=None):
    """A Mesh with a leading federated ``clients`` axis composing with the
    SPMD axes: ``client_mesh(4)`` shards 4 clients over 4 devices;
    ``client_mesh(2, (2,), ("dp",))`` gives each of 2 clients a 2-device dp
    sub-mesh. Arrays whose leading axis is the clients dimension shard over
    the ``clients`` axis (paddle_tpu.federated.client_map does this when
    handed this mesh); everything inside one client's shard uses the inner
    axes exactly as plain SPMD code does."""
    inner_shape = tuple(int(s) for s in inner_shape)
    need = int(n_clients) * int(np.prod(inner_shape, dtype=np.int64)
                                if inner_shape else 1)
    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < need:
        raise ValueError(
            f"client_mesh needs {need} devices for {n_clients} clients x "
            f"{inner_shape or (1,)} inner mesh, have {len(devs)}")
    return build_mesh((int(n_clients),) + inner_shape,
                      ("clients",) + tuple(inner_names),
                      devices=devs[:need])


def sharding(*spec, mesh=None):
    return NamedSharding(mesh or get_mesh(), P(*spec))


def replicated(mesh=None):
    return NamedSharding(mesh or get_mesh(), P())
