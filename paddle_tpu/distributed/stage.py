"""MPMD stage programs: per-stage compiled programs on their own mesh
slices, connected by typed, validated, backpressured transfer edges.

The repo ran two parallel per-stage hand-off systems — the pipeline
trainer's single-program ppermute schedule (distributed/pipeline.py) and
the serving pool's prefill→decode hand-off (serving/disagg.py). This
module is the unification ROADMAP item 3 named, the MPMD
pipeline-parallelism design of arXiv:2412.14374 (PAPERS.md): each stage
is its OWN compiled program on its OWN mesh (unequal per-stage device
counts allowed), and what moves between stages is a typed payload on a
:class:`StageEdge` — declared as a ``HANDOFF_SCHEMA`` literal
(analysis/handoff_schema.py), validated on every ``put``, bounded
(``EdgeFullError`` is the backpressure signal, never silent loss), and
metered at the existing ``kv_handoff_bytes_total`` chokepoint.

Three pieces:

- :class:`StageEdge` — a capacity-bounded FIFO whose payloads are
  validated against a declared schema. ``compress=8`` encodes every
  ``quantizable`` leaf through the EQuARX-style int8 row codec
  (distributed/compress.py, arXiv:2506.17615): wire bytes land in
  ``kv_handoff_bytes_total``, the displaced fp32 bytes in
  ``collective_bytes_saved_total{op="stage_edge"}`` — wire-vs-logical
  accounting identical to the quantized all-reduce's.
- :class:`StageProgram` — one pure function + its mesh, compiled through
  the PR 3 AOT cache with the stage's OWN ``mesh_fingerprint`` (and its
  name) in the cache key: a warmed ``FLAGS_jit_cache_dir`` disk-hits
  per stage, per topology.
- :class:`StageGraph` — the MPMD runner: executes a schedule of
  (stage, thunk) ticks, each under a ``stage_step`` span sharing ONE
  trace_id (a ``stage_graph`` root) and a blackbox progress window, so a
  stalled stage is named by the stall sentinel.

:class:`MpmdPipelineRunner` re-bases ``PipelineTrainer`` on the graph
(armed by ``FLAGS_mpmd`` at trainer construction): the 1F1B /
F-then-B / interleaved schedules become tick orderings over per-stage
forward/backward programs whose activations and grads ride typed edges —
no hand-rolled ppermute bookkeeping. ``DisaggregatedPool`` routes its
prefill→decode hand-off over a :class:`StageEdge` validating the SAME
``disagg_kv`` declaration ``ServingEngine.admit_prefilled`` enforces.

This module is manifest-lazy (analysis/import_graph.py): with
``FLAGS_mpmd`` unset nothing imports it and the plain trainer/engine are
byte-identical to the pre-PR build (tests/test_stage_gate.py).
"""
import collections
import contextlib
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade
from .. import trace as _trace
from ..framework import aot as _aot
from ..testing import failpoints as _fp

__all__ = ["StageEdge", "StageProgram", "StageGraph", "EdgeFullError",
           "EdgeEmptyError", "MpmdPipelineRunner", "HANDOFF_SCHEMA",
           "HANDOFF_SCHEMA_GRAD"]

#: The MPMD stage-boundary activation edge (docs/ANALYSIS.md "Declaring a
#: transfer edge"): one micro-batch of transformer-stage activations,
#: carried stage->stage by a typed edge instead of the ppermute ring.
#: ``mb`` binds to the micro-batch rows, ``t``/``d`` to the stage's
#: sequence/feature dims, ``$act`` to the stages' compute dtype. The leaf
#: is ``quantizable``: a ``compress=8`` edge moves the int8
#: (values, scales) pair — per-last-axis-row symmetric, deterministic
#: rounding (compress.quantize_rows) — and the consumer decodes against
#: this same declaration.
HANDOFF_SCHEMA = {
    "edge": "mpmd_activation",
    "producer": "paddle_tpu/distributed/stage.py::StageEdge.put",
    "consumer": "paddle_tpu/distributed/stage.py::StageEdge.get",
    "runtime_checked": True,
    "doc": "one micro-batch of stage activations moving over a typed "
           "MPMD stage edge (forward direction)",
    "payload": {
        "activation": {"shape": ("mb", "t", "d"), "dtype": "$act",
                       "layout": "[micro_batch, seq, features]",
                       "quantizable": True},
    },
}

#: The backward twin: the loss gradient w.r.t. a stage boundary
#: activation. Grad edges stay DENSE even under ``compress=8`` —
#: quantizing the backward signal compounds the forward quantization
#: error, so only the forward direction trades bits for bandwidth.
HANDOFF_SCHEMA_GRAD = {
    "edge": "mpmd_grad",
    "producer": "paddle_tpu/distributed/stage.py::StageEdge.put",
    "consumer": "paddle_tpu/distributed/stage.py::StageEdge.get",
    "runtime_checked": True,
    "doc": "the loss gradient w.r.t. one micro-batch of stage-boundary "
           "activations (backward direction; never quantized)",
    "payload": {
        "grad": {"shape": ("mb", "t", "d"), "dtype": "$act",
                 "layout": "[micro_batch, seq, features]"},
    },
}

#: Same chokepoint counter serving/disagg.py meters (the registry is
#: get-or-create by name, so whichever module loads first owns the help
#: text and both increment ONE family): every edge transfer's WIRE bytes.
_EDGE_BYTES = _monitor.counter(
    "kv_handoff_bytes_total",
    "bytes handed between stage programs (KV rows, activations, grads) "
    "— wire bytes: a compress=8 edge counts the int8+scales payload")

_ELASTIC_RESUME = None  # lazy elastic_resume_total — same family the
#                         ElasticSupervisor (distributed/elastic.py)
#                         counts under; get-or-create by name, so both
#                         call sites increment ONE family


def _note_elastic_resume(reason):
    global _ELASTIC_RESUME
    if not _monitor.is_enabled():
        return
    if _ELASTIC_RESUME is None:
        _ELASTIC_RESUME = _monitor.counter(
            "elastic_resume_total",
            "elastic recoveries by reason (failpoint | nonfinite | crash "
            "from the supervisor's resume path, stage_replace from MPMD "
            "stage rebinding); zero unless FLAGS_elastic machinery "
            "actually recovered something",
            labelnames=("reason",))
    _ELASTIC_RESUME.labels(reason=reason).inc()


def _goodput_bucket(name):
    """Goodput wall-time attribution for edge transfers (FLAGS_goodput,
    ISSUE 20): null context unless the accountant is armed — one flag
    read per put, and the disarmed path never imports monitor/goodput.py
    (manifest-lazy). Edge validate/quantize/enqueue time books as
    ``edge_wait``, pausing the enclosing tick's ``step`` bucket."""
    if not _flags.get_flag("goodput", False):
        return contextlib.nullcontext()
    from ..monitor import goodput as _goodput

    return _goodput.bucket(name)


class EdgeFullError(RuntimeError):
    """A producer ran ahead of its consumer past the edge's capacity —
    the backpressure signal. The payload was NOT enqueued (and not
    dropped elsewhere): the producer must drain the consumer and retry,
    exactly like serving's QueueFullError."""


class EdgeEmptyError(RuntimeError):
    """get() on an edge with nothing in flight."""


def _nbytes(a):
    return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize if a.shape \
        else np.dtype(a.dtype).itemsize


def _iter_leaves(payload_spec, prefix=""):
    """(dotted-path, leaf-spec) pairs, sorted — mirrors the walk
    analysis/handoff_schema.validate performs."""
    for k in sorted(payload_spec):
        v = payload_spec[k]
        path = f"{prefix}{k}"
        if isinstance(v, dict) and ("shape" in v or "dtype" in v
                                    or "kind" in v):
            yield path, v
        elif isinstance(v, dict):
            yield from _iter_leaves(v, f"{path}.")


def _get_path(tree, path):
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def _set_path(tree, path, value):
    parts = path.split(".")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


class StageEdge:
    """A typed, validated, backpressured transfer edge between stage
    programs.

    ``put(payload)`` validates the payload against the declared
    ``schema`` (raising ``HandoffMismatch`` naming the leaf), meters its
    wire bytes into ``kv_handoff_bytes_total``, and enqueues; a full
    edge raises :class:`EdgeFullError` BEFORE any work (backpressure,
    never loss). ``get()`` dequeues in FIFO order, decoding quantized
    leaves back to their original dtype.

    ``compress=8`` (only value; EQuARX int8, arXiv:2506.17615) encodes
    every leaf the schema marks ``quantizable`` through
    ``compress.quantize_rows`` — deterministic per-row symmetric int8 —
    and re-validates the encoded (values, scales) pairs against the SAME
    declaration with the dtype symbol bound to int8. Non-quantizable
    leaves (logits, grads) always move dense. Per payload the compressed
    transfer also lands in ``collective_bytes_total{op="stage_edge"}`` /
    ``collective_bytes_saved_total{op="stage_edge"}`` — the wire-vs-
    logical split the quantized all-reduce established. Byte math for a
    leaf with last dim D: wire/logical = (1 + 4/D)/4, i.e. ~3.94x saved
    at D=256, 3.76x at D=64, 3.2x at the disagg KV row's hd=16.

    Every ``put`` runs under a ``stage/edge`` blackbox progress window
    and fires the registered ``stage/edge`` failpoint first — a chaos
    delay injected there reads as a stalled stage to the stall sentinel.
    """

    def __init__(self, name, schema, capacity=2, compress=None,
                 dims=None, dtypes=None):
        if compress not in (None, 8):
            raise ValueError(f"edge {name!r}: compress={compress!r} "
                             "unsupported (None or 8)")
        self.name = name
        self.schema = schema
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"edge {name!r}: capacity must be >= 1")
        self.compress = compress
        self._dims = dict(dims or {})
        self._dtypes = dict(dtypes or {})
        self._q = collections.deque()
        self.stats = {"puts": 0, "gets": 0, "backpressured": 0,
                      "wire_bytes": 0, "logical_bytes": 0}

    def __len__(self):
        return len(self._q)

    def full(self):
        return len(self._q) >= self.capacity

    def put(self, payload, dims=None, dtypes=None):
        """Validate + enqueue one payload; returns its wire bytes."""
        from ..analysis import handoff_schema as _hs

        if len(self._q) >= self.capacity:
            self.stats["backpressured"] += 1
            raise EdgeFullError(
                f"stage edge {self.name!r} is full ({self.capacity} "
                "payload(s) in flight) — backpressure: drain the "
                "consumer before producing more")
        with _goodput_bucket("edge_wait"), \
                _blackbox.progress("stage/edge"):
            _fp.failpoint("stage/edge")
            bind_dims = dict(self._dims, **(dims or {}))
            bind_dtypes = dict(self._dtypes, **(dtypes or {}))
            _hs.validate(self.schema, payload, dims=bind_dims,
                         dtypes=bind_dtypes)
            logical = wire = 0
            stored = {}
            enc_dtypes = {}
            for leaf, spec in _iter_leaves(self.schema["payload"]):
                node = _get_path(payload, leaf)
                nb = _nbytes(node)
                logical += nb
                if (self.compress and spec.get("quantizable")
                        and jnp.issubdtype(node.dtype, jnp.floating)):
                    from . import compress as _compress

                    q, scales = _compress.quantize_rows(node)
                    stored[leaf] = ("q", q, scales, str(node.dtype))
                    wire += _nbytes(q) + _nbytes(scales)
                    dt = spec.get("dtype")
                    if isinstance(dt, str) and dt.startswith("$"):
                        enc_dtypes[dt[1:]] = "int8"
                else:
                    stored[leaf] = ("dense", node)
                    wire += nb
            if self.compress:
                # the ENCODED form must satisfy the same declaration the
                # consumer decodes against: int8 values at the declared
                # shape, f32 per-row scales
                enc = {}
                for leaf, s in stored.items():
                    _set_path(enc, leaf,
                              (s[1], s[2]) if s[0] == "q" else s[1])
                _hs.validate(self.schema, enc, dims=bind_dims,
                             dtypes=dict(bind_dtypes, **enc_dtypes))
                from . import collective as _coll

                _coll.record_compressed("stage_edge", logical, wire)
            _EDGE_BYTES.inc(int(wire))
            self.stats["puts"] += 1
            self.stats["wire_bytes"] += int(wire)
            self.stats["logical_bytes"] += int(logical)
            self._q.append(stored)
            return int(wire)

    def get(self):
        """Dequeue (FIFO) one payload, decoding quantized leaves back to
        their original dtype."""
        if not self._q:
            raise EdgeEmptyError(f"stage edge {self.name!r} is empty")
        stored = self._q.popleft()
        out = {}
        for leaf, s in stored.items():
            if s[0] == "q":
                from . import compress as _compress

                _set_path(out, leaf,
                          _compress.dequantize_rows(s[1], s[2],
                                                    dtype=s[3]))
            else:
                _set_path(out, leaf, s[1])
        self.stats["gets"] += 1
        return out


class StageProgram:
    """One stage of an MPMD graph: a pure function compiled for — and
    pinned to — its OWN mesh.

    Inputs are committed (replicated, ``P()``) onto the stage's mesh
    before dispatch, so the compiled program belongs to that topology;
    the AOT cache key joins the stage's ``mesh_fingerprint`` AND its
    name (via the CachedJit label), giving per-stage disk entries under
    ``FLAGS_jit_cache_dir`` — two stages with different device counts
    never share an executable (compile_cache_total{site="stage"}).
    """

    def __init__(self, name, fn, mesh=None):
        self.name = name
        self.mesh = mesh
        self._fn = fn   # retained so rebind() can recompile elsewhere
        self._sharding = (NamedSharding(mesh, P())
                         if mesh is not None else None)
        self._jit = _aot.cached_jit(
            fn, site="stage", label=name, record_event="stage/compile",
            extra_key=("stage", _aot.mesh_fingerprint(mesh)))

    def _commit(self, x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.device_put(x, self._sharding)
        return x

    def __call__(self, *args):
        _fp.failpoint("stage/run")
        if self._sharding is not None:
            args = jax.tree_util.tree_map(self._commit, args)
        return self._jit(*args)

    def warm(self, *specs):
        return self._jit.warm(*specs)

    def rebind(self, mesh):
        """Re-pin THIS program to a replacement mesh (the PR 15
        remainder, armed by MpmdPipelineRunner.replace_stage): a fresh
        CachedJit keyed by the new mesh_fingerprint — which hashes
        shape/kind, not device ids, so a same-shape replacement slice
        disk-hits a warmed FLAGS_jit_cache_dir instead of recompiling.
        Sibling programs are untouched (their CachedJit objects keep
        their compiled entries)."""
        self.mesh = mesh
        self._sharding = (NamedSharding(mesh, P())
                         if mesh is not None else None)
        self._jit = _aot.cached_jit(
            self._fn, site="stage", label=self.name,
            record_event="stage/compile",
            extra_key=("stage", _aot.mesh_fingerprint(mesh)))
        return self


class StageGraph:
    """The MPMD runner: N registered stage programs + edges, executed as
    an explicit schedule of (stage_name, thunk) ticks.

    ``run(plan)`` opens one ``stage_graph`` root span and runs each tick
    under a ``stage_step`` span carrying the stage name — every span in
    one step shares ONE trace_id — and a ``stage/<name>`` blackbox
    progress window, so the stall sentinel names the stalled stage."""

    def __init__(self, name="stage_graph"):
        self.name = name
        self.stages = {}
        self.edges = {}
        #: weight lineage the ticks execute under (framework/lineage.py,
        #: ISSUE 20): set by whoever drives the graph (MpmdPipelineRunner
        #: refreshes it from its trainer each step); surfaced on every
        #: ``stage_step`` span when set
        self.weight_version = None
        # perf ledger (FLAGS_perf_ledger, docs/OBSERVABILITY.md):
        # consumed at construction; disarmed, run() pays one `is None`
        self._perf_ledger = None
        if _flags.get_flag("perf_ledger", False):
            from ..monitor import perfledger as _perfledger

            self._perf_ledger = _perfledger.get_ledger()
        # goodput accountant (FLAGS_goodput, ISSUE 20): same
        # construction-consumed pattern — each tick books `step`, edge
        # transfers inside it nest `edge_wait`
        self._goodput = None
        if _flags.get_flag("goodput", False):
            from ..monitor import goodput as _goodput

            self._goodput = _goodput

    def add_stage(self, program):
        self.stages[program.name] = program
        return program

    def add_edge(self, edge):
        self.edges[edge.name] = edge
        return edge

    def run(self, plan, trace_id=None):
        """Execute `plan` (iterable of (stage_name, thunk)) in order;
        returns the list of thunk results."""
        traced = _trace.is_enabled()
        root = _trace.start_span("stage_graph", subsystem="stage",
                                 trace_id=trace_id, graph=self.name) \
            if traced else None
        t0 = time.perf_counter() if self._perf_ledger is not None else None
        out = []
        try:
            for sname, thunk in plan:
                attrs = {} if self.weight_version is None else \
                    {"weight_version": str(self.weight_version)}
                sp = _trace.start_span(
                    "stage_step", subsystem="stage", parent=root,
                    stage=sname, **attrs) if traced else None
                try:
                    with (self._goodput.bucket("step")
                          if self._goodput is not None
                          else contextlib.nullcontext()), \
                            _blackbox.progress(f"stage/{sname}"):
                        out.append(thunk())
                finally:
                    if sp is not None:
                        sp.end()
        finally:
            if root is not None:
                root.end(ticks=len(out))
            if t0 is not None:
                self._ledger_run((time.perf_counter() - t0) * 1e3,
                                 len(out))
        return out

    def _ledger_run(self, run_ms, ticks):
        """Armed-only (FLAGS_perf_ledger) per-run feed: run/mean-tick
        wall ms through the regression sentinel, with the edge transfer
        tallies riding the row (every FLAGS_perf_ledger_interval-th
        run)."""
        m = {"run_ms": run_ms, "ticks": ticks}
        if ticks:
            m["tick_ms"] = run_ms / ticks
        edges = {}
        for name, st in self.edge_stats().items():
            nums = {k: v for k, v in st.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
            if nums:
                edges[name] = nums
        if edges:
            m["edges"] = edges
        self._perf_ledger.on_step("stage/" + self.name, m)

    def edge_stats(self):
        return {n: dict(e.stats) for n, e in sorted(self.edges.items())}


# ---------------------------------------------------------------------------
# PipelineTrainer re-based on the graph (the FLAGS_mpmd armed path)
# ---------------------------------------------------------------------------


class MpmdPipelineRunner:
    """Runs a ``PipelineTrainer``'s schedule as true MPMD: one compiled
    forward/backward program per stage, each on its own mesh slice,
    activations and grads moving over typed edges.

    Program split (stage template is ``stage_layers[0]`` — stages are
    structurally identical, exactly the baseline's assumption):

    - ``fwd0``: pre (embedding) folded into stage 0 — ``(pre_p, s0_p,
      x_micro) -> h``; ``bwd0`` rematerializes the forward inside a vjp
      and returns ``(g_pre, g_s0)``;
    - ``fwd<k>``/``bwd<k>`` for middle stages: ``(s_p, h) -> h'`` and the
      vjp-recompute backward ``(s_p, h, g') -> (g_s, g_h)``;
    - ``last<K-1>``: the head+loss fused with the final stage —
      ``(s_p, post_p, h, y_micro) -> (loss, g_s, g_post, g_h)`` via
      value_and_grad (1F1B's "backward follows immediately" property by
      construction).

    Schedules order the SAME ticks — per-micro grads are collected and
    summed in fixed micro order, then averaged, so all three schedules
    produce bit-identical updates:

    - ``F-then-B``: every forward tick stage-major, then every backward —
      edge depth reaches n_micro (the GPipe memory profile);
    - ``1F1B``: each micro's backward chain drains as soon as its forward
      chain completes — edge depth 1 (the 1F1B memory profile);
    - ``interleaved``: the 1F1B tick order with TWO virtual stage chunks
      per physical mesh slice (stage k placed on slice k mod K/2; K must
      be even) — the interleaved-virtual-stage placement at the same
      math.

    The optimizer update is the trainer's own ``functional_apply`` in one
    more cached program pinned to the trainer mesh, reading/writing the
    trainer's existing param/opt-state shardings — ``state_dict`` /
    ``sync_to_layer`` keep working unchanged.
    """

    SCHEDULES = ("F-then-B", "1F1B", "interleaved")

    def __init__(self, trainer, stage_meshes=None, compress=None):
        from .mesh import build_mesh
        from .pipeline import _pure_call

        self.tr = trainer
        K = trainer.n_stages
        if K < 2:
            raise ValueError("MPMD needs >= 2 stages")
        if trainer.schedule_mode not in self.SCHEDULES:
            raise ValueError(
                f"unknown schedule {trainer.schedule_mode!r}; MPMD "
                f"schedules: {self.SCHEDULES}")
        self.n_stages = K
        self.schedule_mode = trainer.schedule_mode
        self.compress = compress

        if stage_meshes is not None:
            if len(stage_meshes) != K:
                raise ValueError(f"{len(stage_meshes)} stage meshes for "
                                 f"{K} stages")
            self.stage_meshes = list(stage_meshes)
        else:
            ax_i = list(trainer.mesh.axis_names).index(trainer.pp_axis)
            devs = np.moveaxis(np.asarray(trainer.mesh.devices), ax_i,
                               0).reshape(K, -1)
            if self.schedule_mode == "interleaved":
                if K % 2:
                    raise ValueError("the interleaved schedule needs an "
                                     "even stage count (two virtual "
                                     "chunks per physical slice)")
                n_phys = K // 2
                slices = [list(devs[k % n_phys]) for k in range(K)]
            else:
                slices = [list(devs[k]) for k in range(K)]
            self.stage_meshes = [
                build_mesh((len(s),), ("stage",), devices=s)
                for s in slices]

        cap = trainer.n_micro
        self.act_edges = [
            StageEdge(f"act{k}", HANDOFF_SCHEMA, capacity=cap,
                      compress=compress) for k in range(K - 1)]
        self.grad_edges = [
            StageEdge(f"grad{k}", HANDOFF_SCHEMA_GRAD, capacity=cap)
            for k in range(K - 1)]

        pre, post = trainer.pre, trainer.post_loss
        tpl = trainer.stage_layers[0]

        def fwd_first(pre_p, s_p, x):
            return _pure_call(tpl, s_p, _pure_call(pre, pre_p, x))

        def bwd_first(pre_p, s_p, x, g):
            _, vjp = jax.vjp(
                lambda pp, sp: _pure_call(tpl, sp,
                                          _pure_call(pre, pp, x)),
                pre_p, s_p)
            return vjp(g)

        def fwd_mid(s_p, h):
            return _pure_call(tpl, s_p, h)

        def bwd_mid(s_p, h, g):
            _, vjp = jax.vjp(lambda sp, hh: _pure_call(tpl, sp, hh),
                             s_p, h)
            return vjp(g)

        def last_fused(s_p, post_p, h, y):
            def f(sp, pp, hh):
                o = _pure_call(tpl, sp, hh)
                return _pure_call(post, pp, o, y).astype(jnp.float32)

            loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
                s_p, post_p, h)
            return (loss,) + tuple(grads)

        self.programs = {}
        for k in range(K):
            mesh_k = self.stage_meshes[k]
            if k == 0:
                self.programs["fwd0"] = StageProgram("fwd0", fwd_first,
                                                     mesh=mesh_k)
                self.programs["bwd0"] = StageProgram("bwd0", bwd_first,
                                                     mesh=mesh_k)
            elif k == K - 1:
                self.programs[f"last{k}"] = StageProgram(
                    f"last{k}", last_fused, mesh=mesh_k)
            else:
                self.programs[f"fwd{k}"] = StageProgram(
                    f"fwd{k}", fwd_mid, mesh=mesh_k)
                self.programs[f"bwd{k}"] = StageProgram(
                    f"bwd{k}", bwd_mid, mesh=mesh_k)
        self._fwd0_fn = fwd_first
        self._last_fn = last_fused
        self.graph = StageGraph("pipeline")
        for p in self.programs.values():
            self.graph.add_stage(p)
        for e in self.act_edges + self.grad_edges:
            self.graph.add_edge(e)
        self._opt_step = None

    # -- MPMD stage elasticity (FLAGS_elastic; docs/DISTRIBUTED.md) ---------
    def replace_stage(self, k, mesh):
        """Re-bind stage ``k``'s program(s) to a replacement mesh WITHOUT
        recompiling siblings — the MPMD elasticity axis: one stage's
        slice dies, the other K-1 compiled programs (and their warmed
        AOT entries) survive untouched. Requires FLAGS_elastic (the
        structural elastic posture); a same-shape replacement slice
        disk-hits FLAGS_jit_cache_dir via the mesh fingerprint. Counted
        in elastic_resume_total{reason="stage_replace"} and noted on the
        blackbox ring so the recovery is attributable."""
        if not _flags.get_flag("elastic", False):
            raise RuntimeError(
                "MpmdPipelineRunner.replace_stage requires "
                "FLAGS_elastic=1 — stage elasticity is part of the "
                "structural elastic posture (docs/DISTRIBUTED.md)")
        K = self.n_stages
        if not 0 <= k < K:
            raise ValueError(f"stage index {k} out of range [0, {K})")
        if k == 0:
            names = ["fwd0", "bwd0"]
        elif k == K - 1:
            names = [f"last{k}"]
        else:
            names = [f"fwd{k}", f"bwd{k}"]
        for name in names:
            self.programs[name].rebind(mesh)
        self.stage_meshes[k] = mesh
        _note_elastic_resume("stage_replace")
        _blackbox.note("stage_replace", stage=k, programs=names,
                       mesh=str(_aot.mesh_fingerprint(mesh)))
        return self

    # -- per-step execution -------------------------------------------------
    def _split_groups(self):
        tr = self.tr
        groups = {"pre": {}, "stage": {}, "post": {}}
        for kname, v in {**tr.frozen, **tr.params}.items():
            grp, nm = kname.split("::", 1)
            groups[grp][nm] = v
        return groups

    def _build_opt(self):
        tr = self.tr

        def opt_fn(params, opt_state, grads, lr):
            return tr.optimizer.functional_apply(params, grads,
                                                 opt_state, lr=lr)

        repl = NamedSharding(tr.mesh, P())
        jitted = jax.jit(
            opt_fn,
            in_shardings=(tr.p_shardings, dict(tr.s_shardings),
                          tr.p_shardings, repl),
            out_shardings=(tr.p_shardings, dict(tr.s_shardings)))
        return _aot.cached_jit(
            jit=jitted, site="stage", label="optimizer",
            record_event="stage/compile",
            extra_key=("stage", _aot.mesh_fingerprint(tr.mesh)))

    def train_step(self, x_micro, y_micro):
        """One MPMD train step over pre-split [n_micro, mb, ...] batches;
        returns the mean scalar loss and updates the trainer's
        params/opt_state in place (same layout as the baseline step)."""
        tr = self.tr
        K, n = self.n_stages, tr.n_micro
        groups = self._split_groups()
        pre_p, post_p = groups["pre"], groups["post"]
        stage_p = [{nm: v[k] for nm, v in groups["stage"].items()}
                   for k in range(K)]
        mb = int(x_micro.shape[1])

        h_in = [[None] * n for _ in range(K)]
        losses = [None] * n
        g_stage = [[None] * n for _ in range(K)]
        g_pre = [None] * n
        g_post = [None] * n

        def fwd_tick(k, m):
            def thunk():
                if k == 0:
                    h = self.programs["fwd0"](pre_p, stage_p[0],
                                              x_micro[m])
                    self.act_edges[0].put({"activation": h},
                                          dims={"mb": mb})
                elif k < K - 1:
                    h = self.act_edges[k - 1].get()["activation"]
                    h_in[k][m] = h
                    out = self.programs[f"fwd{k}"](stage_p[k], h)
                    self.act_edges[k].put({"activation": out},
                                          dims={"mb": mb})
                else:
                    h = self.act_edges[k - 1].get()["activation"]
                    h_in[k][m] = h
                    loss, g_s, g_po, g_h = self.programs[f"last{k}"](
                        stage_p[k], post_p, h, y_micro[m])
                    losses[m] = loss
                    g_stage[k][m] = g_s
                    g_post[m] = g_po
                    self.grad_edges[k - 1].put({"grad": g_h},
                                               dims={"mb": mb})
            return thunk

        def bwd_tick(k, m):
            def thunk():
                g = self.grad_edges[k].get()["grad"]
                if k == 0:
                    gp, gs = self.programs["bwd0"](pre_p, stage_p[0],
                                                   x_micro[m], g)
                    g_pre[m] = gp
                    g_stage[0][m] = gs
                else:
                    gs, gh = self.programs[f"bwd{k}"](stage_p[k],
                                                      h_in[k][m], g)
                    g_stage[k][m] = gs
                    self.grad_edges[k - 1].put({"grad": gh},
                                               dims={"mb": mb})
            return thunk

        def _name(k, kind):
            if k == 0:
                return "fwd0" if kind == "fwd" else "bwd0"
            if k == K - 1 and kind == "fwd":
                return f"last{k}"
            return f"{kind}{k}"

        plan = []
        if self.schedule_mode == "F-then-B":
            for k in range(K):
                for m in range(n):
                    plan.append((_name(k, "fwd"), fwd_tick(k, m)))
            for k in range(K - 2, -1, -1):
                for m in range(n):
                    plan.append((_name(k, "bwd"), bwd_tick(k, m)))
        else:   # 1F1B and interleaved: one micro's backward chain drains
                # as soon as its forward chain completes
            for m in range(n):
                for k in range(K):
                    plan.append((_name(k, "fwd"), fwd_tick(k, m)))
                for k in range(K - 2, -1, -1):
                    plan.append((_name(k, "bwd"), bwd_tick(k, m)))
        # weight lineage (ISSUE 20): the ticks about to run execute under
        # the trainer's CURRENT version — refresh per step, not at
        # construction, so post-restore/reshard bumps show on spans
        self.graph.weight_version = getattr(tr, "weight_version", None)
        self.graph.run(plan)

        def _acc(trees):
            out = trees[0]
            for t in trees[1:]:
                out = jax.tree_util.tree_map(jnp.add, out, t)
            return out

        # fixed micro-order accumulation, THEN the 1/n mean: every
        # schedule sums the same floats in the same order — schedules
        # are placement/ordering choices, not numerics choices
        gp, gpo = _acc(g_pre), _acc(g_post)
        gs = [_acc(g_stage[k]) for k in range(K)]
        # each stage's grads live on ITS mesh — re-commit onto the
        # trainer mesh (replicated) before stacking/the optimizer program
        repl_tr = NamedSharding(tr.mesh, P())
        grads = {}
        for kname in tr.params:
            grp, nm = kname.split("::", 1)
            if grp == "pre":
                g = jax.device_put(gp[nm], repl_tr)
            elif grp == "post":
                g = jax.device_put(gpo[nm], repl_tr)
            else:
                g = jnp.stack([jax.device_put(gs[k][nm], repl_tr)
                               for k in range(K)], axis=0)
            grads[kname] = jax.device_put(
                (g / n).astype(tr.params[kname].dtype),
                tr.p_shardings[kname])

        loss = jnp.mean(jnp.stack(losses))
        if self._opt_step is None:
            self._opt_step = self._build_opt()
        lr = jnp.asarray(tr.optimizer.get_lr(), dtype=jnp.float32)
        tr.params, tr.opt_state = self._opt_step(tr.params, tr.opt_state,
                                                 grads, lr)
        return loss

    # -- analysis hooks ------------------------------------------------------
    def lint_jaxpr(self, x_micro, y_micro):
        """ClosedJaxpr of the fused last-stage program (loss + grads —
        the densest stage) on one micro batch, for the sharding-flow
        lint target (analysis/sharding_flow.py "mpmd_train")."""
        K = self.n_stages
        groups = self._split_groups()
        stage0 = {nm: v[0] for nm, v in groups["stage"].items()}
        stage_last = {nm: v[K - 1] for nm, v in groups["stage"].items()}
        h = jax.eval_shape(
            self._fwd0_fn, groups["pre"], stage0,
            jax.ShapeDtypeStruct(tuple(x_micro.shape[1:]),
                                 x_micro.dtype))
        return jax.make_jaxpr(self._last_fn)(
            stage_last, groups["post"],
            jax.ShapeDtypeStruct(h.shape, h.dtype),
            jax.ShapeDtypeStruct(tuple(y_micro.shape[1:]),
                                 y_micro.dtype))

    def stats(self):
        return {"schedule": self.schedule_mode,
                "n_stages": self.n_stages,
                "compress": self.compress,
                "stage_devices": [len(m.devices.ravel())
                                  for m in self.stage_meshes],
                "edges": self.graph.edge_stats()}


def pipeline_trainer_from_plan(config, model, optimizer):
    """Realize a plan-search emission (analysis/plan_search.emit,
    ``kind="stage_graph"``) as a FLAGS_mpmd :class:`PipelineTrainer`
    whose runner builds this module's typed-edge StageGraph.

    FLAGS_mpmd must already be set (the trainer consumes it at
    construction); ``model`` must expose ``pipeline_split``. The stage
    cut is the config's per-stage layer lists — equal cuts, which is
    what ``pipeline_split(pp)`` produces; a config whose cuts disagree
    with an equal split is rejected loudly rather than silently
    re-cut."""
    import jax

    from .. import flags as _flags
    from .mesh import build_mesh
    from .pipeline import PipelineTrainer

    if config.get("kind") != "stage_graph":
        raise ValueError(
            f"config kind {config.get('kind')!r} is not 'stage_graph'")
    if not _flags.get_flag("mpmd", False):
        raise ValueError(
            "plan config arms the MPMD stage runtime — set FLAGS_mpmd "
            "before realizing (PipelineTrainer consumes it at "
            "construction)")
    if not hasattr(model, "pipeline_split"):
        raise ValueError(
            f"{type(model).__name__} has no pipeline_split(); the plan "
            "search only emits stage_graph configs for models that do")
    pipe = config["pipeline"]
    cuts = pipe.get("stage_layers") or []
    pp = len(cuts) or int(config["mesh"]["shape"][
        config["mesh"]["axes"].index("pp")])
    sizes = {len(c) for c in cuts} if cuts else set()
    if len(sizes) > 1:
        raise ValueError(
            f"unequal stage cuts {cuts}: pipeline_split(pp) produces "
            "equal stages — re-emit the plan")
    pre, stages, post = model.pipeline_split(pp)
    mesh = build_mesh((pp,), ("pp",), devices=jax.devices()[:pp])
    return PipelineTrainer(
        pre, stages, post, optimizer, mesh=mesh,
        n_micro=int(pipe["n_micro"]),
        schedule_mode=pipe.get("schedule", "1F1B"),
        compress=pipe.get("compress"))
