"""Distributed environment.

Reference parity: the PADDLE_TRAINER_* env protocol (fleet/launch_utils.py:457-464) and
ParallelEnv (fluid/dygraph/parallel.py:68); NCCL-id TCP bootstrap
(platform/gen_comm_id_helper.cc:286) is replaced by jax.distributed.initialize's
coordination service.
"""
import os

import jax

_INITIALIZED = [False]


def get_rank():
    if _INITIALIZED[0]:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size():
    if _INITIALIZED[0]:
        return jax.process_count()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    if eps:
        return len(eps.split(","))
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))


def init_distributed(coordinator_address=None, num_processes=None, process_id=None):
    """jax.distributed.initialize wrapper honoring the PADDLE_* env protocol."""
    if _INITIALIZED[0]:
        return
    nproc = num_processes or get_world_size()
    if nproc <= 1:
        _INITIALIZED[0] = True
        return
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        coordinator_address = eps[0] if eps and eps[0] else "127.0.0.1:12355"
    pid = process_id if process_id is not None else get_rank()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=nproc,
        process_id=pid,
    )
    _INITIALIZED[0] = True


def is_initialized():
    return _INITIALIZED[0]


class ParallelEnv:
    """fluid/dygraph/parallel.py:68 ParallelEnv parity."""

    def __init__(self):
        self._rank = get_rank()
        self._world_size = get_world_size()

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", self._rank))

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def device_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
