"""SPMD training engine — the ParallelExecutor/SSA-graph replacement.

Reference parity: this one module supersedes the reference's multi-device machinery:
ParallelExecutor + multi_devices_graph_pass (grad allreduce insertion,
framework/details/), ShardingOptimizer program surgery
(fleet/meta_optimizers/sharding_optimizer.py:161-308), and the dygraph Reducer.

TPU-native design: ONE jitted train step over a Mesh.
 - data parallel: batch sharded on 'dp'; XLA inserts the grad psum (ICI).
 - ZeRO ("sharding" stage 1/2/3): optimizer states (and for stage 3, params) get
   NamedShardings over the dp axis; XLA emits reduce_scatter/all_gather — the
   _split_program/_add_broadcast_allreduce passes become sharding annotations.
 - tensor parallel: param shardings over 'mp' provided by distributed.split layers.
 - recompute: jax.checkpoint on the forward.
 - gradient merge / accumulation: lax.scan over micro-batches.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tape import global_tape
from ..core.tensor import Tensor
from .mesh import get_mesh


def owned_device_put(v, sh):
    """device_put that never shares buffers with `v`.

    The jitted train step donates its param/state inputs; device_put to a
    replicated sharding reuses the source's buffer for the shard on its device,
    so donating the placed array would invalidate the Layer's eager tensors
    (and any other trainer placed from the same source). Copy first so the
    trainer exclusively owns every buffer it donates."""
    return jax.device_put(jnp.copy(jnp.asarray(v)), sh)


def _first_divisible_axis(shape, n):
    for i, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return i
    return None


def param_shardings(params, mesh, axis_name, min_size=16384, shard_params=False):
    """ZeRO-style shardings: arrays >= min_size sharded on their first divisible dim."""
    n = mesh.shape[axis_name]
    out = {}
    for k, v in params.items():
        ax = _first_divisible_axis(v.shape, n)
        if shard_params and ax is not None and v.size >= min_size:
            spec = [None] * v.ndim
            spec[ax] = axis_name
            out[k] = NamedSharding(mesh, P(*spec))
        else:
            out[k] = NamedSharding(mesh, P())
    return out


def state_shardings(opt_state, p_shardings, mesh, axis_name, stage):
    """Shard optimizer moments like their params (stage>=2) or replicate."""
    out = {}
    n = mesh.shape[axis_name]
    for pname, st in opt_state.items():
        if pname == "__step__":
            out[pname] = NamedSharding(mesh, P())
            continue
        sub = {}
        for k, v in st.items():
            if stage >= 2 and hasattr(v, "ndim") and v.ndim > 0:
                ax = _first_divisible_axis(v.shape, n)
                if ax is not None and v.size >= 16384:
                    spec = [None] * v.ndim
                    spec[ax] = axis_name
                    sub[k] = NamedSharding(mesh, P(*spec))
                    continue
            sub[k] = NamedSharding(mesh, P())
        out[pname] = sub
    return out


def _collect_moe_aux(layer):
    """Sum MoE load-balance aux losses from the last forward (None if dense).

    Keeps the router's load-balancing gradient alive on trainer paths where the
    loss_fn only sees (outputs, labels)."""
    from ..nn.layer.moe import MoELayer

    aux = None
    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, MoELayer) and sub.aux_loss is not None:
            aux = sub.aux_loss if aux is None else aux + sub.aux_loss
    return aux


class SpmdTrainer:
    """Compile a Layer + Optimizer + loss into one sharded XLA train step."""

    def __init__(self, layer, optimizer, loss_fn=None, mesh=None, dp_axis="dp",
                 sharding_stage=0, recompute=False, accumulate_steps=1,
                 extra_param_specs=None, metrics_fn=None, donate=True,
                 amp_dtype=None, **extra_kwargs):
        self.layer = layer
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_mesh()
        self.dp_axis = dp_axis
        self.sharding_stage = sharding_stage
        self.recompute = recompute
        self.accumulate_steps = accumulate_steps
        self.extra_param_specs = extra_param_specs or {}
        self.amp_dtype = amp_dtype
        self.extra_kwargs = extra_kwargs  # meta-optimizer hints not yet consumed
        self._compiled = None
        self.params = {n: p._data for n, p in layer.named_parameters() if getattr(p, "trainable", True)}
        self.frozen = {n: p._data for n, p in layer.named_parameters() if not getattr(p, "trainable", True)}
        self.buffers = {n: b._data for n, b in layer.named_buffers()}
        self.opt_state = optimizer.functional_init(self.params)
        self._place_state()

    # -- sharding placement ----------------------------------------------------
    def _place_state(self):
        mesh = self.mesh
        ax = self.dp_axis
        self.p_shardings = param_shardings(
            self.params, mesh, ax, shard_params=(self.sharding_stage >= 3)
        )
        for k, spec in self.extra_param_specs.items():
            if k in self.p_shardings:
                self.p_shardings[k] = NamedSharding(mesh, spec)
        self.s_shardings = state_shardings(self.opt_state, self.p_shardings, mesh, ax, self.sharding_stage)
        self.b_shardings = {k: NamedSharding(mesh, P()) for k in self.buffers}
        # device_put everything per its sharding (owned copies: the step donates)
        self.params = {k: owned_device_put(v, self.p_shardings[k]) for k, v in self.params.items()}
        self.buffers = {k: owned_device_put(v, self.b_shardings[k]) for k, v in self.buffers.items()}
        new_state = {}
        for pname, st in self.opt_state.items():
            if pname == "__step__":
                new_state[pname] = owned_device_put(st, NamedSharding(self.mesh, P()))
            else:
                new_state[pname] = {k: owned_device_put(v, self.s_shardings[pname][k]) for k, v in st.items()}
        self.opt_state = new_state

    # -- pure step -------------------------------------------------------------
    def _forward_loss(self, params, buffers, batch):
        layer = self.layer
        tape = global_tape()
        named_p = dict(layer.named_parameters())
        named_b = dict(layer.named_buffers())
        saved = {n: t._data for n, t in {**named_p, **named_b}.items()}
        import contextlib

        amp_ctx = contextlib.nullcontext()
        if self.amp_dtype is not None:
            from ..amp.auto_cast import auto_cast

            amp_ctx = auto_cast(True, dtype=self.amp_dtype)
        try:
            for n, v in params.items():
                named_p[n]._data = v
            for n, v in self.frozen.items():
                named_p[n]._data = v
            for n, v in buffers.items():
                named_b[n]._data = v
            with tape.pause(), amp_ctx:
                inputs = [Tensor(b) for b in batch[:-1]]
                label = Tensor(batch[-1])
                if self.loss_fn is not None:
                    out = layer(*inputs)
                    loss = self.loss_fn(out, label)
                    aux = _collect_moe_aux(layer)
                    if aux is not None:
                        w = getattr(getattr(layer, "cfg", None), "moe_aux_weight", 0.01)
                        loss = loss + w * aux
                else:
                    loss = layer(*inputs, label)
            new_buffers = {n: named_b[n]._data for n in buffers}
            return loss._data if isinstance(loss, Tensor) else loss, new_buffers
        finally:
            for n, t in {**named_p, **named_b}.items():
                t._data = saved[n]

    def _build(self, batch_arrays):
        mesh = self.mesh
        ax = self.dp_axis

        fwd = self._forward_loss
        if self.recompute:
            # the offload custom call (annotate_device_placement) has no CPU
            # lowering under the sharded jit step in this jax version; guard
            # verified empirically — the policy itself works on TPU
            on_cpu = np.asarray(self.mesh.devices).flat[0].platform == "cpu"
            if self.extra_kwargs.get("remat_offload") and on_cpu:
                import warnings

                warnings.warn("remat_offload ignored on the CPU backend; "
                              "falling back to plain recompute")
            if self.extra_kwargs.get("remat_offload") and not on_cpu:
                # RecomputeConfig.enable_offload parity: matmul residuals go
                # to pinned host memory instead of being recomputed or held
                # in HBM (reference offloads checkpoints to CPU)
                policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                    "device", "pinned_host")
                fwd = jax.checkpoint(fwd, static_argnums=(), policy=policy)
            else:
                fwd = jax.checkpoint(fwd, static_argnums=())

        accum = self.accumulate_steps

        def step(params, opt_state, buffers, lr, *batch):
            def loss_fn(p, b):
                loss, new_buf = fwd(p, buffers, b)
                return loss.astype(jnp.float32), new_buf

            if accum > 1:
                # gradient merge (fleet/meta_optimizers/gradient_merge_optimizer.py):
                # micro-batch scan, grads averaged
                micro = [jnp.reshape(b, (accum, b.shape[0] // accum) + b.shape[1:]) for b in batch]

                def body(carry, mb):
                    g_acc, l_acc = carry
                    (loss, nb), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
                    g_acc = jax.tree_util.tree_map(lambda a, g: a + g, g_acc, grads)
                    return (g_acc, l_acc + loss), nb

                g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, loss_sum), new_buf_all = jax.lax.scan(body, (g0, jnp.zeros((), jnp.float32)), micro)
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss_sum / accum
                new_buffers = jax.tree_util.tree_map(lambda v: v[-1], new_buf_all)
            else:
                (loss, new_buffers), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            new_params, new_state = self.optimizer.functional_apply(params, grads, opt_state, lr=lr)
            return loss, new_params, new_state, new_buffers

        batch_shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        in_shardings = (
            self.p_shardings,
            dict(self.s_shardings),
            self.b_shardings,
            repl,
        ) + tuple(batch_shard for _ in batch_arrays)
        out_shardings = (
            repl,
            self.p_shardings,
            dict(self.s_shardings),
            self.b_shardings,
        )
        return jax.jit(step, in_shardings=in_shardings, out_shardings=out_shardings,
                       donate_argnums=(0, 1))

    # -- public ---------------------------------------------------------------
    def train_step(self, *batch):
        batch_arrays = [b._data if isinstance(b, Tensor) else jnp.asarray(np.asarray(b)) for b in batch]
        if self._compiled is None:
            self._compiled = self._build(batch_arrays)
        lr = jnp.asarray(self.optimizer.get_lr(), dtype=jnp.float32)
        loss, self.params, self.opt_state, self.buffers = self._compiled(
            self.params, self.opt_state, self.buffers, lr, *batch_arrays
        )
        self.optimizer._step_count += 1
        if isinstance(self.optimizer._lr, object) and hasattr(self.optimizer._lr, "step"):
            pass  # LR schedulers advance via user calls (paddle semantics)
        return Tensor(loss)

    def sync_to_layer(self):
        """Write the (possibly sharded) params back into the Layer's tensors."""
        named = dict(self.layer.named_parameters())
        for n, v in self.params.items():
            named[n]._data = jax.device_get(v) if self.sharding_stage >= 3 else v
        named_b = dict(self.layer.named_buffers())
        for n, v in self.buffers.items():
            named_b[n]._data = v


def data_parallel_step_fn(layer, optimizer, loss_fn, mesh=None, **kw):
    return SpmdTrainer(layer, optimizer, loss_fn, mesh=mesh, **kw)
