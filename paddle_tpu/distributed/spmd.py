"""SPMD training engine — the ParallelExecutor/SSA-graph replacement.

Reference parity: this one module supersedes the reference's multi-device machinery:
ParallelExecutor + multi_devices_graph_pass (grad allreduce insertion,
framework/details/), ShardingOptimizer program surgery
(fleet/meta_optimizers/sharding_optimizer.py:161-308), and the dygraph Reducer.

TPU-native design: ONE jitted train step over a Mesh.
 - data parallel: batch sharded on 'dp'; XLA inserts the grad psum (ICI).
 - ZeRO ("sharding" stage 1/2/3): optimizer states (and for stage 3, params) get
   NamedShardings over the dp axis; XLA emits reduce_scatter/all_gather — the
   _split_program/_add_broadcast_allreduce passes become sharding annotations.
 - tensor parallel: param shardings over 'mp' provided by distributed.split layers.
 - recompute: jax.checkpoint on the forward.
 - gradient merge / accumulation: lax.scan over micro-batches.
"""
import contextlib
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from ..trace import costs as _costs
from .. import trace as _trace
from ..core.tape import global_tape
from ..core.tensor import Tensor
from ..framework import aot as _aot
from ..framework import lineage as _lineage
from ..profiler import RecordEvent as _RecordEvent
from ..testing import failpoints as _failpoints
from .mesh import get_mesh

#: The checkpoint transfer edge (ISSUE 13; docs/ANALYSIS.md "Declaring a
#: transfer edge"): the host-side train-state tree gather_train_state
#: writes and restore_train_state re-places onto live shardings.
#: Statically extracted and baseline-pinned by
#: analysis/handoff_schema.py — ROADMAP 5's topology-aware resharding
#: grows this edge into a logical [param, shard-spec] tree, and the
#: baseline is where that (intentional) drift gets acknowledged.
CHECKPOINT_SCHEMA = {
    "edge": "checkpoint_state",
    "producer": "paddle_tpu/distributed/spmd.py::gather_train_state",
    "consumer": "paddle_tpu/distributed/spmd.py::restore_train_state",
    "runtime_checked": False,
    "doc": "host snapshot of the sharded train state; __qar_residual__ "
           "(quantized-allreduce error feedback) and [dp, shard] "
           "optimizer moments ride opt_state; shard_specs records the "
           "logical [param, shard-spec] layout that wrote them so a "
           "restore onto a different dp/mp factorization re-lays-out "
           "(ISSUE 19 topology-aware resharding); __weight_version__ "
           "stamps the writer's weight lineage (ISSUE 20)",
    "payload": {
        "params": {"kind": "opaque",
                   "layout": "{param_name: host array}"},
        "opt_state": {"kind": "opaque",
                      "layout": "{param_name: {moment: host array}} + "
                                "__step__"},
        "optimizer_step_count": {"kind": "scalar", "dtype": "int"},
        "lr_scheduler": {"kind": "opaque",
                         "layout": "scheduler state_dict or None"},
        "shard_specs": {"kind": "opaque",
                        "layout": "writer topology metadata: {v, mode, "
                                  "ndp, dp_axis, shard_update, quantized, "
                                  "sharding_stage, params: {name: {shape, "
                                  "size}}, shard_ps, sharded_keys, "
                                  "qar_eligible} or None (pre-elastic "
                                  "checkpoint)"},
        "__weight_version__": {"kind": "opaque",
                               "layout": "{run_id, counter, origin} "
                                         "weight-version lineage stamp "
                                         "(framework/lineage.py) or "
                                         "absent — a pre-version "
                                         "checkpoint restores as "
                                         "version 0 (ISSUE 20)"},
    },
}

# compile_total/compile_cache_total are declared (and recorded) by
# framework/aot.py's record_compile — one mapping for every site; this
# module reports under site="trainer" so one snapshot schema covers both
# train paths
_COMPILE_MS = _monitor.histogram(
    "compile_ms", "wall time to obtain an executable (fresh compile, or "
    "lower+deserialize on an AOT-cache hit)", labelnames=("site",))
_STEP_MS = _monitor.histogram(
    "step_latency_ms",
    "Executor.run / train_step wall time (host dispatch; device-complete "
    "when FLAGS_benchmark=1 forces a sync)", labelnames=("site",))
_BENCH_SYNC = _monitor.counter(
    "benchmark_sync_total",
    "FLAGS_benchmark block_until_ready syncs on fetches",
    labelnames=("site",))
_SKIPPED = _monitor.counter(
    "train_step_skipped_total",
    "updates skipped by the FLAGS_check_nan_inf non-finite guard (params/"
    "optimizer state left bit-identical; > FLAGS_max_skip_steps "
    "consecutive skips raise)", labelnames=("reason",))

_RESHARD = None  # lazy checkpoint_reshard_total — only a cross-topology
#                  restore (FLAGS_elastic posture) ever creates the family


def _note_reshard(action, n=1):
    """Count one topology-aware restore action (lazy, the failpoints
    _note_fire pattern): moment_reshard / moment_shard / moment_unshard
    (bit-exact re-layouts of [dp, shard] moments), residual_fold /
    residual_zero / residual_drop (__qar_residual__ EF residuals re-laid
    or deterministically zeroed), step_passthrough (replicated scalars)."""
    global _RESHARD
    if not _monitor.is_enabled():
        return
    if _RESHARD is None:
        _RESHARD = _monitor.counter(
            "checkpoint_reshard_total",
            "topology-aware checkpoint restore actions by kind "
            "(docs/DISTRIBUTED.md \"Elastic training\" reshard semantics "
            "table; zero unless a checkpoint restores onto a different "
            "dp/mp factorization)",
            labelnames=("action",))
    _RESHARD.labels(action=action).inc(n)


def _batch_sig_label(batch_arrays):
    return "|".join(
        f"{a.dtype}[{','.join(str(d) for d in a.shape)}]"
        for a in batch_arrays) or "-"


def _pvary(x, ax):
    """Mark x device-varying over `ax` inside shard_map. Differentiating
    w.r.t. an UNVARYING (replicated) input auto-psums the cotangent across
    the axis — so a "local" gradient taken against replicated params comes
    back pre-summed. pvary first keeps the grad genuinely rank-local.
    On jax versions with neither pcast nor pvary, shard_map's cotangents
    for replicated inputs are already rank-local (no auto-psum — verified
    empirically on 0.4.x) and the identity fallback is correct."""
    try:
        return jax.lax.pcast(x, (ax,), to="varying")
    except (AttributeError, TypeError):
        try:
            return jax.lax.pvary(x, (ax,))
        except (AttributeError, TypeError):
            return x


def owned_device_put(v, sh):
    """device_put that never shares buffers with `v`.

    The jitted train step donates its param/state inputs; device_put to a
    replicated sharding reuses the source's buffer for the shard on its device,
    so donating the placed array would invalidate the Layer's eager tensors
    (and any other trainer placed from the same source). Copy first so the
    trainer exclusively owns every buffer it donates."""
    return jax.device_put(jnp.copy(jnp.asarray(v)), sh)


def _first_divisible_axis(shape, n):
    for i, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return i
    return None


def param_shardings(params, mesh, axis_name, min_size=16384, shard_params=False):
    """ZeRO-style shardings: arrays >= min_size sharded on their first divisible dim."""
    n = mesh.shape[axis_name]
    out = {}
    for k, v in params.items():
        ax = _first_divisible_axis(v.shape, n)
        if shard_params and ax is not None and v.size >= min_size:
            spec = [None] * v.ndim
            spec[ax] = axis_name
            out[k] = NamedSharding(mesh, P(*spec))
        else:
            out[k] = NamedSharding(mesh, P())
    return out


def state_shardings(opt_state, p_shardings, mesh, axis_name, stage):
    """Shard optimizer moments like their params (stage>=2) or replicate."""
    out = {}
    n = mesh.shape[axis_name]
    for pname, st in opt_state.items():
        if pname == "__step__":
            out[pname] = NamedSharding(mesh, P())
            continue
        sub = {}
        for k, v in st.items():
            if stage >= 2 and hasattr(v, "ndim") and v.ndim > 0:
                ax = _first_divisible_axis(v.shape, n)
                if ax is not None and v.size >= 16384:
                    spec = [None] * v.ndim
                    spec[ax] = axis_name
                    sub[k] = NamedSharding(mesh, P(*spec))
                    continue
            sub[k] = NamedSharding(mesh, P())
        out[pname] = sub
    return out


def _collect_moe_aux(layer):
    """Sum MoE load-balance aux losses from the last forward (None if dense).

    Keeps the router's load-balancing gradient alive on trainer paths where the
    loss_fn only sees (outputs, labels)."""
    from ..nn.layer.moe import MoELayer

    aux = None
    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, MoELayer) and sub.aux_loss is not None:
            aux = sub.aux_loss if aux is None else aux + sub.aux_loss
    return aux


#: named selective-remat policies (SpmdTrainer recompute_policy=...): the
#: TPU-native analog of the reference RecomputeConfig.checkpoints name list
_REMAT_POLICIES = {
    "dots": "dots_saveable",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def _resolve_remat_policy(name):
    if name not in _REMAT_POLICIES:
        raise ValueError(f"recompute_policy must be one of "
                         f"{sorted(_REMAT_POLICIES)}, got {name!r}")
    return getattr(jax.checkpoint_policies, _REMAT_POLICIES[name])


class SpmdTrainer:
    """Compile a Layer + Optimizer + loss into one sharded XLA train step."""

    def __init__(self, layer, optimizer, loss_fn=None, mesh=None, dp_axis="dp",
                 sharding_stage=0, recompute=False, accumulate_steps=1,
                 extra_param_specs=None, metrics_fn=None, donate=True,
                 amp_dtype=None, return_outputs=False, **extra_kwargs):
        self.layer = layer
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_mesh()
        self.dp_axis = dp_axis
        self.sharding_stage = sharding_stage
        self.recompute = recompute
        self.accumulate_steps = accumulate_steps
        self.extra_param_specs = extra_param_specs or {}
        self.amp_dtype = amp_dtype
        # hapi metric path: the jitted step also returns the network outputs
        # (no second eager forward per batch); see JitGraphAdapter
        self.return_outputs = return_outputs
        self.last_outputs = None
        self.extra_kwargs = extra_kwargs
        # consumed meta-optimizer knobs (VERDICT r1 #2: every flag must change
        # the compiled program or raise)
        self.localsgd_k = extra_kwargs.get("localsgd_k")
        self.localsgd_begin = extra_kwargs.get("localsgd_begin", 1)
        self.state_offload = bool(extra_kwargs.get("state_offload"))
        if self.localsgd_k:
            if sharding_stage > 0 or accumulate_steps > 1 or extra_param_specs:
                raise ValueError(
                    "localsgd holds per-rank param replicas and cannot compose "
                    "with sharding/gradient-merge/tensor-parallel specs")
        if return_outputs and (self.localsgd_k or self._is_dgc()):
            raise ValueError(
                "return_outputs is not supported with localsgd/DGC steps "
                "(their shard_map programs do not thread outputs)")
        pol = extra_kwargs.get("recompute_policy")
        if pol is not None:
            _resolve_remat_policy(pol)  # fail fast on unknown names
            if not recompute:
                raise ValueError("recompute_policy requires recompute=True "
                                 "(the policy selects WHAT jax.checkpoint "
                                 "saves; without remat it changes nothing)")
            if extra_kwargs.get("remat_offload"):
                raise ValueError("remat_offload and recompute_policy both "
                                 "select a jax.checkpoint policy — pick one")
        self._compiled = None       # latest executable (back-compat handle)
        self._compiled_store = {}   # (batch-sig, guarded, numerics,
        #                             quantized, shard_update) ->
        #                             (executable, guarded, numerics,
        #                             qerr-leg) — these flags change the
        #                             step's output arity (finiteness
        #                             verdict / fused health-stats leg /
        #                             quantization-error scalar)
        self._nonfinite_streak = 0  # consecutive skipped steps
        self._nonfinite_total = 0   # lifetime skipped steps (stats())
        # step-time accounting for stats(): host wall time per step plus
        # the FLAGS_benchmark sync share, joined with the cost registry's
        # per-executable FLOPs into the MFU report (docs/OBSERVABILITY.md)
        self._step_count = 0
        self._step_ms_sum = 0.0
        self._sync_ms_sum = 0.0
        self._last_sig = None       # batch-sig label of the last step
        self._step_span = None      # open span of the in-flight step
        self._cost_entries = {}     # THIS trainer's sig -> cost entry: a
        #                             second trainer with the same batch
        #                             shapes must not clobber our join
        # numerics telescope (FLAGS_numerics, docs/OBSERVABILITY.md):
        # the monitor is created lazily on the first armed fetch so the
        # plain path never imports monitor/numerics.py at all
        self._numerics = None
        self._numerics_seen = 0            # armed steps so far
        self._numerics_last_device = None  # device-resident stats leg
        self._numerics_last_host = None    # cached fetch of the above
        # perf ledger (FLAGS_perf_ledger, docs/OBSERVABILITY.md):
        # consumed at construction. Deliberately NON-structural — the
        # ledger only observes host-side timings and never changes the
        # compiled program, so it joins NO executable key (armed and
        # disarmed runs share AOT entries and train byte-identically);
        # disarmed, the hook in _finish_step is one `is not None`
        self._perf_ledger = None
        self._perf_mesh_fp = None
        self._perf_cold = False   # last step resolved a compile
        if _flags.get_flag("perf_ledger", False):
            from ..monitor import perfledger as _perfledger

            self._perf_ledger = _perfledger.get_ledger()
            self._perf_mesh_fp = _aot.mesh_fingerprint(self.mesh)
        # weight-version lineage (framework/lineage.py, ISSUE 20):
        # ALWAYS-ON host metadata — every weight state this trainer
        # produces carries a monotone (run_id, counter, origin) stamp,
        # bumped per step/restore/reshard, written into checkpoints as
        # the __weight_version__ leaf and onto train_step spans. No
        # metric series, no compiled-program effect: parity is trivial.
        self.weight_version = _lineage.WeightVersion(
            _lineage.new_run_id(), 0, "init")
        # goodput accountant (FLAGS_goodput, docs/OBSERVABILITY.md):
        # consumed at construction. Deliberately NON-structural like the
        # perf ledger — wall-clock bucketing only, joins NO executable
        # key; disarmed, every hook is one `is not None`
        self._goodput = None
        if _flags.get_flag("goodput", False):
            from ..monitor import goodput as _goodput

            self._goodput = _goodput
            _goodput.ensure_run(self.weight_version.run_id)
        self.params = {n: p._data for n, p in layer.named_parameters() if getattr(p, "trainable", True)}
        self.frozen = {n: p._data for n, p in layer.named_parameters() if not getattr(p, "trainable", True)}
        self.buffers = {n: b._data for n, b in layer.named_buffers()}
        self.opt_state = optimizer.functional_init(self.params)
        # bandwidth-frugal dp (docs/DISTRIBUTED.md): both flags are
        # consumed HERE — the quantized reduce lays residual state into
        # the opt-state pytree and update sharding re-shapes the moments,
        # so a post-construction toggle raises (see _compress_active)
        # instead of silently mis-reducing
        self._quantized, self._shard_update = self._resolve_compress()
        self._qerr_device = None    # banked per-step quantization-error
        #                             norm (device-resident; fetched
        #                             lazily by quantize_error())
        # async double-buffered dispatch (docs/PERF.md): the flag and its
        # window are consumed HERE (post-hoc toggles raise via
        # _async_active); the deferred-guard ledger below exists on EVERY
        # trainer — the non-async path defers the verdict fetch by one
        # step, the armed path by up to FLAGS_async_window steps. Only
        # the armed path imports distributed/async_dispatch.py.
        self._async, self._async_window = self._resolve_async()
        self._overlap_comm = self._resolve_overlap()
        self._mpmd = self._resolve_mpmd()
        self._elastic = self._resolve_elastic()
        self._pending_verdicts = []  # [(schedule position, device bool)]
        self._guard_abort = None     # undelivered deferred FloatingPointError
        self._verdict_fetches = 0    # drains (host syncs) so far
        self._window_max_depth = 0   # deepest in-flight window seen
        self._prefetch_hits = 0      # prefetch()-staged batches consumed
        self._prefetched = None      # (ids key, device arrays) or None
        if self._async:
            from . import async_dispatch as _async_mod

            # crash/stall bundles record how deep the in-flight window
            # was (weakly held — same contract as the serving provider)
            _blackbox.register_provider("trainer_async", self,
                                        _async_mod.blackbox_table)
        self._place_state()

    # -- bandwidth-frugal dp (quantized all-reduce / update sharding) ----------
    def _resolve_compress(self):
        """Consume FLAGS_quantized_allreduce / FLAGS_shard_weight_update
        at construction. Returns (quantized, shard_update) after
        validating the config: both run the plain-dp shard_map step, so
        ZeRO stages / gradient merge / tensor-parallel specs /
        return_outputs are rejected loudly; localsgd/DGC silently ignore
        the flags (they own their reduce — the PR 4 guard's carve-out).
        Also captures bits/min-size and the eligibility set (float
        params >= FLAGS_quantized_allreduce_min_size elements)."""
        q = bool(_flags.get_flag("quantized_allreduce", False))
        s = bool(_flags.get_flag("shard_weight_update", False))
        self._qar_bits = int(_flags.get_flag("quantized_allreduce_bits", 8))
        self._qar_min_size = int(
            _flags.get_flag("quantized_allreduce_min_size", 1024))
        self._qar_eligible = frozenset()
        self._shard_state_keys = {}
        self._shard_ps = {}
        if not (q or s) or self.localsgd_k or self._is_dgc():
            return False, False
        names = ("FLAGS_quantized_allreduce" if q else "") \
            + ("+" if q and s else "") \
            + ("FLAGS_shard_weight_update" if s else "")
        if self.sharding_stage > 0:
            raise ValueError(
                f"{names} targets the plain-dp path; sharding_stage="
                f"{self.sharding_stage} already reduce-scatters through "
                "XLA's ZeRO shardings — pick one (docs/DISTRIBUTED.md "
                "composition matrix)")
        if self.accumulate_steps > 1:
            raise ValueError(
                f"{names} does not compose with gradient merge "
                "(accumulate_steps > 1) yet")
        if self.extra_param_specs:
            raise ValueError(
                f"{names} does not compose with tensor-parallel "
                "extra_param_specs (params must be replicated over dp)")
        if self.return_outputs:
            raise ValueError(
                f"{names} steps run under shard_map, which does not "
                "thread network outputs (same restriction as "
                "localsgd/DGC)")
        if q:
            from . import compress as _compress

            _compress._check_bits(self._qar_bits)
            self._qar_eligible = frozenset(
                n for n, v in self.params.items()
                if jnp.issubdtype(v.dtype, jnp.floating)
                and v.size >= self._qar_min_size)
        if s and type(self.optimizer).__name__ in ("Lamb", "Lars",
                                                   "LarsMomentum"):
            raise ValueError(
                "FLAGS_shard_weight_update needs an elementwise update "
                f"rule; {type(self.optimizer).__name__}'s trust-ratio "
                "reads whole-parameter norms, which a 1/dp shard cannot "
                "see (docs/DISTRIBUTED.md)")
        return q, s

    def _compress_active(self):
        """FLAGS_quantized_allreduce was consumed at construction (the
        error-feedback residuals ride the opt-state pytree laid out
        then); a post-construction toggle is loud instead of silently
        mis-reducing. localsgd/DGC carve-out as for the PR 4 guard —
        the disarmed check is one get_flag + compare."""
        q = bool(_flags.get_flag("quantized_allreduce", False))
        if q != self._quantized and not self.localsgd_k \
                and not self._is_dgc():
            raise RuntimeError(
                "FLAGS_quantized_allreduce changed after this trainer "
                "was constructed; the quantized reduce lays out its "
                "error-feedback residual state at __init__ — build a "
                "new SpmdTrainer under the new flag value")
        return self._quantized

    def _shard_update_active(self):
        """FLAGS_shard_weight_update, same construction-time contract
        as _compress_active (the optimizer moments are stored sharded)."""
        s = bool(_flags.get_flag("shard_weight_update", False))
        if s != self._shard_update and not self.localsgd_k \
                and not self._is_dgc():
            raise RuntimeError(
                "FLAGS_shard_weight_update changed after this trainer "
                "was constructed; update sharding re-shapes the "
                "optimizer-state pytree at __init__ — build a new "
                "SpmdTrainer under the new flag value")
        return self._shard_update

    # -- MPMD stage runtime (distributed/stage.py) -----------------------------
    def _resolve_mpmd(self):
        """Consume FLAGS_mpmd at construction. The data-parallel trainer
        has no stage split — the flag only keys the executables here
        (exec key + AOT extra_key), so an MPMD-armed process never
        aliases a cache entry with a plain one; the armed runtime itself
        lives on PipelineTrainer/DisaggregatedPool."""
        return bool(_flags.get_flag("mpmd", False))

    def _mpmd_active(self):
        """FLAGS_mpmd was consumed at construction (it is baked into
        this trainer's executable keys); a post-construction toggle is
        loud instead of silently re-keying mid-run. One get_flag +
        compare when disarmed."""
        m = bool(_flags.get_flag("mpmd", False))
        if m != self._mpmd:
            raise RuntimeError(
                "FLAGS_mpmd changed after this trainer was constructed; "
                "the flag is baked into the executable cache keys at "
                "__init__ — build a new trainer under the new flag "
                "value")
        return self._mpmd

    # -- elastic training (distributed/elastic.py) -----------------------------
    def _resolve_elastic(self):
        """Consume FLAGS_elastic at construction. Arms resize(mesh) and
        keys the executables (exec key + AOT extra_key) so an elastic
        world never aliases a plain cache entry; the supervisor itself
        lives in the manifest-lazy distributed/elastic.py — a plain
        trainer never imports it (tests/test_elastic_gate.py)."""
        return bool(_flags.get_flag("elastic", False))

    def _elastic_active(self):
        """FLAGS_elastic was consumed at construction (it is baked into
        this trainer's executable keys and gates resize); a
        post-construction toggle is loud instead of silently re-keying
        mid-run. One get_flag + compare when disarmed."""
        e = bool(_flags.get_flag("elastic", False))
        if e != self._elastic:
            raise RuntimeError(
                "FLAGS_elastic changed after this trainer was "
                "constructed; the flag is baked into the executable "
                "cache keys at __init__ — build a new trainer under the "
                "new flag value")
        return self._elastic

    # -- async double-buffered dispatch (docs/PERF.md) -------------------------
    def _resolve_async(self):
        """Consume FLAGS_async_dispatch / FLAGS_async_window at
        construction. Returns (armed, window); window is 1 when the flag
        is unset — the non-async deferred-by-one guard fetch."""
        a = bool(_flags.get_flag("async_dispatch", False))
        w = max(1, int(_flags.get_flag("async_window", 8))) if a else 1
        return a, w

    def _async_active(self):
        """FLAGS_async_dispatch was consumed at construction (the step
        handle/window machinery is armed then); a post-construction
        toggle is loud instead of silently changing what train_step
        returns. One get_flag + compare when disarmed."""
        a = bool(_flags.get_flag("async_dispatch", False))
        if a != self._async:
            raise RuntimeError(
                "FLAGS_async_dispatch changed after this trainer was "
                "constructed; the step-handle/deferred-verdict window is "
                "armed at __init__ — build a new SpmdTrainer under the "
                "new flag value")
        return self._async

    def _resolve_overlap(self):
        """Consume FLAGS_overlap_grad_comm at construction: per-layer
        int8 exchange legs interleavable with backward compute. Only
        meaningful on the quantized quant-only path — anything else is
        rejected loudly (shard_weight_update already exchanges per leg);
        localsgd/DGC ignore it like every compress flag."""
        o = bool(_flags.get_flag("overlap_grad_comm", False))
        if not o or self.localsgd_k or self._is_dgc():
            return False
        if not self._quantized:
            raise ValueError(
                "FLAGS_overlap_grad_comm splits the quantized gradient "
                "exchange into per-layer legs — it requires "
                "FLAGS_quantized_allreduce (docs/PERF.md overlap matrix)")
        if self._shard_update:
            raise ValueError(
                "FLAGS_overlap_grad_comm composed with "
                "FLAGS_shard_weight_update is redundant: the sharded "
                "update already exchanges one quantized leg per param")
        return True

    def _overlap_active(self):
        """Construction-time contract for FLAGS_overlap_grad_comm (the
        leg structure is part of the compiled program's identity)."""
        o = bool(_flags.get_flag("overlap_grad_comm", False))
        if o != self._overlap_comm and not self.localsgd_k \
                and not self._is_dgc():
            raise RuntimeError(
                "FLAGS_overlap_grad_comm changed after this trainer was "
                "constructed; the per-leg exchange structure is compiled "
                "in — build a new SpmdTrainer under the new flag value")
        return self._overlap_comm

    def _drain_verdicts(self, force=False, deliver=False):
        """Host-fetch pending deferred guard verdicts and replay the
        skip bookkeeping in dispatch order (docs/PERF.md "deferred
        guard"). Without `force`, drains only when the window is full —
        ONE host sync per FLAGS_async_window steps. A trailing skip
        rolls the optimizer schedule position back (the device never
        advanced __step__ for it — the retry contract holds); a streak
        beyond FLAGS_max_skip_steps raises the same FloatingPointError
        the per-step fetch used to, just up to a window later.

        The abort is STICKY until delivered through a train_step call
        (`deliver=True`): a drain triggered inside an observability
        helper (stats() under a scraper's try/except) may have its
        raise swallowed, but the run still cannot train past the limit
        — the next train_step entry re-raises it."""
        if self._guard_abort is not None:
            err = self._guard_abort
            if deliver:
                self._guard_abort = None
            raise err
        pending = self._pending_verdicts
        if not pending or (not force and len(pending) < self._async_window):
            return
        if len(pending) > self._window_max_depth:
            self._window_max_depth = len(pending)
        batch, self._pending_verdicts = pending, []
        self._verdict_fetches += 1
        if self._async and _monitor.is_enabled():
            from . import async_dispatch as _async_mod

            _async_mod.window_depth_gauge().set(len(batch))
            _async_mod.verdict_fetch_counter().inc()
        # ONE device_get for the whole window — THE deliberate host sync
        # of the guard path (everything else stays device-resident)
        vals = jax.device_get([v for _, v in batch])  # lint: allow(step-loop-host-sync)
        raise_streak = None
        for (pos, _), val in zip(batch, vals):
            if bool(val):   # device_get above already landed it on host
                self._nonfinite_streak = 0
                continue
            # the update was skipped ON DEVICE (params/state/buffers
            # where-selected pre-update, __step__ included); the host
            # learns now
            self._nonfinite_streak += 1
            self._nonfinite_total += 1
            if pos == self.optimizer._step_count - 1:
                # the skip is the NEWEST dispatch — nothing consumed
                # the next schedule position yet, so rewind and the
                # retry reuses this slot (the window-1 / sync-path
                # retry contract, exactly). A MID-window skip's
                # position is burned instead: later dispatches already
                # advanced the schedule, and rewinding would hand the
                # next dispatch an rng position an APPLIED step
                # already consumed (duplicated dropout masks).
                self.optimizer._step_count -= 1
            _SKIPPED.labels(reason="nonfinite").inc()
            if _trace.is_enabled():
                # the skipping step's own span ended long ago — the
                # trace-level skip signal lands at discovery time
                with _trace.span("guard/skip", subsystem="trainer",
                                 step=int(pos)):
                    pass
            max_skip = int(_flags.get_flag("max_skip_steps", 3))
            if self._nonfinite_streak > max_skip:
                raise_streak = self._nonfinite_streak
        if raise_streak is not None:
            max_skip = int(_flags.get_flag("max_skip_steps", 3))
            err = FloatingPointError(
                f"train_step: non-finite loss/gradients for "
                f"{raise_streak} consecutive steps "
                f"(> FLAGS_max_skip_steps={max_skip}); aborting — "
                "every skipped step left parameters untouched (the "
                "on-device where-select); finite steps dispatched LATER "
                "in this deferred window (if any) applied normally "
                "before the limit was discovered (docs/PERF.md); "
                "inspect the data pipeline / learning rate")
            if not deliver:
                self._guard_abort = err   # sticky until train_step sees it
            raise err

    def guard_sync(self):
        """Force-fetch every pending deferred guard verdict NOW: after
        this, stats()/streak counters reflect every dispatched step and
        a pending FloatingPointError surfaces here. The per-step fetch
        the pre-async trainer did, on demand."""
        self._drain_verdicts(force=True)

    def prefetch(self, *batch):
        """Stage the NEXT step's batch on device (async double-
        buffering): device_put runs asynchronously, so the transfer
        overlaps the in-flight step's compute. The next train_step call
        made with the SAME array objects consumes the staged copies
        instead of re-marshalling them. The originals are HELD here
        until consumed (identity is the match key), and a train_step
        over DIFFERENT arrays discards the staging. Standard
        double-buffer contract: do not mutate a staged array in place
        before the step that consumes it — the device copy was taken
        at prefetch() time."""
        from jax.sharding import NamedSharding as _NS

        shard = _NS(self.mesh, P(self.dp_axis))
        arrays = [jax.device_put(
            b._data if isinstance(b, Tensor) else jnp.asarray(np.asarray(b)),
            shard) for b in batch]
        self._prefetched = (batch, arrays)

    # -- sharding placement ----------------------------------------------------
    def _offload_state_shardings(self, force=False):
        """sharding_configs.offload parity: optimizer moments live in pinned
        host memory; XLA inserts the HBM<->host transfers around the update.
        TPU-only — the CPU backend cannot execute replicated pinned_host
        programs (same XLA limitation as remat_offload). `force` skips the
        CPU guard so tests can assert the produced memory kinds."""
        on_cpu = (not force and
                  np.asarray(self.mesh.devices).flat[0].platform == "cpu")
        if on_cpu:
            import warnings

            warnings.warn("state_offload ignored on the CPU backend; "
                          "optimizer state stays in device memory")
            return self.s_shardings
        out = {}
        for pname, st in self.s_shardings.items():
            if pname == "__step__":
                out[pname] = st
                continue
            out[pname] = {
                k: NamedSharding(sh.mesh, sh.spec, memory_kind="pinned_host")
                for k, sh in st.items()
            }
        return out

    def _place_state(self):
        mesh = self.mesh
        ax = self.dp_axis
        if self.localsgd_k:
            # LocalSGD: every dp rank holds its own param/moment replica
            # (leading replica dim sharded on dp); see _build_localsgd
            ndp = mesh.shape[ax]
            rep = lambda v: jnp.broadcast_to(v, (ndp,) + v.shape)
            self.params = {k: rep(v) for k, v in self.params.items()}
            self.p_shardings = {k: NamedSharding(mesh, P(ax)) for k in self.params}
            self.s_shardings, new_state = {}, {}
            for pname, st in self.opt_state.items():
                if pname == "__step__":
                    self.s_shardings[pname] = NamedSharding(mesh, P())
                    new_state[pname] = st
                    continue
                self.s_shardings[pname] = {k: NamedSharding(mesh, P(ax)) for k in st}
                new_state[pname] = {k: rep(v) for k, v in st.items()}
            self.opt_state = new_state
            self.b_shardings = {k: NamedSharding(mesh, P()) for k in self.buffers}
            self.params = {k: owned_device_put(v, self.p_shardings[k]) for k, v in self.params.items()}
            self.buffers = {k: owned_device_put(v, self.b_shardings[k]) for k, v in self.buffers.items()}
            self.opt_state = {
                pname: (owned_device_put(st, self.s_shardings[pname]) if pname == "__step__"
                        else {k: owned_device_put(v, self.s_shardings[pname][k]) for k, v in st.items()})
                for pname, st in self.opt_state.items()
            }
            return
        if self._is_dgc():
            if self.sharding_stage > 0 or self.accumulate_steps > 1:
                raise ValueError("DGC composes with plain data parallel only "
                                 "(no sharding / gradient merge)")
            ndp = mesh.shape[ax]
            # params/velocity replicated; DGC residuals u/v are PER-RANK state
            self.p_shardings = {k: NamedSharding(mesh, P()) for k in self.params}
            self.s_shardings, new_state = {}, {}
            for pname, st in self.opt_state.items():
                if pname == "__step__":
                    self.s_shardings[pname] = NamedSharding(mesh, P())
                    new_state[pname] = st
                    continue
                sub_sh, sub = {}, {}
                for k, v in st.items():
                    if k in ("dgc_u", "dgc_v"):
                        sub_sh[k] = NamedSharding(mesh, P(ax))
                        sub[k] = jnp.broadcast_to(v, (ndp,) + v.shape)
                    else:
                        sub_sh[k] = NamedSharding(mesh, P())
                        sub[k] = v
                self.s_shardings[pname] = sub_sh
                new_state[pname] = sub
            self.opt_state = new_state
            self.b_shardings = {k: NamedSharding(mesh, P()) for k in self.buffers}
            self.params = {k: owned_device_put(v, self.p_shardings[k]) for k, v in self.params.items()}
            self.buffers = {k: owned_device_put(v, self.b_shardings[k]) for k, v in self.buffers.items()}
            self.opt_state = {
                pname: (owned_device_put(st, self.s_shardings[pname]) if pname == "__step__"
                        else {k: owned_device_put(v, self.s_shardings[pname][k]) for k, v in st.items()})
                for pname, st in self.opt_state.items()
            }
            return
        if self._quantized or self._shard_update:
            # bandwidth-frugal dp layout (docs/DISTRIBUTED.md): params and
            # buffers replicated (the step all-gathers updated params
            # itself when sharding the update); with shard_weight_update
            # every param-shaped optimizer moment is flattened, padded,
            # and stored [dp, shard] over the dp axis (scalar state like
            # Adam's beta powers stays replicated — its update is
            # rank-invariant); with quantized_allreduce each eligible
            # param carries a per-rank error-feedback residual
            # [dp, *shape] under the reserved __qar_residual__ key
            ndp = mesh.shape[ax]
            block = 1
            if self._quantized:
                from . import compress as _compress

                block = _compress.DEFAULT_BLOCK
            self.p_shardings = {k: NamedSharding(mesh, P())
                                for k in self.params}
            self.b_shardings = {k: NamedSharding(mesh, P())
                                for k in self.buffers}
            if self._shard_update:
                for k, v in self.params.items():
                    if k in self._qar_eligible:
                        # the quantized exchange hands each rank whole
                        # blocks — the state shard must line up with it
                        unit = block * ndp
                        self._shard_ps[k] = (-(-int(v.size) // unit)
                                             * unit) // ndp
                    else:
                        self._shard_ps[k] = -(-int(v.size) // ndp)
            s_sh, new_state = {}, {}
            for pname, st in self.opt_state.items():
                if pname == "__step__":
                    s_sh[pname] = NamedSharding(mesh, P())
                    new_state[pname] = st
                    continue
                p = self.params[pname]
                sub_sh, sub, sharded_keys = {}, {}, set()
                for k, v in st.items():
                    if (self._shard_update
                            and getattr(v, "shape", None) == p.shape):
                        ps = self._shard_ps[pname]
                        flat = jnp.pad(jnp.ravel(v),
                                       (0, ps * ndp - int(v.size)))
                        sub[k] = flat.reshape(ndp, ps)
                        sub_sh[k] = NamedSharding(mesh, P(ax))
                        sharded_keys.add(k)
                    else:
                        sub[k] = v
                        sub_sh[k] = NamedSharding(mesh, P())
                s_sh[pname] = sub_sh
                new_state[pname] = sub
                self._shard_state_keys[pname] = sharded_keys
            if self._quantized:
                res_sh, res = {}, {}
                for name in sorted(self._qar_eligible):
                    v = self.params[name]
                    res[name] = jnp.zeros((ndp,) + tuple(v.shape),
                                          jnp.float32)
                    res_sh[name] = NamedSharding(mesh, P(ax))
                new_state["__qar_residual__"] = res
                s_sh["__qar_residual__"] = res_sh
            self.s_shardings = s_sh
            self.opt_state = new_state
            self.params = {k: owned_device_put(v, self.p_shardings[k])
                           for k, v in self.params.items()}
            self.buffers = {k: owned_device_put(v, self.b_shardings[k])
                            for k, v in self.buffers.items()}
            self.opt_state = {
                pname: (owned_device_put(st, self.s_shardings[pname])
                        if pname == "__step__"
                        else {k: owned_device_put(v,
                                                  self.s_shardings[pname][k])
                              for k, v in st.items()})
                for pname, st in self.opt_state.items()
            }
            return
        self.p_shardings = param_shardings(
            self.params, mesh, ax, shard_params=(self.sharding_stage >= 3)
        )
        for k, spec in self.extra_param_specs.items():
            if k in self.p_shardings:
                self.p_shardings[k] = NamedSharding(mesh, spec)
        self.s_shardings = state_shardings(self.opt_state, self.p_shardings, mesh, ax, self.sharding_stage)
        if self.state_offload:
            self.s_shardings = self._offload_state_shardings()
        self.b_shardings = {k: NamedSharding(mesh, P()) for k in self.buffers}
        # device_put everything per its sharding (owned copies: the step donates)
        self.params = {k: owned_device_put(v, self.p_shardings[k]) for k, v in self.params.items()}
        self.buffers = {k: owned_device_put(v, self.b_shardings[k]) for k, v in self.buffers.items()}
        new_state = {}
        for pname, st in self.opt_state.items():
            if pname == "__step__":
                new_state[pname] = owned_device_put(st, NamedSharding(self.mesh, P()))
            else:
                new_state[pname] = {k: owned_device_put(v, self.s_shardings[pname][k]) for k, v in st.items()}
        self.opt_state = new_state

    # -- pure step -------------------------------------------------------------
    def _forward_loss(self, params, buffers, batch, rng=None):
        import contextlib

        from ..core.functional import functional_state
        from ..core.generator import traced_rng

        layer = self.layer
        tape = global_tape()
        amp_ctx = contextlib.nullcontext()
        if self.amp_dtype is not None:
            from ..amp.auto_cast import auto_cast

            amp_ctx = auto_cast(True, dtype=self.amp_dtype)
        rng_ctx = traced_rng(rng) if rng is not None else contextlib.nullcontext()
        with functional_state(layer, {**params, **self.frozen},
                              buffers) as (named_p, named_b):
            with tape.pause(), amp_ctx, rng_ctx:
                inputs = [Tensor(b) for b in batch[:-1]]
                label = Tensor(batch[-1])
                out = None
                if self.loss_fn is not None:
                    out = layer(*inputs)
                    loss = self.loss_fn(out, label)
                    aux = _collect_moe_aux(layer)
                    if aux is not None:
                        w = getattr(getattr(layer, "cfg", None), "moe_aux_weight", 0.01)
                        loss = loss + w * aux
                else:
                    loss = layer(*inputs, label)
            new_buffers = {n: named_b[n]._data for n in buffers}
            out_raw = None
            if self.return_outputs and out is not None:
                out_raw = jax.tree_util.tree_map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            return (loss._data if isinstance(loss, Tensor) else loss,
                    new_buffers, out_raw)

    def _is_dgc(self):
        """DGC + dp>1: grads must be top-k compressed BEFORE the cross-rank
        reduce (the whole point of DGC) — handled by _build_dgc."""
        from .fleet.meta_optimizers.dgc_optimizer import DGCMomentumOptimizer

        return (isinstance(self.optimizer, DGCMomentumOptimizer)
                and self.dp_axis in self.mesh.axis_names
                and self.mesh.shape[self.dp_axis] > 1)

    def _wrapped_forward(self):
        fwd = self._forward_loss
        if self.recompute:
            # the offload custom call (annotate_device_placement) has no CPU
            # lowering under the sharded jit step in this jax version; guard
            # verified empirically — the policy itself works on TPU
            on_cpu = np.asarray(self.mesh.devices).flat[0].platform == "cpu"
            if self.extra_kwargs.get("remat_offload") and on_cpu:
                import warnings

                warnings.warn("remat_offload ignored on the CPU backend; "
                              "falling back to plain recompute")
            if self.extra_kwargs.get("remat_offload") and not on_cpu:
                # RecomputeConfig.enable_offload parity: matmul residuals go
                # to pinned host memory instead of being recomputed or held
                # in HBM (reference offloads checkpoints to CPU)
                policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                    "device", "pinned_host")
                fwd = jax.checkpoint(fwd, static_argnums=(), policy=policy)
            elif self.extra_kwargs.get("recompute_policy") is not None:
                # selective remat: trade recompute FLOPs vs HBM per policy.
                # 'dots' saves matmul outputs (recompute elementwise only) —
                # usually the sweet spot on TPU; 'nothing' recomputes
                # everything (max memory savings, max FLOPs).
                fwd = jax.checkpoint(
                    fwd, static_argnums=(),
                    policy=_resolve_remat_policy(
                        self.extra_kwargs["recompute_policy"]))
            else:
                fwd = jax.checkpoint(fwd, static_argnums=())
        return fwd

    def _build(self, batch_arrays):
        if self.localsgd_k:
            return self._build_localsgd(batch_arrays)
        if self._is_dgc():
            return self._build_dgc(batch_arrays)
        if self._compress_active() or self._shard_update_active():
            return self._build_dp_compressed(batch_arrays)
        mesh = self.mesh
        ax = self.dp_axis
        fwd = self._wrapped_forward()
        accum = self.accumulate_steps

        want_out = self.return_outputs
        guard = self._guard_active()
        narmed = self._numerics_active()
        if narmed:
            from ..monitor import numerics as _numerics

            # SORTED param order: jax returns dict pytrees key-sorted, so
            # self.params' insertion order changes after the first step —
            # sorted is the one order that matches across build/fetch
            stat_layers = sorted(self.params)

        def step(params, opt_state, buffers, lr, rng, *batch):
            def loss_fn(p, b, r):
                loss, new_buf, outs = fwd(p, buffers, b, r)
                return loss.astype(jnp.float32), (new_buf, outs)

            if accum > 1:
                # gradient merge (fleet/meta_optimizers/gradient_merge_optimizer.py):
                # micro-batch scan, grads averaged; per-micro rng via fold_in
                micro = [jnp.reshape(b, (accum, b.shape[0] // accum) + b.shape[1:]) for b in batch]

                def body(carry, xs):
                    g_acc, l_acc = carry
                    mb, idx = xs[:-1], xs[-1]
                    r = jax.random.fold_in(rng, idx)
                    (loss, aux), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, list(mb), r)
                    g_acc = jax.tree_util.tree_map(lambda a, g: a + g, g_acc, grads)
                    return (g_acc, l_acc + loss), aux

                g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, loss_sum), (new_buf_all, outs_all) = jax.lax.scan(
                    body, (g0, jnp.zeros((), jnp.float32)),
                    tuple(micro) + (jnp.arange(accum),))
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss_sum / accum
                new_buffers = jax.tree_util.tree_map(lambda v: v[-1], new_buf_all)
                # outputs scanned [accum, mb, ...] -> full batch [accum*mb, ...]
                outputs = (jax.tree_util.tree_map(
                    lambda v: v.reshape((-1,) + v.shape[2:]), outs_all)
                    if want_out else None)
            else:
                (loss, (new_buffers, outputs)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, batch, rng)
            new_params, new_state = self.optimizer.functional_apply(params, grads, opt_state, lr=lr)
            nstats = None
            if narmed:
                # FLAGS_numerics: the fused per-layer health aggregation
                # (monitor/numerics.py), computed on the RAW grads and
                # update BEFORE any guard select — a poisoned step must
                # still name the layer that went non-finite
                nstats = _numerics.device_stats(
                    stat_layers, loss, grads, params, new_params)
            if guard:
                # FLAGS_check_nan_inf: ONE fused on-device finiteness
                # verdict over loss + every gradient; a non-finite step
                # selects the PRE-update params/state/buffers (bit-
                # identical — __step__ included, so the LR schedule does
                # not advance either) and reports the flag to the host
                finite = jnp.isfinite(loss)
                for g in jax.tree_util.tree_leaves(grads):
                    finite = jnp.logical_and(
                        finite, jnp.all(jnp.isfinite(g)))

                def keep(new, old):
                    return jnp.where(finite, new, old)

                new_params = jax.tree_util.tree_map(keep, new_params, params)
                new_state = jax.tree_util.tree_map(keep, new_state, opt_state)
                new_buffers = jax.tree_util.tree_map(
                    keep, new_buffers, buffers)
            out = [loss, new_params, new_state, new_buffers]
            if want_out:
                out.append(outputs)
            if narmed:
                out.append(nstats)
            if guard:
                out.append(finite)
            return tuple(out)

        batch_shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        in_shardings = (
            self.p_shardings,
            dict(self.s_shardings),
            self.b_shardings,
            repl,
            repl,  # per-step rng key
        ) + tuple(batch_shard for _ in batch_arrays)
        out_shardings = (
            repl,
            self.p_shardings,
            dict(self.s_shardings),
            self.b_shardings,
        )
        if want_out:
            # outputs: per-example arrays, batch-sharded over dp (prefix spec)
            out_shardings = out_shardings + (batch_shard,)
        if narmed:
            out_shardings = out_shardings + (
                _numerics.stat_shardings(repl),)   # the stats leg
        if guard:
            out_shardings = out_shardings + (repl,)   # the finite flag
        # buffers (argnum 2) donate like params/opt_state: the trainer
        # owns them (owned_device_put) and rebinds them from the step
        # output every call — not donating doubled their HBM footprint
        # (the donation-miss finding ISSUE 13's sharding targets surfaced)
        return jax.jit(step, in_shardings=in_shardings, out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))

    def _shard_map(self, f, in_specs, out_specs, check_rep=True):
        """check_rep=False is for bodies whose replicated outputs flow
        through all_gather: the values are identical on every rank by
        construction (deterministic dequantize of identical gathered
        bytes), but static rep-inference cannot prove it — the compressed
        dp step's tests assert the cross-replica equality dynamically."""
        ax = self.dp_axis
        try:
            return jax.shard_map(f, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, axis_names={ax},
                                 **({} if check_rep
                                    else {"check_vma": False}))
        except (AttributeError, TypeError):
            try:
                from jax import shard_map as sm
            except ImportError:
                from jax.experimental.shard_map import shard_map as sm

            try:
                return sm(f, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs,
                          **({} if check_rep else {"check_rep": False}))
            except TypeError:
                return sm(f, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs)

    def _build_localsgd(self, batch_arrays):
        """LocalSGD (fleet/meta_optimizers/localsgd_optimizer.py parity, SPMD):
        every dp rank trains its own param replica for k steps with NO grad
        allreduce; every k-th step (>= begin_step) the replicas are pmean'd.
        The compiled program provably differs from plain DP: the per-step grad
        psum disappears and a step-gated param pmean appears."""
        mesh, ax = self.mesh, self.dp_axis
        k, begin = int(self.localsgd_k), int(self.localsgd_begin)
        fwd = self._wrapped_forward()
        opt = self.optimizer

        def step(params, opt_state, buffers, lr, rng, *batch):
            def local(params_r, state_r, buffers, lr, rng, *batch_local):
                p = {n: v[0] for n, v in params_r.items()}
                st = {n: (v if n == "__step__" else {m: a[0] for m, a in v.items()})
                      for n, v in state_r.items()}
                # per-rank dropout masks (ranks intentionally diverge)
                r = jax.random.fold_in(rng, jax.lax.axis_index(ax))

                def loss_fn(pp, b):
                    loss, nb, _ = fwd(pp, buffers, b, r)
                    return loss.astype(jnp.float32), nb

                (loss, new_buf), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(p, batch_local)
                new_p, new_st = opt.functional_apply(p, grads, st, lr=lr)
                step_no = new_st["__step__"]
                do_avg = jnp.logical_and(step_no >= begin, step_no % k == 0)
                avg = {n: jax.lax.pmean(v, ax) for n, v in new_p.items()}
                new_p = {n: jnp.where(do_avg, avg[n], new_p[n]) for n in new_p}
                loss = jax.lax.pmean(loss, ax)
                new_buf = {n: jax.lax.pmean(v, ax) for n, v in new_buf.items()}
                out_p = {n: v[None] for n, v in new_p.items()}
                out_st = {n: (v if n == "__step__" else {m: a[None] for m, a in v.items()})
                          for n, v in new_st.items()}
                return loss, out_p, out_st, new_buf

            in_specs = (
                {n: P(ax) for n in params},
                {n: (P() if n == "__step__" else {m: P(ax) for m in st})
                 for n, st in opt_state.items()},
                {n: P() for n in buffers},
                P(),
                P(),  # rng key (ranks fold in their axis index)
            ) + tuple(P(ax) for _ in batch)
            out_specs = (P(), {n: P(ax) for n in params},
                         {n: (P() if n == "__step__" else {m: P(ax) for m in st})
                          for n, st in opt_state.items()},
                         {n: P() for n in buffers})
            return self._shard_map(local, in_specs, out_specs)(
                params, opt_state, buffers, lr, rng, *batch)

        batch_shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        in_shardings = (self.p_shardings, dict(self.s_shardings),
                        self.b_shardings, repl, repl) + tuple(batch_shard for _ in batch_arrays)
        out_shardings = (repl, self.p_shardings, dict(self.s_shardings), self.b_shardings)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))  # buffers too (ISSUE 13)

    def _build_dgc(self, batch_arrays):
        """DGC (dgc_momentum_op.cc parity) with a REAL cross-rank sparse
        reduce: each dp rank momentum-corrects its LOCAL gradient, top-k
        sparsifies, and only the sparse tensor crosses the interconnect
        (psum); residuals u/v stay rank-local. Plain DP psums the dense grad;
        this program psums the masked one — compressing what crosses DCN."""
        mesh, ax = self.mesh, self.dp_axis
        opt = self.optimizer
        m = opt._momentum
        sparsity = opt._sparsity
        fwd = self._wrapped_forward()

        def step(params, opt_state, buffers, lr, rng, *batch):
            def local(params, state_r, buffers, lr, rng, *batch_local):
                st = {n: (v if n == "__step__" else
                          {k2: (a[0] if k2 in ("dgc_u", "dgc_v") else a)
                           for k2, a in v.items()})
                      for n, v in state_r.items()}
                r = jax.random.fold_in(rng, jax.lax.axis_index(ax))

                def loss_fn(pp, b):
                    loss, nb, _ = fwd(pp, buffers, b, r)
                    return loss.astype(jnp.float32), nb

                # differentiate against VARYING params: grads stay rank-local
                # so top-k masks the local gradient and pmean below is the one
                # true cross-rank reduce (see _pvary)
                params_v = {n: _pvary(p, ax) for n, p in params.items()}
                (loss, new_buf), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params_v, batch_local)
                new_p, new_st = {}, {"__step__": st["__step__"] + 1}
                for n, p in params.items():
                    g = grads[n].astype(p.dtype)
                    u = m * st[n]["dgc_u"] + g
                    v = st[n]["dgc_v"] + u
                    kk = max(1, int(v.size * (1.0 - sparsity)))
                    thresh = jax.lax.top_k(jnp.abs(v).reshape(-1), kk)[0][-1]
                    mask = (jnp.abs(v) >= thresh).astype(v.dtype)
                    sparse = v * mask
                    # THE DGC allreduce: only the compressed tensor crosses ranks
                    cross = jax.lax.pmean(sparse, ax)
                    new_p[n] = p - lr.astype(p.dtype) * cross
                    new_st[n] = {"velocity": st[n]["velocity"],
                                 "dgc_u": (u * (1 - mask))[None],
                                 "dgc_v": (v * (1 - mask))[None]}
                loss = jax.lax.pmean(loss, ax)
                new_buf = {n: jax.lax.pmean(v, ax) for n, v in new_buf.items()}
                return loss, new_p, new_st, new_buf

            state_spec = {n: (P() if n == "__step__" else
                              {k2: (P(ax) if k2 in ("dgc_u", "dgc_v") else P())
                               for k2 in st})
                          for n, st in opt_state.items()}
            in_specs = ({n: P() for n in params}, state_spec,
                        {n: P() for n in buffers}, P(),
                        P()) + tuple(P(ax) for _ in batch)
            out_specs = (P(), {n: P() for n in params}, state_spec,
                         {n: P() for n in buffers})
            return self._shard_map(local, in_specs, out_specs)(
                params, opt_state, buffers, lr, rng, *batch)

        batch_shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        in_shardings = (self.p_shardings, dict(self.s_shardings),
                        self.b_shardings, repl, repl) + tuple(batch_shard for _ in batch_arrays)
        out_shardings = (repl, self.p_shardings, dict(self.s_shardings), self.b_shardings)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))  # buffers too (ISSUE 13)

    def _build_dp_compressed(self, batch_arrays):
        """Plain-dp train step with an EXPLICIT gradient exchange
        (shard_map over dp) replacing the jit path's XLA-inserted psum,
        so the wire format is ours to choose (docs/DISTRIBUTED.md):

        - FLAGS_quantized_allreduce (EQuARX, arXiv:2506.17615): eligible
          grads are padded to quantization blocks, error-feedback
          corrected, bundled, and moved through
          compress.quantized_all_reduce_ef — int8 on the wire, float32
          accumulation, stochastic rounding keyed off the step rng;
          per-layer residuals ride the opt-state pytree as
          __qar_residual__. Small/non-float grads stay on the exact fp32
          pmean.
        - FLAGS_shard_weight_update (arXiv:2004.13336): per param, grads
          are reduce-scattered, the optimizer update runs on each
          replica's 1/dp shard against its sharded moments, and only the
          UPDATED param all-gathers back — no replica computes the same
          update twice. Composed with the quantized flag, the quantized
          exchange's scatter phase feeds the sharded update directly
          (the fp32 all-reduce never exists in any form).

        The PR 4 guard threads through: the finiteness verdict is taken
        on the RAW local loss/grads before any quantization and pmin'd
        across ranks, and the where-select restores params, buffers, AND
        the residuals/sharded moments bit-exactly — a skipped step
        carries no quantization poison forward. The numerics telescope's
        stats leg reads the REDUCED grads, with the per-layer non-finite
        element counts psum'd from the raw local grads so a poisoned
        step still names the dying layer."""
        from . import collective as _coll
        from . import compress as _compress
        from ..optimizer.optimizer import _GLOBAL_NORM_TYPES

        mesh, ax = self.mesh, self.dp_axis
        ndp = mesh.shape[ax]
        opt = self.optimizer
        fwd = self._wrapped_forward()
        quant, shard_upd = self._quantized, self._shard_update
        bits, block = self._qar_bits, _compress.DEFAULT_BLOCK
        guard = self._guard_active()
        narmed = self._numerics_active()
        if narmed:
            from ..monitor import numerics as _numerics

            stat_layers = sorted(self.params)
        eligible = self._qar_eligible
        pnames = list(self.params)
        shapes = {n: (tuple(v.shape), int(v.size), v.dtype)
                  for n, v in self.params.items()}
        has_clip = (opt._grad_clip is not None
                    and isinstance(opt._grad_clip, _GLOBAL_NORM_TYPES))

        # static bundle plan for the fused quantized reduce (quant-only
        # mode): each eligible grad padded to whole blocks so no scale
        # spans two layers, then one exchange moves the whole bundle.
        # FLAGS_overlap_grad_comm instead plans one leg per eligible
        # layer: the legs are independent collectives XLA's scheduler is
        # free to interleave with the remaining backward compute (the
        # EQuARX hide-behind-compute condition; docs/PERF.md)
        plan, bundle, legs = [], 0, []
        if quant and not shard_upd:
            unit = block * ndp
            if self._overlap_comm:
                for name in pnames:
                    if name in eligible:
                        L = -(-shapes[name][1] // unit) * unit
                        legs.append((name, L))
            else:
                for name in pnames:
                    if name in eligible:
                        L = -(-shapes[name][1] // block) * block
                        plan.append((name, bundle, L))
                        bundle += L
                bundle = -(-bundle // unit) * unit if bundle else 0

        def step(params, opt_state, buffers, lr, rng, *batch):
            def local(params, state_r, buffers, lr, rng, *batch_local):
                res_in = state_r.get("__qar_residual__", {})
                st_in = {n: v for n, v in state_r.items()
                         if n != "__qar_residual__"}
                # differentiate against VARYING params so grads stay
                # rank-local and the explicit exchange below is the one
                # true cross-rank reduce (see _pvary)
                params_v = {n: _pvary(p, ax) for n, p in params.items()}

                def loss_fn(pp, b):
                    loss, nb, _ = fwd(pp, buffers, b, rng)
                    return loss.astype(jnp.float32), nb

                (loss, new_buf), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params_v, batch_local)
                qkey = jax.random.fold_in(rng, 0x514152)
                finite = None
                if guard:
                    # verdict on the RAW local values, agreed across
                    # ranks BEFORE any quantization touches the grads
                    finite = jnp.isfinite(loss)
                    for g in jax.tree_util.tree_leaves(grads):
                        finite = jnp.logical_and(
                            finite, jnp.all(jnp.isfinite(g)))
                    finite = jax.lax.pmin(
                        finite.astype(jnp.int32), ax) > 0
                raw_nonf = None
                if narmed:
                    raw_nonf = jax.lax.psum(jnp.stack([
                        jnp.sum(~jnp.isfinite(
                            grads[n].astype(jnp.float32))
                        ).astype(jnp.float32)
                        for n in stat_layers]), ax)

                red = {}          # name -> full-shape MEAN grad (f32)
                g_shards = {}     # name -> [ps] MEAN grad shard (f32)
                res_out = {}
                qerr_sq = jnp.zeros((), jnp.float32)
                if legs:
                    # overlapped per-layer legs: each eligible grad is
                    # its own EF-corrected int8 exchange with a per-leg
                    # rounding key — independent ops the scheduler can
                    # pipeline against backward compute
                    for i, (name, L) in enumerate(legs):
                        shape, size, _ = shapes[name]
                        g32 = grads[name].astype(jnp.float32).ravel()
                        inp = (g32 + res_in[name][0]
                               .astype(jnp.float32).ravel())
                        flat = jnp.pad(inp, (0, L - size))
                        _coll.record_compressed(
                            "quantized_all_reduce", size * 4,
                            L * bits // 8 + (L // block) * 4)
                        reduced, local_rt = \
                            _compress.quantized_all_reduce_ef(
                                flat, ax, jax.random.fold_in(qkey, i),
                                bits=bits, block=block)
                        red[name] = (reduced[:size] / ndp).reshape(shape)
                        r_new = (inp - local_rt[:size]).reshape(shape)
                        res_out[name] = r_new
                        qerr_sq = qerr_sq + jnp.sum(r_new * r_new)
                if plan and bundle:
                    parts, logical = [], 0
                    for name, off, L in plan:
                        g32 = grads[name].astype(jnp.float32).ravel()
                        inp = (g32 + res_in[name][0]
                               .astype(jnp.float32).ravel())
                        parts.append(jnp.pad(inp, (0, L - g32.shape[0])))
                        logical += shapes[name][1] * 4
                    tail = bundle - sum(L for _, _, L in plan)
                    if tail:
                        parts.append(jnp.zeros((tail,), jnp.float32))
                    flat = (jnp.concatenate(parts) if len(parts) > 1
                            else parts[0])
                    _coll.record_compressed(
                        "quantized_all_reduce", logical,
                        bundle * bits // 8 + (bundle // block) * 4)
                    reduced, local_rt = _compress.quantized_all_reduce_ef(
                        flat, ax, qkey, bits=bits, block=block)
                    for name, off, L in plan:
                        shape, size, _ = shapes[name]
                        red[name] = (reduced[off:off + size]
                                     / ndp).reshape(shape)
                        r_new = (flat[off:off + size]
                                 - local_rt[off:off + size]).reshape(shape)
                        res_out[name] = r_new
                        qerr_sq = qerr_sq + jnp.sum(r_new * r_new)
                if shard_upd:
                    for i, name in enumerate(pnames):
                        shape, size, _ = shapes[name]
                        ps = self._shard_ps[name]
                        g32 = grads[name].astype(jnp.float32).ravel()
                        if name in eligible:
                            inp = (g32 + res_in[name][0]
                                   .astype(jnp.float32).ravel())
                            flat = jnp.pad(inp, (0, ps * ndp - size))
                            _coll.record_compressed(
                                "quantized_reduce_scatter", size * 4,
                                ps * ndp * bits // 8
                                + (ps * ndp // block) * 4)
                            shard_sum, local_rt = _compress._exchange_reduce(
                                flat, ax, jax.random.fold_in(qkey, i),
                                bits, block)
                            r_new = (inp - local_rt[:size]).reshape(shape)
                            res_out[name] = r_new
                            qerr_sq = qerr_sq + jnp.sum(r_new * r_new)
                        else:
                            flat = jnp.pad(g32, (0, ps * ndp - size))
                            _monitor.record_collective(
                                "reduce-scatter",
                                _monitor.tensor_nbytes(flat))
                            shard_sum = jax.lax.psum_scatter(
                                flat, ax, tiled=True)
                        g_shards[name] = shard_sum / ndp
                else:
                    for name in pnames:
                        if name not in red:
                            g = grads[name]
                            _monitor.record_collective(
                                "all-reduce", _monitor.tensor_nbytes(g))
                            red[name] = jax.lax.pmean(g, ax)

                # ---- optimizer update ---------------------------------
                if shard_upd:
                    wd = jnp.asarray(opt._wd, jnp.float32)
                    stats_red = None
                    if narmed:
                        # the telescope reads full-shape reduced grads
                        # (pre-clip, like the plain path); gathering them
                        # is diagnostic-only traffic
                        stats_red = {}
                        for name in pnames:
                            shape, size, _ = shapes[name]
                            full = jax.lax.all_gather(
                                g_shards[name], ax, tiled=True)
                            stats_red[name] = full[:size].reshape(shape)
                    if has_clip:
                        local_sq = sum(jnp.sum(v * v)
                                       for v in g_shards.values())
                        gnorm = jnp.sqrt(jax.lax.psum(local_sq, ax))
                        clip_norm = opt._grad_clip.clip_norm
                        scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                        g_shards = {k: v * scale
                                    for k, v in g_shards.items()}
                    idx = jax.lax.axis_index(ax)
                    new_params, new_st = {}, {}
                    for name in pnames:
                        shape, size, dtype = shapes[name]
                        ps = self._shard_ps[name]
                        p = params[name]
                        p_flat = jnp.pad(jnp.ravel(p),
                                         (0, ps * ndp - size))
                        p_shard = jax.lax.dynamic_slice_in_dim(
                            p_flat, idx * ps, ps)
                        sharded = self._shard_state_keys.get(name, set())
                        st_shard = {k: (v[0] if k in sharded else v)
                                    for k, v in st_in[name].items()}
                        new_p_shard, new_st_shard = opt._rule_with_decay(
                            p_shard, g_shards[name].astype(p.dtype),
                            st_shard, lr, wd)
                        _monitor.record_collective(
                            "all-gather",
                            _monitor.tensor_nbytes(new_p_shard) * ndp)
                        full = jax.lax.all_gather(new_p_shard, ax,
                                                  tiled=True)
                        new_params[name] = full[:size].reshape(shape)
                        new_st[name] = {
                            k: (v[None] if k in sharded else v)
                            for k, v in new_st_shard.items()}
                    new_st["__step__"] = st_in["__step__"] + 1
                else:
                    stats_red = red
                    new_params, new_st = opt.functional_apply(
                        params, red, st_in, lr=lr)

                loss_red = jax.lax.pmean(loss, ax)
                nstats = None
                if narmed:
                    nstats = _numerics.device_stats(
                        stat_layers, loss_red, stats_red, params,
                        new_params)
                    # raw-grad attribution: the reduced grads a poisoned
                    # step produces are already NaN-scaled, but the
                    # per-layer ELEMENT counts must come from the raw
                    # local grads (psum'd above) to match the plain
                    # path's naming contract
                    nstats = dict(nstats)
                    nstats["nonfinite"] = raw_nonf
                if quant:
                    new_st = dict(new_st)
                    new_st["__qar_residual__"] = {
                        n: res_out[n][None] for n in res_out}
                qerr = None
                if quant:
                    if guard:
                        # a guard-skipped step restores the OLD
                        # residuals — report THEIR norm, not the
                        # poisoned one this step computed and discarded
                        old_sq = jnp.zeros((), jnp.float32)
                        for n in res_out:
                            r_old = res_in[n][0].astype(jnp.float32)
                            old_sq = old_sq + jnp.sum(r_old * r_old)
                        qerr_sq = jnp.where(finite, qerr_sq, old_sq)
                    qerr = jnp.sqrt(jax.lax.psum(qerr_sq, ax))
                new_buffers = {n: jax.lax.pmean(v, ax)
                               for n, v in new_buf.items()}
                if guard:
                    def keep(new, old):
                        return jnp.where(finite, new, old)

                    new_params = jax.tree_util.tree_map(
                        keep, new_params, params)
                    new_st = jax.tree_util.tree_map(
                        keep, new_st, dict(state_r))
                    new_buffers = jax.tree_util.tree_map(
                        keep, new_buffers, buffers)
                out = [loss_red, new_params, new_st, new_buffers]
                if narmed:
                    out.append(nstats)
                if guard:
                    out.append(finite)
                if quant:
                    out.append(qerr)
                return tuple(out)

            state_spec = {}
            for pname, st in opt_state.items():
                if pname == "__step__":
                    state_spec[pname] = P()
                elif pname == "__qar_residual__":
                    state_spec[pname] = {k: P(ax) for k in st}
                else:
                    sharded = self._shard_state_keys.get(pname, set())
                    state_spec[pname] = {
                        k: (P(ax) if k in sharded else P()) for k in st}
            in_specs = (
                {n: P() for n in params}, state_spec,
                {n: P() for n in buffers}, P(), P(),
            ) + tuple(P(ax) for _ in batch)
            out_specs = [P(), {n: P() for n in params}, state_spec,
                         {n: P() for n in buffers}]
            if narmed:
                out_specs.append({k: P() for k in _numerics.STAT_KEYS})
            if guard:
                out_specs.append(P())
            if quant:
                out_specs.append(P())
            return self._shard_map(local, in_specs, tuple(out_specs),
                                   check_rep=False)(
                params, opt_state, buffers, lr, rng, *batch)

        batch_shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        in_shardings = (self.p_shardings, dict(self.s_shardings),
                        self.b_shardings, repl,
                        repl) + tuple(batch_shard for _ in batch_arrays)
        out_shardings = [repl, self.p_shardings, dict(self.s_shardings),
                         self.b_shardings]
        if narmed:
            out_shardings.append(_numerics.stat_shardings(repl))
        if guard:
            out_shardings.append(repl)
        if quant:
            out_shardings.append(repl)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=tuple(out_shardings),
                       donate_argnums=(0, 1, 2))   # buffers too (ISSUE 13)

    # -- compile (lazy or warm-start) ------------------------------------------
    @staticmethod
    def _batch_sig_key(batch_arrays):
        return tuple((tuple(a.shape), str(a.dtype)) for a in batch_arrays)

    def _guard_active(self):
        """FLAGS_check_nan_inf builds the step with the on-device non-
        finite guard (docs/ROBUSTNESS.md). localsgd/DGC shard_map programs
        don't thread the verdict — the flag is ignored there."""
        return (bool(_flags.get_flag("check_nan_inf"))
                and not self.localsgd_k and not self._is_dgc())

    def _numerics_active(self):
        """FLAGS_numerics appends the fused health-stats leg to the
        compiled step (monitor/numerics.py, docs/OBSERVABILITY.md
        "Numerics telescope"). localsgd/DGC shard_map programs don't
        thread it — the same carve-out as the non-finite guard. The flag
        lives in flags.py so this check never imports the telescope."""
        return (bool(_flags.get_flag("numerics"))
                and not self.localsgd_k and not self._is_dgc())

    def _exec_key(self, batch_arrays):
        # the guard/numerics legs change the compiled program's output
        # arity, so they are part of the executable's identity: toggling
        # either flag recompiles instead of mis-unpacking a stale
        # executable. The compressed-dp legs join too (quantized adds
        # the qerr output; both swap the whole program) — they are
        # construction-time static, but _compress_active/_shard_update_
        # active also make a post-hoc flag flip raise here instead of
        # silently reusing the wrong executable
        return (self._batch_sig_key(batch_arrays), self._guard_active(),
                self._numerics_active(), self._compress_active(),
                self._shard_update_active(), self._overlap_active(),
                self._mpmd_active(), self._elastic_active())

    def _aot_compile(self, batch_arrays, lr, rng, force=False):
        """Build the jitted step for THIS batch signature and obtain its
        executable — through the persistent AOT cache (framework/aot.py)
        when FLAGS_jit_cache_dir is set, else the plain lazy jit. Compiled
        steps are kept per batch signature (a trailing partial batch must
        not evict or shadow the full-batch executable); batch_arrays may
        be jax.ShapeDtypeStructs (aot_build: nothing is executed)."""
        sig = _batch_sig_label(batch_arrays)
        guarded = self._guard_active()
        narmed = self._numerics_active()
        with (self._goodput.bucket("compile") if self._goodput is not None
              else contextlib.nullcontext()), \
                _RecordEvent("trainer/compile"), \
                _monitor.timed(_COMPILE_MS.labels(site="trainer")):
            jitted = self._build(batch_arrays)
            compiled, source = _aot.compile_cached(
                jitted,
                (self.params, self.opt_state, self.buffers, lr, rng,
                 *batch_arrays),
                # the perf ledger forces the eager (cost-accountable)
                # compile exactly as tracing does: MFU needs the
                # executable's flops, which a lazy bypass jit never
                # exposes — same program, so still non-structural
                site="trainer", force=force or _trace.is_enabled()
                or self._perf_ledger is not None,
                extra_key=("trainer", _aot.mesh_fingerprint(self.mesh),
                           self.dp_axis, self.sharding_stage,
                           self.accumulate_steps, guarded, narmed,
                           self._quantized, self._shard_update,
                           self._qar_bits, self._qar_min_size,
                           self._overlap_comm, self._mpmd, self._elastic))
        self._compiled_store[self._exec_key(batch_arrays)] = (
            compiled, guarded, narmed, self._quantized)
        self._compiled = compiled  # latest executable (back-compat handle)
        _aot.record_compile("trainer", sig, source)
        cost_entry = _costs.record("trainer", sig,
                                   _aot.executable_of(compiled))
        if cost_entry is not None:
            self._cost_entries[sig] = cost_entry
        return source

    def aot_build(self, batch_specs):
        """Warm-start: compile the train step from batch shape specs — no
        real data, nothing executed. One (shape, dtype) pair (or
        jax.ShapeDtypeStruct) per train_step positional arg::

            trainer.aot_build([((8, 128), "int32"), ((8, 128), "int32")])

        With FLAGS_jit_cache_dir set, the executable is loaded from /
        stored into the persistent cache; without it, the step is still
        AOT-compiled in memory. Either way the first train_step pays zero
        compile. Returns where the executable came from (disk|fresh)."""
        from ..core.generator import default_generator

        specs = []
        for spec in batch_specs:
            if isinstance(spec, jax.ShapeDtypeStruct):
                specs.append(spec)
            else:
                shape, dtype = spec
                specs.append(jax.ShapeDtypeStruct(tuple(shape),
                                                  np.dtype(dtype)))
        lr = jnp.asarray(self.optimizer.get_lr(), dtype=jnp.float32)
        rng = default_generator().fold_in(self.optimizer._step_count)
        return self._aot_compile(specs, lr, rng, force=True)

    # -- public ---------------------------------------------------------------
    def train_step(self, *batch):
        # window beacon around the whole step (compile included): a hung
        # compile or device dispatch leaves an active, non-advancing
        # trainer/step site for the stall sentinel; a finished training
        # run deactivates it instead of reading as stalled forever
        if self._goodput is not None:
            # goodput `step` bucket around the whole step — a compile
            # resolving inside nests its own bucket and PAUSES this one,
            # so productive time never double-books (FLAGS_goodput)
            with self._goodput.bucket("step"), \
                    _blackbox.progress("trainer/step"):
                return self._train_step_impl(*batch)
        with _blackbox.progress("trainer/step"):
            return self._train_step_impl(*batch)

    def _train_step_impl(self, *batch):
        from ..core.generator import default_generator

        _failpoints.failpoint("trainer/step")
        self._async_active()   # post-hoc toggle raises (ctor contract)
        # deferred guard (docs/PERF.md): settle PREVIOUS steps' verdicts
        # before this step's schedule position is read — a full window
        # drains in ONE device_get; a trailing skip rewinds the
        # schedule so this dispatch retries the skipped position.
        # deliver=True: a sticky abort a swallowed stats() drain left
        # behind is re-raised (and cleared) HERE, to train_step's caller
        self._drain_verdicts(deliver=True)
        t_step = time.perf_counter()
        pre, self._prefetched = self._prefetched, None
        if pre is not None and len(pre[0]) == len(batch) \
                and all(a is b for a, b in zip(pre[0], batch)):
            # prefetch() already staged THESE arrays on device while the
            # previous step ran — consume the copies, skip marshalling.
            # (A non-matching step discards the staging: stale copies
            # must not linger to be consumed many steps later.)
            batch_arrays = pre[1]
            self._prefetch_hits += 1
        else:
            batch_arrays = [b._data if isinstance(b, Tensor)
                            else jnp.asarray(np.asarray(b))  # lint: allow(step-loop-host-sync)
                            for b in batch]
        # value-transforming failpoint (scale:F) — chaos tests inject a
        # gradient spike / non-finite batch here; one boolean check when
        # nothing is armed (docs/ROBUSTNESS.md)
        batch_arrays = _failpoints.transform("trainer/batch", batch_arrays)
        lr = jnp.asarray(self.optimizer.get_lr(), dtype=jnp.float32)
        # fresh per-step randomness (dropout etc.): deterministic under
        # paddle.seed, varies per step — a trace-time key would bake ONE
        # dropout mask into the compiled program
        rng = default_generator().fold_in(self.optimizer._step_count)
        sig_label = _batch_sig_label(batch_arrays)
        self._last_sig = sig_label
        entry = self._compiled_store.get(self._exec_key(batch_arrays))
        if entry is None:
            source = self._aot_compile(batch_arrays, lr, rng)
            entry = self._compiled_store[self._exec_key(batch_arrays)]
        else:
            source = "memory"
            if _monitor.is_enabled():
                _aot.record_compile("trainer", sig_label, "memory")
        compiled, guarded, narmed, qleg = entry
        if self._perf_ledger is not None:
            self._perf_cold = source != "memory"
        # exec window starts AFTER compile resolution: stats()/MFU must
        # divide flops by run time, not by jit-build + AOT-compile time
        # (step_latency_ms keeps its historical include-compile meaning)
        t_exec = time.perf_counter()
        # step span: compile-cache source + batch signature (+sync time,
        # stamped by _finish_step); carries the step's trace identity
        # and the weight version this step advances FROM (ISSUE 20)
        self._step_span = _trace.start_span(
            "train_step", subsystem="trainer", sig=sig_label, source=source,
            step=int(self.optimizer._step_count), guarded=guarded,
            weight_version=str(self.weight_version))
        try:
            if self.localsgd_k or self._is_dgc():
                loss, self.params, self.opt_state, self.buffers = compiled(
                    self.params, self.opt_state, self.buffers, lr, rng, *batch_arrays
                )
                self.optimizer._step_count += 1
                return self._finish_step(loss, t_step, t_exec)
            out = list(compiled(
                self.params, self.opt_state, self.buffers, lr, rng, *batch_arrays
            ))
            # fixed unpack order matching _build's packing: loss, state,
            # then the optional legs — outputs / numerics stats / finite
            loss = out.pop(0)
            self.params = out.pop(0)
            self.opt_state = out.pop(0)
            self.buffers = out.pop(0)
            if self.return_outputs:  # ctor rejects localsgd/dgc combinations
                self.last_outputs = jax.tree_util.tree_map(Tensor,
                                                           out.pop(0))
            nstats = out.pop(0) if narmed else None
            finite = out.pop(0) if guarded else None
            if qleg:
                # the quantization-error norm stays device-resident
                # until quantize_error()/stats() asks for it — no new
                # per-step host sync
                self._qerr_device = out.pop(0)
            if nstats is not None:
                # keep the stats leg device-resident; the host fetch
                # happens only every FLAGS_numerics_interval steps
                self._numerics_note(nstats)
            if finite is not None:
                # DEFERRED verdict (docs/PERF.md): the skip already
                # happened on device if it happened at all — bank the
                # device-resident verdict instead of syncing on it here.
                # The schedule advances optimistically; _drain_verdicts
                # rewinds it when a skip is discovered, so the loss
                # trajectory is bit-exact with the old per-step fetch.
                self._pending_verdicts.append(
                    (int(self.optimizer._step_count), finite))
            self.optimizer._step_count += 1
            return self._finish_step(loss, t_step, t_exec)
        except BaseException:
            # the failing step still leaves its span (the very step a
            # trace gets pulled for); a stale handle must not leak into
            # the next step's _finish_step
            sp = self._step_span
            if sp is not None:
                sp.end(error=True)
                self._step_span = None
            raise

    def _finish_step(self, loss, t_step, t_exec=None):
        """Monitor tail of train_step: optional FLAGS_benchmark device sync
        (so step_latency_ms measures device work) + the latency sample +
        the step-span/stats() accounting the MFU report reads. `t_step`
        includes any compile (the histogram's historical meaning);
        `t_exec` excludes it — that is what stats()/MFU accumulate, so a
        2-step run is not dominated by the first step's compile."""
        # the handle's schedule identity, captured BEFORE the benchmark
        # drain below may rewind the counter for this very step's skip
        sched = int(self.optimizer._step_count) - 1
        # the params this step produced are a NEW weight state (a
        # device-side skip still re-ran the program; the lineage tracks
        # states served/trained, not loss-improving updates)
        self.weight_version = self.weight_version.bump("step")
        sync_ms = 0.0
        if _flags.get_flag("benchmark"):
            t_sync = time.perf_counter()
            if hasattr(loss, "block_until_ready"):
                loss.block_until_ready()  # lint: allow(step-loop-host-sync)
            _BENCH_SYNC.labels(site="trainer").inc()
            # the device is drained anyway: settle pending guard
            # verdicts for free (same-call skip visibility under
            # FLAGS_benchmark, exactly the pre-deferral semantics);
            # deliver=True — this raise reaches train_step's caller
            self._drain_verdicts(force=True, deliver=True)
            sync_ms = (time.perf_counter() - t_sync) * 1e3
        now = time.perf_counter()
        step_ms = (now - t_step) * 1e3
        exec_ms = (now - (t_exec if t_exec is not None else t_step)) * 1e3
        if _monitor.is_enabled():
            _STEP_MS.labels(site="trainer").observe(step_ms)
        self._step_count += 1
        self._step_ms_sum += exec_ms
        self._sync_ms_sum += sync_ms
        sp = self._step_span
        if sp is not None:
            sp.end(sync_ms=sync_ms, step_ms=step_ms, exec_ms=exec_ms)
            self._step_span = None
            _trace.add_counter_sample("trainer_step_ms", step_ms)
        if self._perf_ledger is not None:
            self._ledger_step(step_ms, exec_ms, sync_ms)
        if self._async:
            from . import async_dispatch as _async_mod

            return _async_mod.StepHandle(loss, sched, trainer=self)
        return Tensor(loss)

    # -- perf ledger (FLAGS_perf_ledger) ---------------------------------------
    def _ledger_step(self, step_ms, exec_ms, sync_ms):
        """Armed-only per-step perf-ledger feed: the regression sentinel
        sees every step's wall times + t_exec-windowed MFU; a JSONL row
        (sig + mesh fingerprint) lands every FLAGS_perf_ledger_interval
        steps. A step that resolved a compile is recorded (``cold: 1``)
        but kept OUT of the baseline — its jit-build wall time is not
        the steady state the sentinel guards. Host-side bookkeeping only
        — the compiled step is the disarmed one."""
        m = {"step_ms": step_ms, "exec_ms": exec_ms, "sync_ms": sync_ms}
        if self._perf_cold:
            m["cold"] = 1
        entry = (self._cost_entries.get(self._last_sig)
                 or _costs.get("trainer", self._last_sig)
                 if self._last_sig else None)
        flops = entry.get("flops") if entry else None
        peak = _costs.peak_flops()
        if flops and exec_ms and peak:
            m["mfu"] = float(flops) / ((exec_ms / 1e3) * peak)
            m["flops_per_step"] = flops
        if entry and entry.get("bytes_accessed"):
            m["bytes_per_step"] = entry["bytes_accessed"]
        self._perf_ledger.on_step("trainer", m, sig=self._last_sig,
                                  mesh=self._perf_mesh_fp,
                                  check=not self._perf_cold)

    # -- quantized-reduce observability ----------------------------------------
    def quantize_error(self):
        """Host-fetch the last quantized step's global quantization-error
        L2 norm — the error-feedback residual about to be re-injected —
        and publish the lazy ``quantize_error_norm`` gauge. None until a
        FLAGS_quantized_allreduce step has run; between calls the scalar
        stays device-resident (no per-step host sync)."""
        if self._qerr_device is None:
            return None
        val = float(np.asarray(self._qerr_device))
        if _monitor.is_enabled() and np.isfinite(val):
            from . import compress as _compress

            _compress.error_gauge().set(val)
        return val

    # -- numerics telescope ----------------------------------------------------
    def _numerics_note(self, nstats):
        """Bank the step's device-resident stats leg; fetch to host only
        every FLAGS_numerics_interval steps — between fetches the arrays
        never cross the device boundary."""
        self._numerics_seen += 1
        self._numerics_last_device = nstats
        self._numerics_last_host = None
        interval = max(1, int(_flags.get_flag("numerics_interval", 1)))
        if self._numerics_seen % interval == 0:
            self.numerics_fetch()

    def numerics_fetch(self):
        """Fetch the latest on-device numerics stats to the host, feed
        the drift detectors, and return the host dict (STAT_KEYS ->
        np arrays, rows in ``sorted(self.params)`` order) — or None when
        FLAGS_numerics never armed a step. Idempotent per step (the
        parity harness force-fetches after every step without double-
        observing); emits a ``numerics/fetch`` span."""
        if self._numerics_last_host is not None:
            return self._numerics_last_host
        nstats = self._numerics_last_device
        if nstats is None:
            return None
        from ..monitor import numerics as _numerics_mod

        if self._numerics is None:
            # sorted order — matching _build's stat_layers (see there)
            self._numerics = _numerics_mod.NumericsMonitor(
                sorted(self.params), source="trainer")
        with _trace.span("numerics/fetch", subsystem="trainer",
                         step=int(self.optimizer._step_count)):
            if _monitor.is_enabled():
                with _monitor.timed(
                        _numerics_mod._metrics()["fetch_ms"]):
                    host = jax.device_get(nstats)
            else:
                host = jax.device_get(nstats)
        host = {k: np.asarray(v) for k, v in host.items()}
        self._numerics_last_host = host
        # stamp anomalies with the OPTIMIZER step — the same clock the
        # train_step/numerics-fetch spans carry, so a crash bundle's
        # anomaly cross-references its span tree (skipped guard steps
        # repeat a step number; that IS the schedule position retried)
        self._numerics.observe(host, step=int(self.optimizer._step_count))
        return host

    def stats(self):
        """Trainer observability snapshot: step counts/wall time joined
        with the device cost registry into an MFU estimate.

        ``mfu`` = per-step executable FLOPs (XLA ``cost_analysis()``,
        captured at compile under site="trainer") / (average measured
        step wall seconds × device peak FLOP/s). The flops source is the
        compiled train-step executable itself — forward+backward+update,
        exactly what ran — not an analytic 6·N·tokens formula. None until
        both a step has run and the cost registry holds this batch
        signature's entry (FLAGS_trace=1, FLAGS_jit_cache_dir, or
        aot_build() all populate it)."""
        # settle deferred guard verdicts first: the skip counters below
        # must reflect every dispatched step (one cheap device_get — by
        # stats() time the steps in question have long completed)
        self.guard_sync()
        # THIS trainer's entry first: the site-global table keys by batch
        # signature only, which two trainers over different models can
        # share (tools/metrics_dump.py --all does exactly that)
        entry = (self._cost_entries.get(self._last_sig)
                 or _costs.get("trainer", self._last_sig)
                 if self._last_sig else None)
        n = self._step_count
        avg_ms = self._step_ms_sum / n if n else None
        flops = entry.get("flops") if entry else None
        peak = _costs.peak_flops()
        mfu = None
        if flops and avg_ms and peak:
            mfu = float(flops) / ((avg_ms / 1e3) * peak)
        return {
            "steps": n,
            "step_ms_total": self._step_ms_sum,
            "step_ms_avg": avg_ms,
            "batch_sig": self._last_sig,
            "flops_per_step": flops,
            "hbm": ({k: entry[k] for k in ("peak_bytes", "argument_bytes",
                                           "output_bytes", "temp_bytes")
                     if k in entry} if entry else None),
            "peak_flops": peak,
            "mfu": mfu,
            "breakdown": {
                "sync_ms_total": self._sync_ms_sum,
                "dispatch_ms_total": max(
                    0.0, self._step_ms_sum - self._sync_ms_sum),
                "nonfinite_skipped_total": self._nonfinite_total,
                "nonfinite_streak": self._nonfinite_streak,
                # deferred-guard accounting (docs/PERF.md): host syncs
                # spent on verdicts and how far the host ran ahead
                "verdict_fetches": self._verdict_fetches,
                "verdict_window": self._async_window,
                "window_max_depth": self._window_max_depth,
                "prefetch_hits": self._prefetch_hits,
            },
            "device_memory": _costs.sample_device_memory(),
            # quantized-reduce health: the last step's EF-residual norm
            # (None unless FLAGS_quantized_allreduce built this trainer)
            "quantize_error_norm": (self.quantize_error()
                                    if self._quantized else None),
            # the numerics telescope's model-health snapshot (None until
            # FLAGS_numerics arms a step — the plain path never even
            # imports the module)
            "numerics": (self._numerics.snapshot()
                         if self._numerics is not None else None),
        }

    def sync_to_layer(self):
        """Write the (possibly sharded) params back into the Layer's tensors.

        Copies (never aliases) the trainer's arrays — the pipeline
        trainer's documented rule: the jitted step donates params, state
        AND buffers, so handing the live buffers to the Layer would let
        the next train_step invalidate the Layer's eager tensors on a
        donation-honoring backend. device_get lands an independent HOST
        copy (the pre-existing stage>=3 numpy-in-_data contract) — no
        re-upload, no second device-resident model."""
        named = dict(self.layer.named_parameters())
        for n, v in self.params.items():
            named[n]._data = jax.device_get(v)
        named_b = dict(self.layer.named_buffers())
        for n, v in self.buffers.items():
            named_b[n]._data = jax.device_get(v)

    # -- checkpoint / resume ---------------------------------------------------
    def _checkpoint_layout(self):
        """Logical [param, shard-spec] metadata for THIS trainer's state
        layout — the ``shard_specs`` leaf of every checkpoint it writes
        (CHECKPOINT_SCHEMA), and the restore target description when it
        reads one. Pure data (shapes, sizes, key sets) so it pickles
        through framework/io.py unchanged."""
        if self.localsgd_k:
            mode = "localsgd"
        elif self._is_dgc():
            mode = "dgc"
        elif self._quantized or self._shard_update:
            mode = "compressed"
        else:
            mode = "plain"
        return {
            "v": 1,
            "mode": mode,
            "ndp": int(self.mesh.shape[self.dp_axis]),
            "dp_axis": self.dp_axis,
            "shard_update": bool(self._shard_update),
            "quantized": bool(self._quantized),
            "sharding_stage": int(self.sharding_stage),
            "params": {k: {"shape": [int(d) for d in v.shape],
                           "size": int(v.size)}
                       for k, v in self.params.items()},
            "shard_ps": {k: int(ps) for k, ps in self._shard_ps.items()},
            "sharded_keys": {p: sorted(ks)
                             for p, ks in self._shard_state_keys.items()},
            "qar_eligible": sorted(self._qar_eligible),
        }

    def state_dict(self):
        """Host-side checkpoint of the FULL train state — params, buffers,
        optimizer moments, step counters, LR-scheduler state — gathered
        from whatever shardings are live. `paddle.save(trainer.state_dict(),
        path)` + `set_state_dict(paddle.load(path))` resumes bit-exact
        (asserted by tests/test_trainer_checkpoint.py). The snapshot also
        carries this trainer's shard-spec layout so it restores onto a
        DIFFERENT dp/mp factorization (docs/DISTRIBUTED.md "Elastic
        training")."""
        state = gather_train_state(self.params, self.opt_state,
                                   self.optimizer,
                                   layout=self._checkpoint_layout(),
                                   weight_version=self.weight_version)
        state["buffers"] = {k: _host_gather(v)
                            for k, v in self.buffers.items()}
        return state

    def set_state_dict(self, state):
        """Restore a state_dict() checkpoint, re-placing every array with
        the trainer's live shardings. A checkpoint written under a
        different dp/mp factorization (its ``shard_specs`` leaf differs
        from this trainer's layout) is re-laid-out on load —
        topology-aware resharding, counted in
        checkpoint_reshard_total{action}. Key mismatches (stale
        checkpoint vs a changed model) fail fast with names.

        Weight lineage (ISSUE 20): the restored state's
        ``__weight_version__`` leaf (absent — a pre-version checkpoint —
        reads as counter 0) re-joins this trainer's lineage at
        ``max(live, loaded) + 1`` so the counter stays monotone across
        restore AND replay, with origin ``restore`` (``reshard`` when
        the layouts differed and the moments were re-laid-out)."""
        src = state.get("shard_specs")
        layout = self._checkpoint_layout()
        resharded = src is not None and _layouts_differ(src, layout)
        gp_bucket = "reshard" if resharded else "ckpt_restore"
        with (self._goodput.bucket(gp_bucket)
              if self._goodput is not None
              else contextlib.nullcontext()):
            self.params, self.opt_state = restore_train_state(
                state, self.p_shardings, self.s_shardings, self.optimizer,
                layout=layout)
            _validate_state_keys("buffers", state.get("buffers", {}),
                                 self.b_shardings)
            self.buffers = {k: owned_device_put(jnp.asarray(v),
                                                self.b_shardings[k])
                            for k, v in state.get("buffers", {}).items()}
        loaded = _lineage.WeightVersion.from_dict(
            state.get("__weight_version__"),
            run_id=self.weight_version.run_id)
        self.weight_version = _lineage.WeightVersion(
            self.weight_version.run_id,
            max(self.weight_version.counter, loaded.counter) + 1,
            "reshard" if resharded else "restore")
        if resharded and self._goodput is not None:
            self._goodput.count("reshard")

    # -- elastic resize (FLAGS_elastic; docs/DISTRIBUTED.md) -------------------
    def resize(self, mesh):
        """Elastic topology change in place: drain the in-flight window,
        snapshot the live state at its logical shapes, swap the mesh,
        and re-place everything under the new dp factorization. The next
        train_step warm-restarts through the AOT disk cache —
        mesh_fingerprint (already in every key) hashes shape/kind, not
        device ids, so a replacement slice of the same shape disk-hits
        while a genuinely different factorization recompiles cleanly.

        Requires FLAGS_elastic at construction (the flag is structural);
        localsgd/DGC are rejected — their per-rank replicas/residuals
        have no topology-independent logical form. [dp, shard] moments
        re-lay bit-exactly; __qar_residual__ EF residuals fold their
        summed pending correction into rank 0 of the new factorization
        (counted residual_fold — total correction preserved, per-rank
        distribution is not)."""
        if self._goodput is None:
            return self._resize_impl(mesh)
        # goodput `reshard` bucket + event count around the whole
        # drain/snapshot/re-place leg (FLAGS_goodput; ISSUE 20)
        with self._goodput.bucket("reshard"):
            self._goodput.count("reshard")
            return self._resize_impl(mesh)

    def _resize_impl(self, mesh):
        self._elastic_active()
        if not self._elastic:
            raise RuntimeError(
                "SpmdTrainer.resize requires FLAGS_elastic=1 at trainer "
                "construction — the flag is structural (it keys every "
                "executable); build elastic trainers from the start")
        if self.localsgd_k or self._is_dgc():
            raise NotImplementedError(
                "resize() is not supported with localsgd/DGC per-rank "
                "state (no topology-independent logical form)")
        if self.dp_axis not in mesh.axis_names:
            raise ValueError(
                f"replacement mesh has axes {mesh.axis_names}, missing "
                f"this trainer's dp axis {self.dp_axis!r}")
        # drain: settle every deferred verdict (and surface a pending
        # FloatingPointError) before the state is captured
        self._drain_verdicts(force=True, deliver=True)
        state = self.state_dict()
        src = state["shard_specs"]
        old_fp = _aot.mesh_fingerprint(self.mesh)
        self.mesh = mesh
        # executables are keyed WITHOUT mesh identity (_exec_key) — a
        # stale store would silently run the old factorization's program
        self._compiled = None
        self._compiled_store.clear()
        self._prefetched = None
        self._cost_entries = {}
        if self._perf_ledger is not None:
            self._perf_mesh_fp = _aot.mesh_fingerprint(mesh)
        # logicalize the snapshot, then let _place_state re-derive the
        # whole placement vocabulary (shard_ps/sharded keys/zero
        # residuals) for the new mesh — a from-scratch layout of the
        # logical values, so moments land bit-exact
        folds = {}
        opt_l = {}
        for pname, st in state["opt_state"].items():
            if pname == "__step__":
                opt_l[pname] = st
                continue
            if pname == "__qar_residual__":
                for k, v in st.items():
                    folds[k] = np.asarray(v).sum(axis=0)
                continue
            sk = set((src or {}).get("sharded_keys", {}).get(pname, ()))
            sub = {}
            for k, v in st.items():
                arr = np.asarray(v)
                if k in sk:
                    meta = src["params"][pname]
                    arr = arr.reshape(-1)[:int(meta["size"])] \
                             .reshape(tuple(meta["shape"]))
                    _note_reshard("moment_reshard")
                sub[k] = arr
            opt_l[pname] = sub
        self.params = {k: np.asarray(v)
                       for k, v in state["params"].items()}
        self.buffers = {k: np.asarray(v)
                        for k, v in state["buffers"].items()}
        self.opt_state = opt_l
        self._shard_ps = {}
        self._shard_state_keys = {}
        self._place_state()
        if folds and "__qar_residual__" in self.opt_state:
            ndp = int(mesh.shape[self.dp_axis])
            res = {}
            for name, sh in self.s_shardings["__qar_residual__"].items():
                buf = np.zeros((ndp,) + folds[name].shape, np.float32)
                buf[0] = folds[name]
                res[name] = owned_device_put(buf, sh)
                _note_reshard("residual_fold")
            self.opt_state["__qar_residual__"] = res
        # the re-placed params are a new weight state in this lineage
        self.weight_version = self.weight_version.bump("reshard")
        _blackbox.note("trainer_resize", old_mesh=str(old_fp),
                       new_mesh=str(_aot.mesh_fingerprint(mesh)),
                       ndp=int(mesh.shape[self.dp_axis]))
        return self


def data_parallel_step_fn(layer, optimizer, loss_fn, mesh=None, **kw):
    return SpmdTrainer(layer, optimizer, loss_fn, mesh=mesh, **kw)


# -- shared checkpoint helpers (SpmdTrainer + PipelineTrainer) ----------------

def _host_gather(v):
    """device_get that stays correct on multi-process meshes: arrays spanning
    non-addressable devices gather via process_allgather."""
    try:
        return np.asarray(jax.device_get(v))
    except RuntimeError:
        from jax.experimental import multihost_utils

        # tiled=True: a global array sharded across processes assembles
        # into its global shape (non-tiled gather of non-fully-addressable
        # arrays is rejected by jax); fully-replicated arrays pass through
        return np.asarray(multihost_utils.process_allgather(v, tiled=True))


def _validate_state_keys(what, got, expected):
    missing = sorted(set(expected) - set(got))
    unexpected = sorted(set(got) - set(expected))
    if missing or unexpected:
        raise ValueError(
            f"checkpoint {what} mismatch — missing: {missing or 'none'}, "
            f"unexpected: {unexpected or 'none'} (stale checkpoint for a "
            "changed model?)")


def gather_train_state(params, opt_state, optimizer, layout=None,
                       weight_version=None):
    """Host-side {params, opt_state, step, lr_scheduler} snapshot.

    `layout` (SpmdTrainer._checkpoint_layout()) stamps the writer's
    logical [param, shard-spec] metadata into the snapshot's
    ``shard_specs`` leaf (CHECKPOINT_SCHEMA) so restore_train_state can
    re-lay-out onto a different dp/mp factorization; None (the
    PipelineTrainer / pre-elastic path) writes a same-topology-only
    checkpoint, exactly as before. `weight_version`
    (framework/lineage.py) stamps the writer's lineage into the
    ``__weight_version__`` leaf; None omits it (the checkpoint loads as
    version 0 — the pre-version contract)."""
    lr = optimizer._lr
    out = {
        "params": {k: _host_gather(v) for k, v in params.items()},
        "opt_state": {
            pname: (_host_gather(st) if pname == "__step__"
                    else {k: _host_gather(v) for k, v in st.items()})
            for pname, st in opt_state.items()},
        "optimizer_step_count": int(optimizer._step_count),
        "lr_scheduler": (lr.state_dict()
                         if hasattr(lr, "state_dict") else None),
        "shard_specs": layout,
    }
    if weight_version is not None:
        out["__weight_version__"] = weight_version.to_dict()
    return out


def _layouts_differ(src, dst):
    """Do two _checkpoint_layout() dicts describe different opt-state
    topologies? Only the fields that change the PLACED form matter —
    ndp alone is harmless for logical-shaped (plain/ZeRO) state."""
    return any(src.get(k) != dst.get(k)
               for k in ("mode", "ndp", "shard_ps", "sharded_keys",
                         "qar_eligible"))


def _reshard_opt_state(opt_host, src, dst):
    """Transform a host opt_state snapshot written under layout `src`
    into the placed form layout `dst` expects (ISSUE 19 topology-aware
    resharding; docs/DISTRIBUTED.md "Elastic training").

    [dp, shard] moments re-flatten to their logical param shape and
    re-pad to the destination factorization — bit-exact, the padding is
    zeros the sharded update never reads. ``__qar_residual__`` EF
    residuals are genuinely per-rank: each one is folded (summed over
    the writer's ranks) into rank 0 of the destination — the TOTAL
    pending error-feedback correction is preserved exactly, its per-rank
    distribution is not — or deterministically zeroed/dropped when only
    one side runs quantized. Every action lands in
    checkpoint_reshard_total{action}."""
    if src.get("mode") in ("localsgd", "dgc") \
            or dst.get("mode") in ("localsgd", "dgc"):
        raise ValueError(
            "cross-topology restore of localsgd/DGC state is not "
            "supported: per-rank replicas/residuals have no "
            "topology-independent logical form (docs/DISTRIBUTED.md)")
    ndp_t = int(dst["ndp"])
    out = {}
    for pname, st in opt_host.items():
        if pname == "__step__":
            out[pname] = st
            _note_reshard("step_passthrough")
            continue
        if pname == "__qar_residual__":
            continue   # handled below against dst's eligibility set
        src_sk = set(src.get("sharded_keys", {}).get(pname, ()))
        dst_sk = set(dst.get("sharded_keys", {}).get(pname, ()))
        meta = dst.get("params", {}).get(pname) \
            or src.get("params", {}).get(pname)
        sub = {}
        for k, v in st.items():
            arr = np.asarray(v)
            if k in src_sk:
                # placed [ndp_s, ps_s] -> logical (padding is zeros)
                arr = arr.reshape(-1)[:int(meta["size"])] \
                         .reshape(tuple(meta["shape"]))
            if k in dst_sk:
                ps_t = int(dst["shard_ps"][pname])
                flat = np.pad(arr.reshape(-1),
                              (0, ps_t * ndp_t - arr.size))
                sub[k] = flat.reshape(ndp_t, ps_t)
                _note_reshard("moment_reshard" if k in src_sk
                              else "moment_shard")
            else:
                sub[k] = arr
                if k in src_sk:
                    _note_reshard("moment_unshard")
        out[pname] = sub
    dst_eligible = list(dst.get("qar_eligible", ()))
    src_res = opt_host.get("__qar_residual__", {})
    if dst_eligible:
        res = {}
        for name in dst_eligible:
            meta = dst.get("params", {}).get(name) \
                or src.get("params", {}).get(name)
            shape = (ndp_t,) + tuple(meta["shape"])
            buf = np.zeros(shape, np.float32)
            if name in src_res:
                # fold: the summed pending EF correction lands on rank 0
                buf[0] = np.asarray(src_res[name]).sum(axis=0)
                _note_reshard("residual_fold")
            else:
                _note_reshard("residual_zero")
            res[name] = buf
        out["__qar_residual__"] = res
    dropped = set(src_res) - set(dst_eligible)
    if dropped:
        _note_reshard("residual_drop", n=len(dropped))
    return out


def restore_train_state(state, p_shardings, s_shardings, optimizer,
                        layout=None):
    """Re-place a gather_train_state snapshot onto live shardings; restores
    step counters and LR-scheduler state. Returns (params, opt_state).

    With `layout` (the DESTINATION trainer's _checkpoint_layout()) and a
    snapshot that carries its writer's ``shard_specs``, a checkpoint
    written under a different dp/mp factorization is re-laid-out first
    (_reshard_opt_state) — [dp, shard] moments bit-exact, EF residuals
    folded or zeroed, every action counted. Either side missing keeps
    the pre-elastic same-topology contract."""
    opt_host = state["opt_state"]
    src = state.get("shard_specs")
    if src is not None and layout is not None \
            and _layouts_differ(src, layout):
        opt_host = _reshard_opt_state(opt_host, src, layout)
    _validate_state_keys("params", state["params"], p_shardings)
    _validate_state_keys("opt_state", opt_host, s_shardings)
    params = {k: owned_device_put(jnp.asarray(v), p_shardings[k])
              for k, v in state["params"].items()}
    opt_state = {
        pname: (owned_device_put(jnp.asarray(st), s_shardings[pname])
                if pname == "__step__"
                else {k: owned_device_put(jnp.asarray(v),
                                          s_shardings[pname][k])
                      for k, v in st.items()})
        for pname, st in opt_host.items()}
    optimizer._step_count = int(state.get("optimizer_step_count", 0))
    lr = optimizer._lr
    if state.get("lr_scheduler") and hasattr(lr, "set_state_dict"):
        lr.set_state_dict(state["lr_scheduler"])
    return params, opt_state


def spmd_trainer_from_plan(config, layer, optimizer, loss_fn=None):
    """Realize a plan-search emission (analysis/plan_search.emit,
    ``kind="spmd"``) as a live :class:`SpmdTrainer`.

    The config is plain data — this function imports nothing from the
    analysis layer, so the plain-trainer closure stays planner-free.
    ``config["flags"]`` must already be SET: trainer construction
    consumes them (the _resolve_compress contract), so a mismatch here
    would silently build a different trainer than the plan scored —
    instead it raises naming the flag."""
    from .. import flags as _flags
    from .mesh import build_mesh
    from .split import collect_spmd_specs

    if config.get("kind") != "spmd":
        raise ValueError(
            f"config kind {config.get('kind')!r} is not 'spmd' — "
            "stage_graph configs realize via "
            "distributed/stage.py pipeline_trainer_from_plan")
    for name, want in (config.get("flags") or {}).items():
        got = bool(_flags.get_flag(name, False))
        if got != bool(want):
            raise ValueError(
                f"plan config wants FLAGS_{name}={want} but the process "
                f"has {got} — set the flag BEFORE realizing (trainer "
                "construction consumes it)")
    mesh_cfg = config["mesh"]
    import jax

    shape = tuple(int(s) for s in mesh_cfg["shape"])
    n = 1
    for s in shape:
        n *= s
    mesh = build_mesh(shape, tuple(mesh_cfg["axes"]),
                      devices=jax.devices()[:n])
    extra = collect_spmd_specs(layer) \
        if config.get("spmd", {}).get("tensor_parallel") else None
    return SpmdTrainer(layer, optimizer, loss_fn=loss_fn, mesh=mesh,
                       extra_param_specs=extra or None)
