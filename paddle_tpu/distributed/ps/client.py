"""PS client + async Communicator.

Reference parity: paddle/fluid/distributed/service/ps_client.h (PSClient API:
pull/push dense & sparse, barrier) and service/communicator.h (async mode:
background send queues that merge up to max_merge_var_num gradient batches
before each RPC; geo mode: periodic delta exchange every k_steps).

Sharding: dense tables live whole on one server (round-robin by table id);
sparse rows shard by id % server_num — the reference's hash placement.
"""
import queue
import threading
import time

import numpy as np

from .rpc import RpcClient


class PsClient:
    def __init__(self, endpoints, trainer_id=0):
        self.endpoints = list(endpoints)
        self.trainer_id = int(trainer_id)
        self._conns = [RpcClient(ep) for ep in self.endpoints]
        self._n = len(self._conns)
        self._sparse_dims = {}  # table_id -> dim (for empty-batch pulls)
        self._hb_stop = threading.Event()
        self._hb_thread = None

    # -- placement -------------------------------------------------------------
    def _dense_conn(self, table_id):
        return self._conns[table_id % self._n]

    # -- table creation (broadcast so every shard knows the schema) ------------
    def create_dense_table(self, table_id, shape, optimizer="sgd", lr=0.01, init=None):
        self._dense_conn(table_id).call(
            "create_table", "dense", table_id,
            dict(shape=shape, optimizer=optimizer, lr=lr, init=init))

    def create_sparse_table(self, table_id, dim, optimizer="sgd", lr=0.01, geo=False, **kw):
        kind = "geo" if geo else "sparse"
        payload = dict(dim=dim, **kw) if geo else dict(dim=dim, optimizer=optimizer, lr=lr, **kw)
        self._sparse_dims[int(table_id)] = int(dim)
        for c in self._conns:
            c.call("create_table", kind, table_id, payload)

    # -- dense -----------------------------------------------------------------
    def pull_dense(self, table_id):
        return self._dense_conn(table_id).call("pull_dense", table_id)

    def push_dense(self, table_id, grad):
        return self._dense_conn(table_id).call("push_dense", table_id, np.asarray(grad, np.float32))

    def set_dense(self, table_id, value):
        return self._dense_conn(table_id).call("set_dense", table_id, np.asarray(value, np.float32))

    # -- sparse (rows sharded by id % n) ---------------------------------------
    def _shard(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        shard = (ids % self._n).astype(np.int64)
        return ids, shard

    def pull_sparse(self, table_id, ids):
        ids, shard = self._shard(ids)
        rows = None
        for s in range(self._n):
            mask = shard == s
            if not mask.any():
                continue
            part = self._conns[s].call("pull_sparse", table_id, ids[mask])
            if rows is None:
                rows = np.empty((len(ids), part.shape[1]), np.float32)
            rows[mask] = part
        if rows is None:
            rows = np.empty((0, self._sparse_dims.get(int(table_id), 0)), np.float32)
        return rows

    def push_sparse(self, table_id, ids, grads):
        ids, shard = self._shard(ids)
        grads = np.asarray(grads, np.float32)
        for s in range(self._n):
            mask = shard == s
            if mask.any():
                self._conns[s].call("push_sparse", table_id, ids[mask], grads[mask])

    def push_sparse_delta(self, table_id, ids, deltas):
        ids, shard = self._shard(ids)
        deltas = np.asarray(deltas, np.float32)
        for s in range(self._n):
            mask = shard == s
            if mask.any():
                self._conns[s].call(
                    "push_sparse_delta", table_id, self.trainer_id, ids[mask], deltas[mask])

    def pull_geo(self, table_id):
        all_ids, all_deltas = [], []
        for c in self._conns:
            ids, deltas = c.call("pull_geo", table_id, self.trainer_id)
            if len(ids):
                all_ids.append(ids)
                all_deltas.append(deltas)
        if not all_ids:
            return np.empty(0, np.int64), None
        return np.concatenate(all_ids), np.concatenate(all_deltas)

    # -- global-shuffle exchange (data_set.cc GlobalShuffle routing) -----------
    def shuffle_put(self, dst_worker, blob):
        """Push a text blob of instances destined for `dst_worker`; spread
        across servers by destination so exchange bandwidth scales."""
        self._conns[dst_worker % len(self._conns)].call(
            "shuffle_put", dst_worker, blob)

    def shuffle_get(self, worker_id):
        return self._conns[worker_id % len(self._conns)].call(
            "shuffle_get", worker_id)

    # -- control ---------------------------------------------------------------
    def barrier(self):
        """Global worker barrier rendezvoused at server 0 (BarrierTable)."""
        return self._conns[0].call("barrier")

    def start_heartbeat(self, interval=2.0):
        def loop():
            while not self._hb_stop.is_set():
                for c in self._conns:
                    try:
                        c.call("heartbeat", self.trainer_id)
                    except (RuntimeError, ConnectionError, OSError):
                        pass
                self._hb_stop.wait(interval)

        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_server(self):
        for c in self._conns:
            try:
                c.call("stop")
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        self._hb_stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=5)
        for c in self._conns:
            c.close()


class Communicator:
    """Async/geo gradient pipe (service/communicator.h).

    async: push goes into a bounded queue; a background thread merges up to
    `max_merge_var_num` pending grads per table and issues one RPC — training
    never blocks on the PS round-trip.
    geo: `step()` counts local steps; every `k_steps` the worker pushes its
    accumulated sparse deltas and pulls other trainers' deltas.
    """

    def __init__(self, client, mode="async", send_queue_size=16, max_merge_var_num=4,
                 k_steps=4):
        self.client = client
        self.mode = mode
        self.k_steps = int(k_steps)
        self._max_merge = int(max_merge_var_num)
        self._q = queue.Queue(maxsize=int(send_queue_size))
        self._stop = threading.Event()
        self._thread = None
        self._step = 0
        if mode == "async":
            self._thread = threading.Thread(target=self._send_loop, daemon=True)
            self._thread.start()

    # -- async path ------------------------------------------------------------
    def push_dense_async(self, table_id, grad):
        self._q.put(("dense", table_id, np.asarray(grad, np.float32)))

    def push_sparse_async(self, table_id, ids, grads):
        self._q.put(("sparse", table_id, (np.asarray(ids, np.int64), np.asarray(grads, np.float32))))

    def _send_loop(self):
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [item]
            while len(batch) < self._max_merge:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            merged = {}
            for kind, tid, payload in batch:
                merged.setdefault((kind, tid), []).append(payload)
            for (kind, tid), items in merged.items():
                try:
                    if kind == "dense":
                        self.client.push_dense(tid, np.sum(items, axis=0))
                    else:
                        ids = np.concatenate([i for i, _ in items])
                        grads = np.concatenate([g for _, g in items])
                        self.client.push_sparse(tid, ids, grads)
                except (RuntimeError, ConnectionError, OSError):
                    pass  # dropped sends are acceptable in async mode

    def flush(self, timeout=10.0):
        deadline = time.time() + timeout
        while not self._q.empty() and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # let the in-flight batch finish

    # -- geo path --------------------------------------------------------------
    def geo_step(self, table_id, local_table):
        """Called per step in geo mode with the worker's local SparseTable-like
        dict {id: (new_row, old_row)} of rows touched since last sync."""
        self._step += 1
        if self._step % self.k_steps:
            return None
        if local_table:
            ids = np.fromiter(local_table.keys(), np.int64, len(local_table))
            deltas = np.stack([local_table[int(i)][0] - local_table[int(i)][1] for i in ids])
            self.client.push_sparse_delta(table_id, ids, deltas)
            local_table.clear()
        return self.client.pull_geo(table_id)

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
