"""ctypes binding for the native C++ sparse PS table (native/sparse_table.cc).

Reference parity: paddle/fluid/distributed/table/common_sparse_table.cc via the
same build-on-first-use pattern as io/multislot.py (no pybind11 in the image).
Drop-in for tables.SparseTable: pull/push/size plus save/load snapshots.
"""
import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native",
                    "sparse_table.cc")
_SO = os.path.join(os.path.dirname(__file__), "..", "..", "native",
                   "_sparse_table.so")

_OPT_IDS = {"sum": 0, "sgd": 1, "adagrad": 2, "adam": 3}


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is False:  # negative cache: build already failed this session
            raise RuntimeError("native sparse table build failed previously")
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.abspath(_SO)
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
                     "-o", so, src],
                    check=True, capture_output=True,
                )
        except (OSError, subprocess.CalledProcessError):
            _LIB = False
            raise
        lib = ctypes.CDLL(so)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.pst_create.restype = ctypes.c_void_p
        lib.pst_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_float,
                                   ctypes.c_float, ctypes.c_uint64]
        lib.pst_destroy.argtypes = [ctypes.c_void_p]
        lib.pst_size.restype = ctypes.c_int64
        lib.pst_size.argtypes = [ctypes.c_void_p]
        lib.pst_pull.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64, f32p]
        lib.pst_get_rows.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64, f32p]
        lib.pst_push.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64, f32p]
        lib.pst_keys.argtypes = [ctypes.c_void_p, i64p]
        lib.pst_save.restype = ctypes.c_int
        lib.pst_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pst_load.restype = ctypes.c_int
        lib.pst_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _LIB = lib
        return lib


def available():
    try:
        _load_lib()
        return True
    except Exception:
        return False


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeSparseTable:
    """SparseTable-compatible facade over the C++ engine."""

    def __init__(self, dim, optimizer="sgd", lr=0.01, initializer="uniform",
                 init_scale=0.01, seed=0):
        if optimizer not in _OPT_IDS:
            raise ValueError(f"unknown PS optimizer rule: {optimizer}")
        self.dim = int(dim)
        self._lib = _load_lib()
        scale = 0.0 if initializer == "zeros" else float(init_scale)
        self._h = self._lib.pst_create(self.dim, _OPT_IDS[optimizer],
                                       float(lr), scale, int(seed))
        self._destroy = self._lib.pst_destroy  # survive interpreter teardown

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._destroy(h)
            self._h = None

    _q = None   # int8 serving store: (sorted ids, int8 codes, f32 scales)

    def quantize(self):
        """Freeze into int8 serving form (lookup_table_dequant parity —
        same contract as SparseTable.quantize): rows exported from the C++
        engine into an int8-codes + per-row-absmax store; pulls dequantize,
        pushes are refused."""
        ids = np.sort(self.keys())
        rows = self.get_rows(ids)
        scales = np.max(np.abs(rows), axis=1)
        scales[scales == 0.0] = 1.0
        codes = np.clip(np.rint(rows / scales[:, None] * 127.0),
                        -127, 127).astype(np.int8)
        self._q = (ids, codes, scales.astype(np.float32))

    @property
    def quantized(self):
        return self._q is not None

    def pull(self, ids):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        if self._q is not None:
            q_ids, codes, scales = self._q
            idx = np.searchsorted(q_ids, ids)
            idx_c = np.clip(idx, 0, max(len(q_ids) - 1, 0))
            hit = (len(q_ids) > 0) & (q_ids[idx_c] == ids)
            out = np.zeros((len(ids), self.dim), np.float32)
            if np.any(hit):
                sel = idx_c[hit]
                out[hit] = codes[sel].astype(np.float32) \
                    * (scales[sel, None] / 127.0)
            return out
        out = np.empty((len(ids), self.dim), np.float32)
        self._lib.pst_pull(self._h, _i64p(ids), len(ids), _f32p(out))
        return out

    def get_rows(self, ids):
        """Lookup without init-on-miss (missing rows read as zeros)."""
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        out = np.empty((len(ids), self.dim), np.float32)
        self._lib.pst_get_rows(self._h, _i64p(ids), len(ids), _f32p(out))
        return out

    def push(self, ids, grads):
        if self._q is not None:
            raise RuntimeError(
                "NativeSparseTable is quantized (int8 serving mode) — "
                "pushes are not accepted")
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(len(ids), self.dim))
        self._lib.pst_push(self._h, _i64p(ids), len(ids), _f32p(grads))

    def size(self):
        return int(self._lib.pst_size(self._h))

    def keys(self):
        n = self.size()
        out = np.empty(n, np.int64)
        if n:
            self._lib.pst_keys(self._h, _i64p(out))
        return out

    def save(self, path):
        rc = self._lib.pst_save(self._h, str(path).encode())
        if rc != 0:
            raise IOError(f"pst_save({path}) failed: {rc}")

    def load(self, path):
        rc = self._lib.pst_load(self._h, str(path).encode())
        if rc != 0:
            raise IOError(f"pst_load({path}) failed: {rc}")
