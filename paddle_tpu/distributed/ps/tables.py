"""PS tables with server-side optimizer rules.

Reference parity: paddle/fluid/distributed/table/ — CommonDenseTable,
CommonSparseTable (common_sparse_table.cc), SparseGeoTable (sparse_geo_table.cc),
BarrierTable (barrier_table.cc), TensorTable (tensor_table.h); embedded optimizer
rules mirror table/depends/dense.h and table/depends/sparse.h (sum/sgd/adagrad/
adam applied where the parameters live, so workers ship gradients, not weights).

All storage is host numpy — the PS tier is deliberately off the XLA path; only
pulled rows enter device memory, as jnp arrays on the worker side.
"""
import threading

import numpy as np


class _Rule:
    """Server-side optimizer rules (table/depends/{dense,sparse}.h parity)."""

    def __init__(self, name, lr):
        self.name = name
        self.lr = float(lr)

    def slots(self, dim):
        if self.name == "adagrad":
            return {"g2sum": np.zeros(dim, np.float32)}
        if self.name == "adam":
            return {
                "m": np.zeros(dim, np.float32),
                "v": np.zeros(dim, np.float32),
                "beta1_pow": np.ones((), np.float32),
                "beta2_pow": np.ones((), np.float32),
            }
        return {}

    def apply(self, value, grad, slots):
        if self.name == "sum":
            value -= grad  # raw accumulation; caller controls scaling
        elif self.name == "sgd":
            value -= self.lr * grad
        elif self.name == "adagrad":
            slots["g2sum"] += grad * grad
            value -= self.lr * grad / (np.sqrt(slots["g2sum"]) + 1e-6)
        elif self.name == "adam":
            b1, b2, eps = 0.9, 0.999, 1e-8
            slots["beta1_pow"] *= b1
            slots["beta2_pow"] *= b2
            slots["m"] = b1 * slots["m"] + (1 - b1) * grad
            slots["v"] = b2 * slots["v"] + (1 - b2) * grad * grad
            mhat = slots["m"] / (1 - slots["beta1_pow"])
            vhat = slots["v"] / (1 - slots["beta2_pow"])
            value -= self.lr * mhat / (np.sqrt(vhat) + eps)
        else:
            raise ValueError(f"unknown PS optimizer rule: {self.name}")
        return value


class DenseTable:
    """Whole-block dense parameters (table/common_dense_table.cc)."""

    def __init__(self, shape, optimizer="sgd", lr=0.01, init=None):
        self._value = (
            np.asarray(init, np.float32).copy()
            if init is not None
            else np.zeros(shape, np.float32)
        )
        self._rule = _Rule(optimizer, lr)
        self._slots = self._rule.slots(self._value.shape)
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self._value.copy()

    def push(self, grad):
        with self._lock:
            self._value = self._rule.apply(self._value, np.asarray(grad, np.float32), self._slots)

    def set(self, value):
        with self._lock:
            self._value = np.asarray(value, np.float32).copy()


class CountFilterEntry:
    """paddle.distributed.CountFilterEntry parity (the_one_ps accessor entry
    config): a sparse key is only admitted (row created) after it has been
    seen `count_filter` times in pushes/pulls."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.threshold = int(count_filter)

    def admit(self, seen_count, rng):
        return seen_count >= self.threshold


class ProbabilityEntry:
    """paddle.distributed.ProbabilityEntry parity: a new sparse key is
    admitted with the given probability."""

    def __init__(self, probability):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def admit(self, seen_count, rng):
        return rng.rand() < self.probability


class SparseTable:
    """Auto-growing row store keyed by int64 id (table/common_sparse_table.cc).
    Rows initialize lazily on first pull — the reference's fill-on-miss accessor."""

    def __init__(self, dim, optimizer="sgd", lr=0.01, initializer="uniform",
                 init_scale=0.01, seed=0, entry=None):
        self.dim = int(dim)
        self._rule = _Rule(optimizer, lr)
        self._rows = {}
        self._slots = {}
        self._lock = threading.Lock()
        self._initializer = initializer
        self._scale = float(init_scale)
        self._rng = np.random.RandomState(seed)
        # admission policy (CountFilterEntry / ProbabilityEntry); None admits all
        self._entry = entry
        self._seen = {}
        # int8 serving mode (lookup_table_dequant parity): rows stored as
        # (int8 codes, f32 absmax scale), dequantized on pull
        self._qrows = None

    def _init_row(self, rid):
        if self._initializer == "zeros":
            row = np.zeros(self.dim, np.float32)
        else:
            row = self._rng.uniform(-self._scale, self._scale, self.dim).astype(np.float32)
        self._rows[rid] = row
        self._slots[rid] = self._rule.slots(self.dim)
        return row

    def _admitted(self, rid):
        if self._entry is None or rid in self._rows:
            return True
        self._seen[rid] = self._seen.get(rid, 0) + 1
        return self._entry.admit(self._seen[rid], self._rng)

    def quantize(self):
        """Freeze the table into int8 serving form (lookup_table_dequant
        parity, operators/lookup_table_dequant_op: the deployed table keeps
        int8 rows ~4x smaller; lookups dequantize on the fly). Per-row
        absmax scale; the f32 rows are dropped and the table becomes
        serve-only — push() raises, matching the inference-side op."""
        with self._lock:
            self._qrows = {}
            for rid, row in self._rows.items():
                scale = float(np.max(np.abs(row))) or 1.0
                codes = np.clip(np.rint(row / scale * 127.0),
                                -127, 127).astype(np.int8)
                self._qrows[rid] = (codes, np.float32(scale))
            self._rows = {}
            self._slots = {}

    @property
    def quantized(self):
        return self._qrows is not None

    def _dequant(self, rid):
        codes, scale = self._qrows[rid]
        return codes.astype(np.float32) * (scale / 127.0)

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        zero = np.zeros(self.dim, np.float32)
        with self._lock:
            out = []
            for i in ids:
                rid = int(i)
                if self._qrows is not None:
                    # int8 serving mode: dequantize; unknown keys read zero
                    out.append(self._dequant(rid) if rid in self._qrows
                               else zero)
                elif rid in self._rows:
                    out.append(self._rows[rid])
                elif self._admitted(rid):
                    out.append(self._init_row(rid))
                else:
                    out.append(zero)  # filtered keys read as zeros until admitted
            return np.stack(out)

    def _refuse_if_quantized(self):
        # call with self._lock HELD: the check must not race quantize()
        if self._qrows is not None:
            raise RuntimeError(
                "SparseTable is quantized (int8 serving mode) — pushes are "
                "not accepted; re-deploy an f32 table to keep training")

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            self._refuse_if_quantized()
            # duplicate ids in one batch accumulate (reference merges by id)
            order = np.argsort(ids, kind="stable")
            uniq, starts = np.unique(ids[order], return_index=True)
            summed = np.add.reduceat(grads[order], starts, axis=0)
            for rid, g in zip(uniq, summed):
                rid = int(rid)
                if rid not in self._rows:
                    if not self._admitted(rid):  # entry policy gates pushes too
                        continue
                    self._init_row(rid)
                self._rows[rid] = self._rule.apply(self._rows[rid], g, self._slots[rid])

    def size(self):
        with self._lock:
            return len(self._qrows if self._qrows is not None
                       else self._rows)


class GeoSparseTable(SparseTable):
    """Geo-async sparse table (table/sparse_geo_table.cc): workers train local
    replicas; the server additionally accumulates per-trainer row deltas so each
    trainer can periodically pull only what *others* changed."""

    def __init__(self, dim, trainers, **kw):
        super().__init__(dim, **kw)
        self._trainers = int(trainers)
        self._pending = [dict() for _ in range(self._trainers)]  # per-trainer {id: delta}

    def push_delta(self, trainer_id, ids, deltas):
        ids = np.asarray(ids, np.int64).ravel()
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            self._refuse_if_quantized()   # serve-only table: no geo writes
            for rid, d in zip(ids, deltas):
                rid = int(rid)
                if rid not in self._rows:
                    if not self._admitted(rid):
                        continue
                    self._init_row(rid)
                self._rows[rid] = self._rows[rid] + d
                for t in range(self._trainers):
                    if t == trainer_id:
                        continue
                    q = self._pending[t]
                    q[rid] = q.get(rid, 0) + d

    def pull_geo(self, trainer_id):
        with self._lock:
            q = self._pending[trainer_id]
            self._pending[trainer_id] = {}
        if not q:
            return np.empty(0, np.int64), np.empty((0, self.dim), np.float32)
        ids = np.fromiter(q.keys(), np.int64, len(q))
        deltas = np.stack([np.asarray(q[int(i)], np.float32) for i in ids])
        return ids, deltas


class BarrierTable:
    """Blocks until `trigger` participants arrive (table/barrier_table.cc)."""

    def __init__(self, trigger):
        self._trigger = int(trigger)
        self._count = 0
        self._generation = 0
        self._cond = threading.Condition()

    def barrier(self, timeout=60.0):
        with self._cond:
            gen = self._generation
            self._count += 1
            if self._count >= self._trigger:
                self._count = 0
                self._generation += 1
                self._cond.notify_all()
                return True
            return self._cond.wait_for(lambda: self._generation != gen, timeout=timeout)


class TensorTable:
    """Named arbitrary tensors (table/tensor_table.h) — e.g. global step, lr."""

    def __init__(self):
        self._store = {}
        self._lock = threading.Lock()

    def set(self, name, value):
        with self._lock:
            self._store[name] = np.asarray(value)

    def get(self, name):
        with self._lock:
            return self._store.get(name)


def make_sparse_table(dim, optimizer="sgd", lr=0.01, backend="auto", **kw):
    """Factory: native C++ engine (native/sparse_table.cc) when it builds,
    Python fallback otherwise. backend: 'auto' | 'native' | 'python'."""
    if optimizer not in ("sum", "sgd", "adagrad", "adam"):
        raise ValueError(f"unknown PS optimizer rule: {optimizer}")
    if backend in ("auto", "native"):
        from . import native_table

        # available() negative-caches a failed g++ build, so auto mode never
        # re-spawns the compiler per table inside an RPC handler
        if native_table.available():
            return native_table.NativeSparseTable(dim, optimizer, lr, **kw)
        if backend == "native":
            raise RuntimeError("native sparse table backend failed to build")
    return SparseTable(dim, optimizer, lr, **kw)
