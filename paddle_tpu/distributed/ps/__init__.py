"""TPU-native parameter-server mode.

Reference parity: paddle/fluid/distributed/ ("pscore" — service/brpc_ps_server.cc,
service/ps_client.h, service/communicator.h, table/*.h) and the legacy
operators/distributed/ RPC ops. TPU-native design: the PS tier is a host-side
(CPU, numpy) key-value tier that feeds the XLA compute path — embedding rows are
pulled into device arrays at batch start and row gradients are pushed after
backward (the DownpourWorker flow, framework/device_worker.h:271), while the
dense math stays inside jit. RPC is a length-prefixed-pickle TCP protocol
instead of brpc/protobuf; sharding is row-hash across servers.
"""
from .tables import (  # noqa: F401
    BarrierTable,
    DenseTable,
    GeoSparseTable,
    SparseTable,
    TensorTable,
)
from .rpc import RpcClient, RpcServer  # noqa: F401
from .server import HeartBeatMonitor, PsServer  # noqa: F401
from .client import Communicator, PsClient  # noqa: F401
from .runtime import TheOnePs, PsEmbedding  # noqa: F401
