"""Minimal threaded RPC: length-prefixed pickle over TCP.

Reference parity: the brpc/gRPC channel layer (paddle/fluid/distributed/service/
brpc_ps_client.h, operators/distributed/grpc/). One persistent connection per
client; the server runs one thread per connection — PS traffic is few-and-large
(whole dense blocks / batched sparse rows), so per-message threading overhead is
irrelevant next to serialization, and pickle handles numpy arrays zero-fuss.
"""
import pickle
import socket
import struct
import threading

_HDR = struct.Struct("!Q")


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return pickle.loads(_recv_exact(sock, n))


class RpcServer:
    """Serves `handler(method: str, args: tuple) -> result` over TCP."""

    def __init__(self, host, port, handler):
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)

    def start(self):
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                method, args = _recv_msg(conn)
                try:
                    result = self._handler(method, args)
                    _send_msg(conn, ("ok", result))
                except Exception as e:  # surfaced client-side
                    _send_msg(conn, ("err", f"{type(e).__name__}: {e}"))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RpcClient:
    """Blocking call() against one server; thread-safe via a per-connection lock."""

    def __init__(self, endpoint, timeout=120.0, connect_timeout=60.0):
        import time

        host, port = endpoint.rsplit(":", 1)
        deadline = time.time() + connect_timeout
        while True:  # workers may start before servers finish booting
            try:
                self._sock = socket.create_connection((host, int(port)), timeout=timeout)
                break
            except OSError:
                if time.time() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, method, *args):
        with self._lock:
            if self._sock is None:
                raise ConnectionError("PS RPC connection is broken")
            try:
                _send_msg(self._sock, (method, args))
                status, result = _recv_msg(self._sock)
            except OSError:
                # a timeout/half-send leaves the stream desynced (a late reply
                # would be read as the answer to the next call) — poison it
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise
        if status == "err":
            raise RuntimeError(f"PS RPC {method} failed: {result}")
        return result

    def close(self):
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None
