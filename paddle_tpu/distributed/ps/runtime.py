"""The-one-PS runtime: fleet-facing glue + the PS-backed embedding layer.

Reference parity: python/paddle/distributed/fleet/runtime/the_one_ps.py (table
construction from the program, server/worker lifecycles) and the DownpourWorker
pull→compute→push step (framework/device_worker.h:271). TPU-native design: the
worker's dense math runs the normal jit path; PS interaction happens at the
batch boundary. PsEmbedding materializes the batch's rows as an autograd *leaf*
tensor so a normal loss.backward() leaves the row gradients on the leaf — no
custom tracing needed — and push_step() ships them (sync, async-queue, or
geo-delta per DistributedStrategy).
"""
import os

import numpy as np

from .client import Communicator, PsClient
from .server import PsServer
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer


class TheOnePs:
    """One instance per process; role decides server vs worker behavior."""

    def __init__(self, role_maker=None, strategy=None, endpoints=None, trainer_id=0,
                 worker_num=1):
        self._rm = role_maker
        self._strategy = strategy
        if role_maker is not None:
            self.endpoints = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",")
            self.endpoints = [e for e in self.endpoints if e]
            self.trainer_id = role_maker.worker_index()
            self.worker_num = role_maker.worker_num()
        else:
            self.endpoints = list(endpoints or [])
            self.trainer_id = int(trainer_id)
            self.worker_num = int(worker_num)
        self.client = None
        self.communicator = None
        self._server = None

    # -- server side -----------------------------------------------------------
    def make_server(self, port=None, host=None):
        """Create (not yet blocking) this process's PsServer from its endpoint."""
        if port is None:
            my_ep = os.environ.get("PADDLE_PORT")
            ip = os.environ.get("POD_IP", "127.0.0.1")
            if my_ep is None:
                # derive from endpoint list position
                idx = int(os.environ.get("PADDLE_PSERVER_ID", 0))
                ip, my_ep = self.endpoints[idx].rsplit(":", 1)
            host, port = ip, int(my_ep)
        self._server = PsServer(host or "127.0.0.1", int(port), worker_num=self.worker_num)
        return self._server

    def run_server(self):
        if self._server is None:
            self.make_server()
        self._server.run()

    # -- worker side -----------------------------------------------------------
    def init_worker(self):
        self.client = PsClient(self.endpoints, trainer_id=self.trainer_id)
        mode = "sync"
        kw = {}
        if self._strategy is not None and getattr(self._strategy, "a_sync", False):
            cfg = getattr(self._strategy, "a_sync_configs", None)
            k = getattr(cfg, "k_steps", -1) if cfg else -1
            mode = "geo" if k > 0 else "async"
            if cfg:
                kw = dict(send_queue_size=cfg.send_queue_size,
                          max_merge_var_num=cfg.max_merge_var_num,
                          k_steps=max(k, 1))
        self.mode = mode
        if mode != "sync":
            self.communicator = Communicator(self.client, mode=mode, **kw)
        self.client.start_heartbeat()
        launch_barrier = True
        if self._strategy is not None and getattr(self._strategy, "a_sync_configs", None):
            launch_barrier = self._strategy.a_sync_configs.launch_barrier
        if launch_barrier and self.worker_num > 1:
            self.client.barrier()
        return self.client

    def stop_worker(self):
        if self.communicator is not None:
            self.communicator.flush()
            self.communicator.stop()
        if self.client is not None:
            all_arrived = True
            if self.worker_num > 1:
                try:
                    all_arrived = bool(self.client.barrier())
                except (RuntimeError, ConnectionError, OSError):
                    all_arrived = False
            # only tear the PS tier down once every trainer is known finished —
            # a failed barrier means someone may still be training against it
            if self.trainer_id == 0 and all_arrived:
                self.client.stop_server()
            self.client.close()
            self.client = None


class PsEmbedding(Layer):
    """Distributed lookup table (the reference's sparse-embedding path:
    distributed/table/common_sparse_table.cc + DownpourWorker pull/push).

    forward(ids) pulls the batch's unique rows from the PS into a leaf Tensor
    (stop_gradient=False) and gathers locally; after loss.backward(), the leaf
    holds d(loss)/d(rows), and push_step() ships them to the table's server-side
    optimizer. In geo mode the layer keeps a local row cache trained locally and
    exchanges deltas every k steps via the Communicator."""

    def __init__(self, table_id, embedding_dim, client=None, communicator=None,
                 optimizer="sgd", lr=0.01, name=None):
        super().__init__()
        self.table_id = int(table_id)
        self.dim = int(embedding_dim)
        self.client = client
        self.communicator = communicator
        self._pending = []  # [(ids, leaf_tensor)] awaiting push
        if client is not None:
            client.create_sparse_table(self.table_id, self.dim, optimizer=optimizer, lr=lr)

    def forward(self, ids):
        import jax.numpy as jnp

        from ...core.dispatch import apply

        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids).astype(np.int64)
        flat = ids_np.ravel()
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows_np = self.client.pull_sparse(self.table_id, uniq)
        rows = Tensor(rows_np, stop_gradient=False)
        inv = jnp.asarray(inverse.reshape(ids_np.shape))
        out = apply(lambda r: jnp.take(r, inv, axis=0), rows)
        from ...core.tape import is_grad_enabled

        if is_grad_enabled():  # eval loops never push; don't accumulate leaves
            self._pending.append((uniq, rows))
        return out

    def push_step(self):
        """Push accumulated row grads for every forward since the last push."""
        for uniq, rows in self._pending:
            if rows.grad is None:
                continue
            g = np.asarray(rows.grad._data, np.float32)
            if self.communicator is not None and self.communicator.mode == "async":
                self.communicator.push_sparse_async(self.table_id, uniq, g)
            else:
                self.client.push_sparse(self.table_id, uniq, g)
        self._pending.clear()
