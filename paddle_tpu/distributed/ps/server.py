"""PS server process: table host + heartbeat monitor.

Reference parity: paddle/fluid/distributed/service/brpc_ps_server.cc (service
dispatch over tables), operators/distributed/heart_beat_monitor.h (worker
liveness tracking at the server).
"""
import threading
import time

from .rpc import RpcServer
from .tables import BarrierTable, DenseTable, GeoSparseTable, SparseTable, TensorTable


class HeartBeatMonitor:
    """Tracks last-beat time per worker; flags workers silent > `threshold` s
    (heart_beat_monitor.h UPDATE/CHECK loop)."""

    def __init__(self, worker_num, threshold=60.0):
        self._beats = {}
        self._threshold = float(threshold)
        self._worker_num = int(worker_num)
        self._lock = threading.Lock()

    def update(self, worker_id):
        with self._lock:
            self._beats[int(worker_id)] = time.time()

    def dead_workers(self):
        now = time.time()
        with self._lock:
            return sorted(
                w for w, t in self._beats.items() if now - t > self._threshold
            )

    def alive_count(self):
        now = time.time()
        with self._lock:
            return sum(1 for t in self._beats.values() if now - t <= self._threshold)


class PsServer:
    """Hosts tables behind the RPC endpoint. Table ids are dense ints assigned
    by the runtime; method surface mirrors PSClient (service/ps_client.h):
    pull/push dense, pull/push sparse, geo pull/push, barrier, stop."""

    def __init__(self, host="127.0.0.1", port=0, worker_num=1):
        self._tables = {}
        self._worker_num = int(worker_num)
        self._barrier = BarrierTable(self._worker_num)
        self._monitor = HeartBeatMonitor(self._worker_num)
        self._stop_requested = threading.Event()
        # global-shuffle exchange buffers (data_set.cc Dataset::GlobalShuffle:
        # instances route between workers THROUGH the servers): dst worker ->
        # list of text blobs pushed by source workers
        self._shuffle_buf = {}
        self._shuffle_lock = threading.Lock()
        self._rpc = RpcServer(host, port, self._handle)
        self.endpoint = f"{host}:{self._rpc.port}"

    # -- table management (idempotent: every worker announces the schema) ------
    def create_dense_table(self, table_id, shape, optimizer="sgd", lr=0.01, init=None):
        self._tables.setdefault(int(table_id), DenseTable(shape, optimizer, lr, init))

    def create_sparse_table(self, table_id, dim, optimizer="sgd", lr=0.01, **kw):
        from .tables import make_sparse_table

        self._tables.setdefault(int(table_id),
                                make_sparse_table(dim, optimizer, lr, **kw))

    def create_geo_table(self, table_id, dim, **kw):
        self._tables.setdefault(int(table_id), GeoSparseTable(dim, self._worker_num, **kw))

    def create_tensor_table(self, table_id):
        self._tables.setdefault(int(table_id), TensorTable())

    # -- RPC dispatch ----------------------------------------------------------
    def _handle(self, method, args):
        if method == "heartbeat":
            self._monitor.update(args[0])
            return self._monitor.alive_count()
        if method == "barrier":
            return self._barrier.barrier()
        if method == "stop":
            self._stop_requested.set()
            return True
        if method == "list_tables":
            return sorted(self._tables)
        if method == "shuffle_put":
            dst, blob = args
            with self._shuffle_lock:
                self._shuffle_buf.setdefault(int(dst), []).append(blob)
            return True
        if method == "shuffle_get":
            with self._shuffle_lock:
                return self._shuffle_buf.pop(int(args[0]), [])
        if method == "create_table":
            kind, table_id, kw = args
            getattr(self, f"create_{kind}_table")(table_id, **kw)
            return True
        table = self._tables[int(args[0])]
        rest = args[1:]
        if method == "pull_dense":
            return table.pull()
        if method == "push_dense":
            table.push(*rest)
            return True
        if method == "set_dense":
            table.set(*rest)
            return True
        if method == "pull_sparse":
            return table.pull(*rest)
        if method == "push_sparse":
            table.push(*rest)
            return True
        if method == "push_sparse_delta":
            table.push_delta(*rest)
            return True
        if method == "pull_geo":
            return table.pull_geo(*rest)
        if method == "tensor_set":
            table.set(*rest)
            return True
        if method == "tensor_get":
            return table.get(*rest)
        if method == "sparse_size":
            return table.size()
        raise ValueError(f"unknown PS method: {method}")

    # -- lifecycle -------------------------------------------------------------
    def start(self):
        self._rpc.start()
        return self

    def run(self, poll_s=0.2):
        """Block until a worker calls stop() — fleet.run_server() semantics."""
        self.start()
        while not self._stop_requested.is_set():
            time.sleep(poll_s)
        self.shutdown()

    def shutdown(self):
        self._rpc.shutdown()
