"""Inference deployment (paddle.inference parity).

Reference parity: paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor
+ paddle_api.h:350 CreatePaddlePredictor + api/paddle_analysis_config.h Config.

TPU-native design: the "analysis pipeline" (ir passes, TensorRT subgraphs) collapses to
XLA AOT compilation: a saved model = StableHLO text + params npz (static/io.py
save_inference_model); the Predictor re-jits the restored callable once and serves
zero-copy numpy in/out.
"""
from .predictor import Config, Predictor, create_predictor  # noqa: F401


class PrecisionType:
    """paddle.inference.PrecisionType parity (analysis_config precision)."""

    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kTPU = 3


class DataType:
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


def get_version():
    return "paddle_tpu-2.0 (TPU-native; StableHLO/jax.export runtime)"


def convert_to_mixed_precision(src_model, src_params, dst_model, dst_params,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=None, keep_io_types=True,
                               black_list=None):
    """Reference parity: inference convert_to_mixed_precision (the analysis
    pass that rewrites a saved program to fp16/bf16).

    TPU-native behavior: the saved PARAMS are actually cast to the target
    low precision (the artifact shrinks ~2x) and a precision hint is written
    beside the model. The Predictor's re-jit path reads the hint and runs
    the forward under amp.auto_cast with the recorded dtype/black_list, so
    compute precision changes too; the AOT jax.export path upcasts params to
    its traced dtypes at load (static/io._load_exported), keeping it servable.

    `black_list` semantics (two granularities, both honored where they can
    be): entries matching PARAM names keep those params f32 on disk; entries
    matching OP names (the reference's semantics, e.g. 'matmul'/'softmax')
    are forwarded to auto_cast's custom_black_list so those ops compute in
    f32 on the Predictor's re-jit path. A param name alone does not force
    f32 COMPUTE for ops consuming it — pass the op name for that."""
    import json
    import os
    import pickle
    import shutil

    import numpy as np
    import ml_dtypes

    target = {PrecisionType.Bfloat16: ml_dtypes.bfloat16,
              PrecisionType.Half: np.float16}.get(mixed_precision)
    black = set(black_list or ())

    def _cast(params):
        out = {}
        for k, v in params.items():
            v = np.asarray(v)
            if (target is not None and k not in black
                    and v.dtype in (np.float32, np.float64)):
                v = v.astype(target)
            out[k] = v
        return out

    # model side: single file, or a save_inference_model/jit.save prefix
    copied = False
    if src_model and os.path.isfile(src_model):
        if os.path.abspath(src_model) != os.path.abspath(dst_model):
            shutil.copy(src_model, dst_model)
        copied = True
    else:
        for suf in (".pdmodel", ".pdmodel.jaxexport", ".pdmodel.stablehlo",
                    ".pdmodel.meta"):
            if os.path.isfile(str(src_model) + suf):
                if os.path.abspath(str(src_model) + suf) != \
                        os.path.abspath(str(dst_model) + suf):
                    shutil.copy(str(src_model) + suf, str(dst_model) + suf)
                copied = True
    if not copied:
        raise FileNotFoundError(f"no model file/prefix at {src_model!r}")

    # params side: npz (static/io artifact), pickle (.pdiparams), or prefix
    if src_params and dst_params:
        from ..static.io import _load_params_npz, _savez_params

        sp, dp = str(src_params), str(dst_params)
        if not os.path.isfile(sp) and os.path.isfile(sp + ".pdiparams.npz"):
            sp, dp = sp + ".pdiparams.npz", dp + ".pdiparams.npz"
        elif not os.path.isfile(sp) and os.path.isfile(sp + ".pdiparams"):
            sp, dp = sp + ".pdiparams", dp + ".pdiparams"
        if sp.endswith(".npz"):
            _savez_params(dp, _cast(_load_params_npz(sp)))
        else:
            with open(sp, "rb") as f:
                params = pickle.load(f)
            with open(dp, "wb") as f:
                pickle.dump(_cast(params), f)

    hint = {"mixed_precision": int(mixed_precision),
            "dtype": (np.dtype(target).name if target is not None
                      else "float32"),
            "keep_io_types": bool(keep_io_types),
            "black_list": sorted(black)}
    with open(str(dst_model) + ".precision.json", "w") as f:
        json.dump(hint, f)
