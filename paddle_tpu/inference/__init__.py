"""Inference deployment (paddle.inference parity).

Reference parity: paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor
+ paddle_api.h:350 CreatePaddlePredictor + api/paddle_analysis_config.h Config.

TPU-native design: the "analysis pipeline" (ir passes, TensorRT subgraphs) collapses to
XLA AOT compilation: a saved model = StableHLO text + params npz (static/io.py
save_inference_model); the Predictor re-jits the restored callable once and serves
zero-copy numpy in/out.
"""
from .predictor import Config, Predictor, create_predictor  # noqa: F401


class PrecisionType:
    """paddle.inference.PrecisionType parity (analysis_config precision)."""

    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kTPU = 3


class DataType:
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


def get_version():
    return "paddle_tpu-2.0 (TPU-native; StableHLO/jax.export runtime)"


def convert_to_mixed_precision(src_model, src_params, dst_model, dst_params,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=None, keep_io_types=True,
                               black_list=None):
    """Compat: precision policy is applied at run time via amp.auto_cast
    (bf16-first); the saved artifact is precision-agnostic StableHLO, so the
    conversion is a copy + recorded precision hint."""
    import json
    import shutil

    shutil.copy(src_model, dst_model)
    if src_params and dst_params:
        shutil.copy(src_params, dst_params)
    hint = {"mixed_precision": int(mixed_precision),
            "keep_io_types": bool(keep_io_types),
            "black_list": sorted(black_list or [])}
    with open(str(dst_model) + ".precision.json", "w") as f:
        json.dump(hint, f)
