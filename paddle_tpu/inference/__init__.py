"""Inference deployment (paddle.inference parity).

Reference parity: paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor
+ paddle_api.h:350 CreatePaddlePredictor + api/paddle_analysis_config.h Config.

TPU-native design: the "analysis pipeline" (ir passes, TensorRT subgraphs) collapses to
XLA AOT compilation: a saved model = StableHLO text + params npz (static/io.py
save_inference_model); the Predictor re-jits the restored callable once and serves
zero-copy numpy in/out.
"""
from .predictor import Config, Predictor, create_predictor  # noqa: F401
