"""Predictor: the AnalysisPredictor analog.

Reference parity: inference/api/analysis_predictor.cc (Run/ZeroCopyRun with named
input/output tensors) and the Config knobs (paddle_analysis_config.h) — device
selection, memory-optim toggles (XLA handles both).

Two load paths:
 1. pdmodel pickle (jit.save product) -> re-jit the Layer (preferred; portable across
    this framework's versions).
 2. stablehlo text + npz params (static/io.py save_inference_model product) -> compile
    via jax.export round-trip when available.
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tape import global_tape
from ..core.tensor import Tensor
from ..framework import aot as _aot


class Config:
    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu"
        self._memory_optim = True

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator == TPU in this build

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_ir_optim(self, enable=True):
        pass  # XLA always optimizes

    def set_cpu_math_library_num_threads(self, n):
        pass


class _IOTensor:
    """ZeroCopyTensor parity: named handle with copy_from/to_cpu."""

    def __init__(self, store, name):
        self._store = store
        self._name = name

    def copy_from_cpu(self, arr):
        self._store[self._name] = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._store[self._name])

    def shape(self):
        return list(np.asarray(self._store[self._name]).shape)


class Predictor:
    def __init__(self, config):
        self.config = config
        self._inputs = {}
        self._outputs = {}
        self._layer = None
        self._compiled = None  # CachedJit over _pure_fn (per-shape inside)
        self._input_names = ["input_0"]
        self._load()

    def _load(self):
        path = self.config.model_path
        self._aot = None
        # convert_to_mixed_precision hint: the re-jit path honors it by
        # tracing under amp.auto_cast with the recorded dtype/black_list
        self._precision = None
        if path and os.path.exists(path + ".precision.json"):
            import json

            try:
                with open(path + ".precision.json") as f:
                    self._precision = json.load(f)
            except Exception:
                self._precision = None
        if path and os.path.exists(path + ".pdmodel.jaxexport"):
            # AOT path (save_inference_model artifact): no python Layer, no
            # re-trace — the AnalysisPredictor-on-saved-model analog. The
            # pickled-Layer path (shape-polymorphic) stays as a fallback for
            # corrupt artifacts or off-export input shapes.
            from ..static.io import load_aot_predictor

            try:
                self._aot = load_aot_predictor(path)
            except Exception:
                self._aot = None
        if self._aot is None:
            self._load_pickled_layer(path)

    def _load_pickled_layer(self, path):
        self._compiled = None  # a (re)loaded layer invalidates compiled fns
        if path and os.path.exists(path + ".pdmodel"):
            with open(path + ".pdmodel", "rb") as f:
                self._layer = pickle.load(f)
            if self._layer is None:
                raise RuntimeError("saved model not loadable")
            if os.path.exists(path + ".pdiparams"):
                with open(path + ".pdiparams", "rb") as f:
                    self._layer.set_state_dict(pickle.load(f))
            # else: the pickled layer already carries its weights
            self._layer.eval()
        elif self._aot is None:
            raise FileNotFoundError(f"no model at {path}.pdmodel")

    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._outputs.keys()) or ["output_0"]

    def get_input_handle(self, name):
        if name not in self._input_names:
            self._input_names.append(name)
        return _IOTensor(self._inputs, name)

    def get_output_handle(self, name):
        return _IOTensor(self._outputs, name)

    def _stage_inputs(self, inputs):
        """Bind positional inputs to named slots and collect the call's
        arrays in slot order. Inputs beyond the known names ride along
        POSITIONALLY for this call only (they used to be staged under an
        unlisted name and silently dropped from the forward call) —
        nothing persists for them, so an accidental surplus input fails
        its own call without poisoning later ones."""
        extras = []
        if inputs is not None:
            for i, a in enumerate(inputs):
                if i < len(self._input_names):
                    self._inputs[self._input_names[i]] = a
                else:
                    extras.append(a)
        return [self._inputs[n] for n in self._input_names
                if n in self._inputs] + extras

    def run(self, inputs=None):
        """inputs: optional list of numpy arrays (paddle_infer.Predictor.run parity)."""
        arrs = self._stage_inputs(inputs)
        if self._aot is not None:
            try:
                return self._pack_outputs(self._aot(*arrs))
            except Exception:
                # off-export shape/dtype or corrupt artifact: fall back to the
                # shape-polymorphic pickled-Layer path when it exists
                if self._layer is None:
                    self._load_pickled_layer(self.config.model_path)
                if self._layer is None:
                    raise
                self._aot = None
        if self._compiled is None:
            # one wrapper, one per-shape executable map inside; compiles
            # go through the persistent AOT cache when
            # FLAGS_jit_cache_dir is set (framework/aot.py)
            self._compiled = _aot.cached_jit(
                self._pure_fn(), site="predictor", label="predictor_run")
        out = self._compiled(*[jnp.asarray(a) for a in arrs])
        return self._pack_outputs(out)

    def _pure_fn(self):
        """The pure forward Run() jits — also handed (un-jitted) to
        paddle_tpu.analysis via analysis_jaxpr, so lint findings refer to
        the exact graph the predictor executes."""
        layer = self._layer
        tape = global_tape()
        hint = self._precision

        low_precision = bool(hint) and \
            hint.get("dtype") in ("bfloat16", "float16")

        def pure(*xs):
            import contextlib

            amp_ctx = contextlib.nullcontext()
            if low_precision:
                from ..amp import auto_cast

                amp_ctx = auto_cast(
                    True, dtype=hint["dtype"],
                    custom_black_list=hint.get("black_list") or None)
            with tape.pause(), amp_ctx:
                out = layer(*[Tensor(x) for x in xs])
            out = jax.tree_util.tree_map(
                lambda v: v._data if isinstance(v, Tensor) else v, out,
                is_leaf=lambda v: isinstance(v, Tensor),
            )
            if low_precision and hint.get("keep_io_types", True):
                out = jax.tree_util.tree_map(
                    lambda v: v.astype(jnp.float32)
                    if hasattr(v, "dtype")
                    and jnp.issubdtype(v.dtype, jnp.floating)
                    and v.dtype != jnp.float32 else v, out)
            return out

        return pure

    def analysis_jaxpr(self, inputs=None):
        """Trace the predictor's forward to a ClosedJaxpr for
        paddle_tpu.analysis.run_passes (tracing only — nothing runs).

        inputs: optional list of numpy arrays; defaults to whatever was
        staged via get_input_handle().copy_from_cpu(). Requires the
        re-jit (pickled-Layer) path — the AOT artifact is already
        compiled HLO with no jaxpr to inspect.
        """
        arrs = self._stage_inputs(inputs)
        if not arrs:
            raise ValueError("analysis_jaxpr: no inputs staged — pass "
                             "inputs= or copy_from_cpu first")
        if self._layer is None:
            self._load_pickled_layer(self.config.model_path)
        if self._layer is None:
            raise RuntimeError("analysis_jaxpr: AOT-only artifact (no "
                               "pickled Layer to re-trace)")
        return jax.make_jaxpr(self._pure_fn())(
            *[jnp.asarray(a) for a in arrs])

    def _pack_outputs(self, out):
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs.clear()
        results = []
        for i, o in enumerate(outs):
            arr = np.asarray(o._data if isinstance(o, Tensor) else o)
            self._outputs[f"output_{i}"] = arr
            results.append(arr)
        return results


def create_predictor(config):
    """paddle_infer.create_predictor / CreatePaddlePredictor (paddle_api.h:350) parity."""
    return Predictor(config)
