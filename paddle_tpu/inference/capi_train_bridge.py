"""Python side of the C TRAINING API (native/capi.cc PD_CreateTrainer /
PD_TrainStepFloat / PD_TrainerSave).

Reference parity: paddle/fluid/train/demo/demo_trainer.cc — a standalone
C/C++ host that loads a Python-authored model and runs real training steps
without any Python source of its own. TPU-native shape: the host drives a
jitted SpmdTrainer step through the embedded interpreter; parameters and
optimizer state live DEVICE-SIDE between calls (only the scalar loss
crosses the C boundary per step), so the hot path is one cached XLA
executable per (shape, dtype) signature.
"""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def create_trainer(model_prefix, optimizer_name, learning_rate, loss_name):
    """Load the jit.save'd trainable Layer at model_prefix and wrap it in a
    single-device SpmdTrainer with the named optimizer and loss."""
    import jax

    import paddle_tpu as paddle
    from ..distributed.mesh import build_mesh
    from ..distributed.spmd import SpmdTrainer
    from .. import nn

    with open(model_prefix + ".pdmodel", "rb") as f:
        layer = pickle.load(f)
    if layer is None:
        raise ValueError(
            "PD_CreateTrainer needs the pickled-Layer artifact (the "
            "jax.export inference artifact is not trainable); re-save "
            "with jit.save on a picklable Layer")
    if os.path.exists(model_prefix + ".pdiparams"):
        with open(model_prefix + ".pdiparams", "rb") as f:
            layer.set_state_dict(pickle.load(f))
    layer.train()

    opts = {
        "sgd": lambda: paddle.optimizer.SGD(
            learning_rate=learning_rate, parameters=layer.parameters()),
        "momentum": lambda: paddle.optimizer.Momentum(
            learning_rate=learning_rate, momentum=0.9,
            parameters=layer.parameters()),
        "adam": lambda: paddle.optimizer.Adam(
            learning_rate=learning_rate, parameters=layer.parameters()),
        "adamw": lambda: paddle.optimizer.AdamW(
            learning_rate=learning_rate, parameters=layer.parameters()),
    }
    if optimizer_name not in opts:
        raise ValueError(f"unknown optimizer '{optimizer_name}' "
                         f"(supported: {sorted(opts)})")
    losses = {
        "cross_entropy": nn.CrossEntropyLoss,
        "mse": nn.MSELoss,
    }
    if loss_name not in losses:
        raise ValueError(f"unknown loss '{loss_name}' "
                         f"(supported: {sorted(losses)})")

    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(layer, opts[optimizer_name](),
                          loss_fn=losses[loss_name](), mesh=mesh)
    return trainer


def train_step_bytes(trainer, x_buf, x_shape, y_buf, y_shape, y_is_float):
    """One jitted train step on raw C buffers; returns the scalar loss.
    x is float32; y is int64 labels (classification) or float32 targets
    (y_is_float, e.g. mse)."""
    x = np.frombuffer(x_buf, np.float32).reshape([int(s) for s in x_shape])
    ydt = np.float32 if y_is_float else np.int64
    y = np.frombuffer(y_buf, ydt).reshape([int(s) for s in y_shape])
    loss = trainer.train_step(Tensor(x), Tensor(y))
    return float(np.asarray(loss._data))


def save_params(trainer, prefix):
    """Persist the trained parameters in the jit.save fallback format, so
    PD_CreatePredictor / jit.load serve the trained model from `prefix`
    (the pickled .pdmodel must already exist there or be copied)."""
    trainer.sync_to_layer()   # device-side train state -> Layer tensors
    state = {n: np.asarray(t._data)
             for n, t in trainer.layer.state_dict().items()}
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    with open(prefix + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    # a stale durable artifact at this prefix would shadow the trained
    # params (jit.load prefers .pdmodel.jaxexport + .pdiparams.npz, which
    # still hold the UNtrained weights) — same hygiene as jit.save
    for stale in (".pdmodel.jaxexport", ".pdiparams.npz"):
        try:
            os.remove(prefix + stale)
        except FileNotFoundError:
            pass
    return prefix
