"""Python side of the C inference API (native/capi.cc).

Keeps the C shim free of the numpy C API: the shim passes flat float lists +
shape, this bridge reshapes, runs the jit-loaded model, and returns
(flat_output_list, shape_list).
"""
import numpy as np

from ..core.tensor import Tensor


def run_float(model, flat, shape):
    arr = np.asarray(flat, np.float32).reshape([int(s) for s in shape])
    res = _run(model, arr)
    return [float(v) for v in res.reshape(-1)], [int(s) for s in res.shape]


def run_float_bytes(model, buf, shape):
    """Zero-boxing path: C passes the raw float32 buffer as bytes."""
    arr = np.frombuffer(buf, np.float32).reshape([int(s) for s in shape])
    res = _run(model, arr)
    return np.ascontiguousarray(res).tobytes(), [int(s) for s in res.shape]


def _run(model, arr):
    out = model(Tensor(arr))
    if isinstance(out, (tuple, list)):
        out = out[0]
    return np.asarray(out._data, np.float32)
