"""Continuous-batching serving engine (beyond the reference).

The reference serves LMs request-at-a-time through its predictor; modern
LLM serving interleaves requests so a long generation never blocks a short
one. This engine is that recipe, TPU-shaped:

- a FIXED [max_batch, max_seq] KV cache (static shapes — one compiled
  decode program, ever);
- each slot carries its own sequence position: the decode step runs the
  whole batch with PER-ROW positions and per-row cache columns
  (models/gpt.py _decode_fns grew a vectorized-pos path for this);
- admission prefills a new prompt into a fresh single-row cache (prompt
  right-padded to a length bucket, so prefill compiles once per bucket)
  and copies that row into the big cache — one row copy per admission,
  nothing per step;
- right-pad junk in the prefill is never read: it sits at columns the
  causal mask hides until the decode loop OVERWRITES them (the store runs
  before attention each step);
- finished slots (eos / max_new_tokens / capacity) free immediately and
  the next queued request takes the slot on the following step() —
  continuous batching, not static batching.

Per-request decoding knobs: temperature=0 (default) is greedy with EXACT
parity vs a solo `model.generate(temperature=0)` (asserted in tests);
temperature>0 samples from the (optionally top_k-truncated) distribution
with a deterministic per-request PRNG stream, without disturbing greedy
neighbors — an all-greedy batch dispatches to a lean argmax-only compiled
step. Composes with bf16 serving params/cache (dtype="bfloat16") and the
int8 KV cache (cache_dtype="int8").

`prefill_chunk=C` enables CHUNKED prefill: a long prompt is consumed C
tokens per step() with decode steps for active slots running in between,
so an arriving 1024-token prompt stalls inter-token latency by one chunk's
compute, not one full prefill (the whole-prompt path remains the default;
outputs are identical either way — asserted in tests).

`register_prefix(ids)` caches a shared prefix's KV ONCE (system prompts):
requests submitted with `prefix_id=` start from a copy of that cache and
prefill only their suffix — identical outputs to resending the full
prompt, without recomputing the prefix per request.

Robustness (docs/ROBUSTNESS.md): per-request `deadline_ms` finishes an
overdue request with reason="deadline" while batch-mates continue;
`cancel(rid)` evicts a queued or in-flight request; `max_queue=` bounds
the admission queue — a full queue rejects (`QueueFullError`) or, when the
incoming request outranks a queued one, load-sheds the lowest-priority
entry (reason="shed", `request_shed_total{reason}`); per-slot host-side
failures are ISOLATED (the failing slot finishes with reason="error" and
is evicted, the rest of the batch continues); `health()` reports
ok/degraded/draining and `drain()` stops admission for graceful shutdown.
A non-converging `run_until_complete` fails its in-flight requests with
reason="engine_stalled" instead of leaving them dangling.

`draft_model=` turns on SPECULATIVE continuous batching (the batched form
of `generate_speculative`): each round a small draft proposes `spec_k`
tokens per slot and the target verifies all slots in ONE (spec_k+1)-token
forward at per-slot positions, emitting 1..spec_k+1 tokens per slot per
round — output bit-identical to plain greedy. Rounds run while every
active slot is greedy with cache headroom; sampling neighbors or
near-capacity slots fall back to exact single-token steps. Composes with
chunked prefill, shared prefixes, bf16/int8 caches, and tp_mesh (the
draft stays replicated; the target verify shares the head-sharded cache).

Multi-engine tier (docs/SERVING.md): the engine is MODEL-AGNOSTIC — all
model-specific decode math arrives through the DecodeModel adapter
resolved from `paddle_tpu.serving.decode_model` (gpt registers itself;
`decode_model=` picks explicitly). `submit(trace_id=, parent_span=)`
lets a fronting `serving.Router` thread its placement span into the
request's trace, and `admit_prefilled()` accepts a KV row prefilled by a
`serving.PrefillWorker` — the prefill/decode disaggregation handoff,
bit-identical to local admission.
"""
import time

import numpy as np

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from ..trace import costs as _costs
from .. import trace as _trace
from ..core.tensor import Tensor
from ..framework import aot as _aot
from ..framework import lineage as _lineage
from ..serving import decode_model as _dm_registry
from ..testing import failpoints as _fp

__all__ = ["ServingEngine", "Request", "QueueFullError"]


class QueueFullError(RuntimeError):
    """submit() rejected: the bounded admission queue is full and the
    request's priority does not outrank any queued entry."""


class _AdapterUnavailable(RuntimeError):
    """Paged admission found the request's adapter not loaded (evicted
    mid-flight): requeue-at-head backpressure, exactly like
    ``PagePoolFullError`` — never a reason='error' finish. The request
    re-admits, and regenerates bit-identically, once the adapter is
    loaded again."""

# engine metrics in the default registry (every engine in the process
# shares them; per-engine views live on ServingEngine.stats())
_REQ_SUBMITTED = _monitor.counter(
    "serving_requests_submitted_total", "requests accepted by submit()")
_REQ_FINISHED = _monitor.counter(
    "serving_requests_finished_total",
    "finished requests by reason (eos|length|capacity)",
    labelnames=("reason",))
_TOKENS = _monitor.counter(
    "serving_tokens_total", "generated tokens across all requests")
_QUEUE_WAIT_MS = _monitor.histogram(
    "serving_queue_wait_ms", "submit() -> admission start wait")
_TTFT_MS = _monitor.histogram(
    "serving_ttft_ms", "submit() -> first generated token")
_ITL_MS = _monitor.histogram(
    "serving_inter_token_ms",
    "gap between consecutive generated tokens of one request (a "
    "speculative round lands its accepted run at once: near-zero gaps)")
_STEPS = _monitor.counter(
    "serving_steps_total",
    "engine step slices by kind "
    "(decode_greedy|decode_sample|prefill_chunk|speculative)",
    labelnames=("kind",))
_OCCUPANCY = _monitor.gauge(
    "serving_batch_occupancy", "active decode slots at the last step()")
_PREFIX = _monitor.counter(
    "serving_prefix_cache_total",
    "prefix-reuse admissions: hit = suffix-only prefill from cached KV, "
    "miss = a prefix_id request that fell back to whole-prompt prefill",
    labelnames=("event",))
_SPEC = _monitor.counter(
    "serving_spec_tokens_total",
    "speculative decoding draft tokens (proposed vs accepted)",
    labelnames=("event",))
_SHED = _monitor.counter(
    "request_shed_total",
    "load-shedding on the bounded admission queue (queue_full = incoming "
    "request rejected with QueueFullError; preempted = a lower-priority "
    "queued request was finished with reason='shed' to admit a higher-"
    "priority one)",
    labelnames=("reason",))
_DEADLINE = _monitor.counter(
    "request_deadline_exceeded_total",
    "requests finished with reason='deadline' (per-request deadline_ms "
    "elapsed before completion)")


class _MsSummary:
    """O(1) per-request/per-engine latency accumulator for stats()."""

    __slots__ = ("count", "sum", "min", "max")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def add(self, v):
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def to_dict(self):
        return {"count": self.count, "sum_ms": self.sum,
                "avg_ms": self.sum / self.count if self.count else 0.0,
                "min_ms": self.min or 0.0, "max_ms": self.max or 0.0}


class Request:
    """One submitted prompt and, when finished, its generated tokens.
    Lifecycle timestamps (perf_counter seconds) are stamped by the engine;
    ``stats()`` is the per-request observability view."""

    def __init__(self, rid, prompt_ids, max_new_tokens, temperature=0.0,
                 top_k=None, top_p=None, seed=None, prefix_id=None,
                 prefix_len=0, deadline_ms=None, priority=0, adapter=None):
        self.rid = rid
        self.prompt_ids = np.asarray(prompt_ids, np.int32).ravel()
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.seed = rid if seed is None else int(seed)
        self.prefix_id = prefix_id          # registered shared prefix, or
        self.prefix_len = int(prefix_len)   # 0 = no prefix reuse
        self.adapter = adapter    # loaded LoRA adapter name (paged engines)
        self.deadline_ms = deadline_ms      # None = no deadline
        self.priority = int(priority)       # higher outranks on a full queue
        self.output_ids = []          # generated tokens (no prompt echo)
        # tracing (FLAGS_trace): one trace_id per request; the root span
        # lives submit() -> finish reason, queue_wait is its first child
        self.trace_id = None
        self._span = None
        self._qspan = None
        self.finished = False
        # "eos" | "length" | "capacity" | "deadline" | "error" |
        # "cancelled" | "shed" | "engine_stalled"
        self.finish_reason = None
        self.submit_time = None       # stamped by ServingEngine.submit
        self.admit_time = None        # admission start (queue wait ends)
        self.first_token_time = None
        self.last_token_time = None
        self.finish_time = None
        self._inter_token = _MsSummary()
        # weight lineage (framework/lineage.py, ISSUE 20): the engine
        # stamps at submission which weight (and adapter) version this
        # session decodes under — a hot_swap mid-stream leaves the
        # session on its pre-swap stamp, which _finish_req counts as a
        # stale finish (serving_stale_sessions_total, FLAGS_goodput)
        self.weight_version = None
        self.adapter_version = None

    @property
    def tokens(self):
        return np.asarray(self.output_ids, np.int32)

    def _note_token(self, now):
        """Record one emitted token; returns the inter-token gap in ms
        (None for the first token)."""
        gap = None
        if self.first_token_time is None:
            self.first_token_time = now
        else:
            gap = (now - self.last_token_time) * 1e3
            self._inter_token.add(gap)
        self.last_token_time = now
        return gap

    def stats(self):
        """Per-request latency/throughput stats (ms), live at any point of
        the lifecycle — the latency-tracker surface get_request promises."""
        out = {"rid": self.rid, "finished": self.finished,
               "trace_id": self.trace_id,   # joins req stats to its spans
               "finish_reason": self.finish_reason,
               "prompt_tokens": int(len(self.prompt_ids)),
               "prefix_tokens": self.prefix_len,
               "new_tokens": len(self.output_ids)}
        if self.weight_version is not None:
            out["weight_version"] = str(self.weight_version)
        if self.adapter_version is not None:
            out["adapter_version"] = str(self.adapter_version)
        if self.submit_time is not None and self.admit_time is not None:
            out["queue_wait_ms"] = (self.admit_time - self.submit_time) * 1e3
        if self.submit_time is not None \
                and self.first_token_time is not None:
            out["ttft_ms"] = (self.first_token_time
                              - self.submit_time) * 1e3
        out["inter_token"] = self._inter_token.to_dict()
        if self.first_token_time is not None \
                and self.last_token_time is not None \
                and len(self.output_ids) > 1:
            dt = self.last_token_time - self.first_token_time
            if dt > 0:
                out["decode_tokens_per_sec"] = \
                    (len(self.output_ids) - 1) / dt
        return out


def _blackbox_request_table(eng):
    """One engine's in-flight request table for a blackbox dump bundle:
    where every unfinished request lives and how far it got — the
    'which rids were mid-flight when it wedged' evidence."""
    running = [{"rid": r.rid, "slot": s, "pos": int(eng._pos[s]),
                "new_tokens": len(r.output_ids)}
               for s, r in enumerate(eng._slot_req)
               if r is not None and s not in eng._prefilling]
    table = {
        "slots": eng.B,
        "step_no": eng._step_no,
        "draining": eng._draining,
        "queued": [r.rid for r in eng._queue],
        "handoff": [e[0].rid for e in eng._handoff],
        "prefilling": {s: e[0].rid for s, e in eng._prefilling.items()},
        "running": running,
        "finished": len(eng._finished),
    }
    table["in_flight"] = sorted(
        set(table["queued"]) | set(table["handoff"])
        | set(table["prefilling"].values())
        | {r["rid"] for r in running})
    return table


class ServingEngine:
    def __init__(self, model, max_batch=4, dtype=None, cache_dtype=None,
                 eos_token_id=None, prompt_buckets=(32, 64, 128, 256, 512,
                                                    1024), tp_mesh=None,
                 prefill_chunk=None, draft_model=None, spec_k=4,
                 max_queue=None, decode_model=None, page_block=None,
                 page_blocks=None, max_adapters=None, lora_rank=None,
                 page_cold_steps=None):
        import jax
        import jax.numpy as jnp

        # the engine is model-agnostic: every model-specific decode entry
        # point (config check, param extraction, decode math, tp recipe)
        # comes through the DecodeModel adapter resolved here — never from
        # a model module's privates (docs/SERVING.md; lint-enforced by
        # analysis/source_lint.py private-model-import-in-serving)
        dm = _dm_registry.resolve(model, decode_model)
        self._dm = dm
        cfg = model.cfg
        dm.check_config(cfg)
        self.cfg = cfg
        self.B = int(max_batch)
        self.T = cfg.max_seq_len
        self.eos = eos_token_id
        # argument validation FIRST — before any device allocation/compile
        # (cache_dtype is validated centrally by _decode_fns' _QUANT table)
        if prefill_chunk is not None:
            if not 1 <= int(prefill_chunk) <= self.T:
                raise ValueError(
                    f"prefill_chunk must be in [1, max_seq_len={self.T}], "
                    f"got {prefill_chunk}")
        if max_queue is not None and int(max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._max_queue = None if max_queue is None else int(max_queue)
        # paged KV + batched multi-LoRA serving (FLAGS_paged_kv, ISSUE 18).
        # STRUCTURAL and construction-consumed: the boolean read here joins
        # the AOT extra_key below (paged executables never alias dense
        # ones), and _paged_active() raises on a post-construction disarm.
        # Armed, the dense [max_batch, max_seq] cache is replaced by a
        # physical block pool + per-slot block tables (serving/paging.py)
        # with whole-budget reservation at admission, refcounted prefix
        # sharing, int8 cold pages, and per-request adapter deltas batched
        # inside the one jitted decode step.
        _paged = bool(_flags.get_flag("paged_kv", False))
        self._paged = _paged
        _pg_set = sorted(k for k, v in (
            ("page_block", page_block), ("page_blocks", page_blocks),
            ("max_adapters", max_adapters), ("lora_rank", lora_rank),
            ("page_cold_steps", page_cold_steps)) if v is not None)
        if not _paged and _pg_set:
            raise ValueError(
                f"{', '.join(_pg_set)}= need FLAGS_paged_kv=1 — the paged "
                "engine is flag-gated (structural; consumed at engine "
                "construction)")
        if _paged:
            if tp_mesh is not None:
                raise ValueError(
                    "FLAGS_paged_kv does not compose with tp_mesh= serving:"
                    " the block pool is single-host state — serve tensor-"
                    "parallel engines dense")
            if draft_model is not None:
                raise ValueError(
                    "FLAGS_paged_kv does not compose with draft_model= "
                    "(speculative rounds write multi-token columns; the "
                    "paged scatter writes one frontier column per step)")
            if cache_dtype is not None:
                raise ValueError(
                    "FLAGS_paged_kv does not compose with cache_dtype=: "
                    "hot pages live at the compute dtype; the cold tier is "
                    "the pool's int8 page codec (page_cold_steps=)")
            if prefill_chunk is not None:
                raise ValueError(
                    "FLAGS_paged_kv does not compose with prefill_chunk= "
                    "(paged admission prefills whole prompts into blocks "
                    "reserved up front)")
        dm_d = None
        if draft_model is not None:
            dm_d = _dm_registry.resolve(draft_model, None)
            if draft_model.cfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if not (1 <= int(spec_k) <= 16):
                raise ValueError(f"spec_k must be in [1, 16], got {spec_k}")
            if draft_model.cfg.max_seq_len < self.T:
                raise ValueError(
                    f"draft max_seq_len ({draft_model.cfg.max_seq_len}) "
                    f"must cover the target's ({self.T})")
            dm_d.check_config(draft_model.cfg)
        self._buckets = tuple(sorted(b for b in prompt_buckets
                                     if b <= self.T))
        if not self._buckets:
            raise ValueError("no prompt bucket fits max_seq_len")
        params, dm_aux = dm.extract_params(model, "the model")
        self._compute_dtype = dm.compute_dtype(dtype)
        if self._compute_dtype is not None:
            params = {k: (v.astype(self._compute_dtype)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}
        # tensor-parallel serving: dense checkpoint Megatron-split over an
        # 'mp' mesh (same recipe as generate(tp_mesh=...)); the engine's
        # PERSISTENT KV cache lives head-sharded across the mesh
        tp_axis, tp_size, tp_specs = None, 1, None
        if tp_mesh is not None:
            tp_axis, tp_size, params, tp_specs = dm.tp_setup(tp_mesh, cfg,
                                                             params)
        self._tp_mesh = tp_mesh
        self._params = params
        fwd, logits_of, cache_init = dm.decode_fns(cfg, dm_aux,
                                                   cache_dtype=cache_dtype,
                                                   tp_axis=tp_axis,
                                                   tp_size=tp_size)
        cache_dt = self._compute_dtype or jnp.float32

        if _paged:
            # no dense [B, T] cache: physical K/V lives in the block pool;
            # each decode step gathers it through the block tables into the
            # exact dense layout fwd consumes, then scatters the frontier
            # column back (paged programs below)
            from ..serving import paging as _paging

            self._paging = _paging
            side = jax.eval_shape(lambda: cache_init(1, self.T, cache_dt))
            L, _, KVh, _, hd = side[0].shape
            bs_pg = 16 if page_block is None else int(page_block)
            if bs_pg < 1 or self.T % bs_pg:
                raise ValueError(
                    f"page_block must divide max_seq_len={self.T}, "
                    f"got {page_block}")
            maxb = self.T // bs_pg
            # default pool: every slot can hold a full-length session,
            # plus the permanent NULL frame — a ceiling, not a win; the
            # memory win comes from page_blocks= sized to the real
            # shared-prefix workload (tools/parity_check.py paged_kv)
            n_blocks = (self.B * maxb + 1 if page_blocks is None
                        else int(page_blocks))
            self._pool = _paging.PagePool(
                (int(L), int(KVh), int(hd)), cache_dt, bs_pg, n_blocks,
                self.B, self.T, cold_after=page_cold_steps)
            self._kc = self._vc = None
            n_ad = 8 if max_adapters is None else int(max_adapters)
            self._lora_rank = 8 if lora_rank is None else int(lora_rank)
            self._adapters = None
            self._lora = None
            if n_ad > 0:
                try:
                    # slot 0 is the permanent all-zero BASE adapter: base
                    # requests take the lora path with an exact-zero delta
                    self._lora = dm.lora_init(cfg, n_ad + 1,
                                              self._lora_rank,
                                              dtype=self._compute_dtype)
                    self._adapters = _paging.AdapterRegistry(n_ad)
                except NotImplementedError:
                    pass   # pool serves base-only; adapter APIs raise
            self._adapter_slot = np.zeros(self.B, np.int32)
        elif tp_mesh is None:
            self._kc, self._vc = cache_init(self.B, self.T, cache_dt)
        else:
            # allocate the GLOBAL cache (full KV heads) sharded on the
            # head axis, DIRECTLY into its sharding (no transient
            # single-device copy). The global layout comes from the DENSE
            # cache_init via eval_shape — one source of truth, so a cache
            # layout change in _decode_fns can't silently diverge here.
            from jax.sharding import NamedSharding, PartitionSpec as P

            dense_cache_init = dm.decode_fns(cfg, dm_aux,
                                             cache_dtype=cache_dtype)[2]
            tpl = jax.eval_shape(
                lambda: dense_cache_init(self.B, self.T, cache_dt))
            cache_spec = P(None, None, "mp", None, None)
            shard = NamedSharding(tp_mesh, cache_spec)
            alloc = jax.jit(
                lambda: jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), tpl),
                out_shardings=jax.tree_util.tree_map(lambda s: shard, tpl))
            self._kc, self._vc = alloc()
            self._cache_spec = cache_spec
            # single-row SIDE caches (chunked prefill staging, shared
            # prefixes) use the same global-layout + head-sharded
            # allocation recipe as the big cache
            side_tpl = jax.eval_shape(
                lambda: dense_cache_init(1, self.T, cache_dt))
            side_alloc = jax.jit(
                lambda: jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), side_tpl),
                out_shardings=jax.tree_util.tree_map(
                    lambda s: shard, side_tpl))

        def prefill(p, ids_padded, true_len):
            """ids_padded [1, Pb] right-padded; returns (kc1, vc1,
            last_logits [vocab]). Junk beyond true_len is causally
            invisible and later overwritten by the decode loop."""
            kc1, vc1 = cache_init(1, self.T, cache_dt)
            x, kc1, vc1 = fwd(p, ids_padded, 0, kc1, vc1)
            x_last = jax.lax.dynamic_slice_in_dim(
                x, true_len - 1, 1, axis=1)[:, 0]
            return kc1, vc1, logits_of(p, x_last).astype(jnp.float32)[0]

        def prefill_start():
            return cache_init(1, self.T, cache_dt)

        def prefill_chunk_fn(p, chunk_ids, offset, kc1, vc1, last_in_chunk):
            """Consume ONE fixed-size chunk at column `offset` of the slot's
            side cache; returns updated cache + the logits at
            last_in_chunk (only meaningful on the final chunk — junk
            columns beyond it are causally invisible/overwritten)."""
            x, kc1, vc1 = fwd(p, chunk_ids, offset, kc1, vc1)
            x_last = jax.lax.dynamic_slice_in_dim(
                x, last_in_chunk, 1, axis=1)[:, 0]
            return kc1, vc1, logits_of(p, x_last).astype(jnp.float32)[0]

        def admit(big, row, r):
            """Copy a 1-row cache into row r of the big cache (r traced —
            one compile covers every slot)."""

            def put(b_leaf, r_leaf):
                return jax.lax.dynamic_update_slice(
                    b_leaf, r_leaf, (0, r, 0, 0, 0))

            if isinstance(big, tuple):
                return (put(big[0], row[0]), put(big[1], row[1]))
            return put(big, row)

        vocab = cfg.vocab_size

        def _pick(logits, temps, kvec, pvec, seeds, pos_vec):
            """Per-row pick: temperature 0 = exact greedy (the argmax path
            is untouched); temperature > 0 samples from the (optionally
            per-row top-k and/or top-p truncated) distribution with a PRNG
            key derived from (request seed, position) — deterministic per
            request, independent across slots. Nucleus filtering runs on
            the temperature-scaled logits, exactly like generate()'s
            single-request pick (models/gpt.py _gpt_generate)."""
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)

            # per-row top-k cutoff (kvec = vocab means no truncation)
            srt = jnp.sort(logits, axis=-1)[:, ::-1]
            cut = jnp.take_along_axis(
                srt, jnp.clip(kvec - 1, 0, vocab - 1)[:, None], axis=-1)
            lg = jnp.where(logits < cut, -jnp.inf, logits)
            safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
            lgt = lg / safe_t
            # per-row nucleus (pvec = 1.0 means no truncation): smallest
            # sorted prefix reaching mass p; the top token always survives
            srt_t = jnp.sort(lgt, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt_t, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            k_keep = jnp.sum(cum - probs < pvec[:, None], axis=-1)
            cutoff = jnp.take_along_axis(
                srt_t, jnp.maximum(k_keep - 1, 0)[:, None], axis=-1)
            lgt = jnp.where(lgt < cutoff, -jnp.inf, lgt)

            def draw(row_logits, seed, p_):
                key = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(0), seed), p_)
                return jax.random.categorical(key, row_logits)

            sampled = jax.vmap(draw)(lgt, seeds,
                                     pos_vec).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        def step_greedy(p, kc, vc, last_toks, pos_vec):
            """One decode step for ALL slots at their own positions —
            argmax only (the default workload keeps its lean hot loop:
            no sort/categorical machinery compiled in)."""
            x, kc, vc = fwd(p, last_toks[:, None], pos_vec, kc, vc)
            logits = logits_of(p, x[:, 0]).astype(jnp.float32)
            return jnp.argmax(logits, -1).astype(jnp.int32), kc, vc

        def step_sample(p, kc, vc, last_toks, pos_vec, temps, kvec,
                        pvec, seeds):
            """Decode step with per-request sampling knobs [B] (used only
            while at least one active request has temperature > 0)."""
            x, kc, vc = fwd(p, last_toks[:, None], pos_vec, kc, vc)
            logits = logits_of(p, x[:, 0]).astype(jnp.float32)
            return _pick(logits, temps, kvec, pvec, seeds, pos_vec), kc, vc

        if _paged:
            _paging_mod = self._paging
            _has_lora = self._lora is not None

            def _fwd_pg(p, toks, pos, kc, vc, lora, aids):
                if _has_lora:
                    return fwd(p, toks, pos, kc, vc, lora=lora,
                               adapter_ids=aids)
                return fwd(p, toks, pos, kc, vc)

            def prefill_paged(p, ids_padded, true_len, lora, aid):
                """Whole-prompt prefill with the request's adapter delta
                applied (aid [1]; slot 0 = base = exact-zero add): the
                prefilled row and first-token logits match a dedicated
                engine serving that adapter byte-for-byte."""
                kc1, vc1 = cache_init(1, self.T, cache_dt)
                x, kc1, vc1 = _fwd_pg(p, ids_padded, 0, kc1, vc1, lora, aid)
                x_last = jax.lax.dynamic_slice_in_dim(
                    x, true_len - 1, 1, axis=1)[:, 0]
                return kc1, vc1, logits_of(p, x_last).astype(jnp.float32)[0]

            def step_greedy_paged(p, kp, vp, tables, last_toks, pos_vec,
                                  lora, aids):
                """Paged decode step: gather pool frames -> the dense
                [L, B, KVh, T, hd] layout, run the UNCHANGED decode math
                (per-row adapter deltas included), scatter each row's
                frontier column back into its frame. Junk in null/free
                columns sits strictly above every row's position, so
                causal masking makes tokens bit-identical to the dense
                engine's."""
                kc, vc = _paging_mod.gather_dense(kp, vp, tables)
                x, kc, vc = _fwd_pg(p, last_toks[:, None], pos_vec, kc, vc,
                                    lora, aids)
                kp, vp = _paging_mod.scatter_cols(kp, vp, kc, vc, tables,
                                                  pos_vec)
                logits = logits_of(p, x[:, 0]).astype(jnp.float32)
                return jnp.argmax(logits, -1).astype(jnp.int32), kp, vp

            def step_sample_paged(p, kp, vp, tables, last_toks, pos_vec,
                                  temps, kvec, pvec, seeds, lora, aids):
                kc, vc = _paging_mod.gather_dense(kp, vp, tables)
                x, kc, vc = _fwd_pg(p, last_toks[:, None], pos_vec, kc, vc,
                                    lora, aids)
                kp, vp = _paging_mod.scatter_cols(kp, vp, kc, vc, tables,
                                                  pos_vec)
                logits = logits_of(p, x[:, 0]).astype(jnp.float32)
                return (_pick(logits, temps, kvec, pvec, seeds, pos_vec),
                        kp, vp)

        # every program in the family goes through the persistent AOT
        # compile cache (framework/aot.py): with FLAGS_jit_cache_dir set,
        # a fresh server process deserializes executables instead of
        # re-jitting the whole family; warmup() compiles them from shape
        # specs before traffic. Flag unset = plain jax.jit behavior.
        _mesh_fp = _aot.mesh_fingerprint(tp_mesh)

        def _cj(fn=None, label=None, jit=None, donate=()):
            return _aot.cached_jit(fn, jit=jit, site="serving", label=label,
                                   donate_argnums=donate,
                                   record_event="serving/compile",
                                   extra_key=(_mesh_fp, _paged))

        # donate the big cache through admit/step: XLA aliases it in place
        # instead of copying GBs of K/V per token (the loop this engine
        # exists to make fast); CPU backends that can't donate just warn
        if tp_mesh is None:
            self._prefill = _cj(prefill, "prefill")
            self._step_greedy = _cj(step_greedy, "step_greedy",
                                    donate=(1, 2))
            self._step_sample = _cj(step_sample, "step_sample",
                                    donate=(1, 2))
            if _paged:
                # pool sides donate through the step exactly like the
                # dense big cache: the scatter updates them in place
                self._prefill_pg = _cj(prefill_paged, "prefill_paged")
                self._step_greedy_pg = _cj(step_greedy_paged,
                                           "step_greedy_paged",
                                           donate=(1, 2))
                self._step_sample_pg = _cj(step_sample_paged,
                                           "step_sample_paged",
                                           donate=(1, 2))
        else:
            from jax.sharding import PartitionSpec as P

            _tp_wrap = dm.tp_wrap
            cs = self._cache_spec   # pytree-prefix: covers int8 tuples too
            self._prefill = _cj(jit=_tp_wrap(
                prefill, tp_mesh, tp_specs, 0, (cs, cs, P()),
                in_specs=(tp_specs, P(), P())), label="prefill")
            self._step_greedy = _cj(jit=_tp_wrap(
                step_greedy, tp_mesh, tp_specs, 0, (P(), cs, cs),
                in_specs=(tp_specs, cs, cs, P(), P()), donate=(1, 2)),
                label="step_greedy")
            self._step_sample = _cj(jit=_tp_wrap(
                step_sample, tp_mesh, tp_specs, 0, (P(), cs, cs),
                in_specs=(tp_specs, cs, cs, P(), P(), P(), P(), P(), P()),
                donate=(1, 2)), label="step_sample")
            # chunked prefill composes with tp: the chunk side-cache
            # allocates head-sharded (side_alloc above) and the chunk
            # program runs inside the same shard_map recipe
            self._prefill_start = side_alloc
            self._prefill_chunk = _cj(jit=_tp_wrap(
                prefill_chunk_fn, tp_mesh, tp_specs, 0, (cs, cs, P()),
                in_specs=(tp_specs, P(), P(), cs, cs, P()),
                donate=(3, 4)), label="prefill_chunk")
        # admit slices only the batch axis: a plain jit partitions it
        # fine over the head-sharded cache
        self._admit = _cj(admit, "admit", donate=(0,))
        # the prefill token goes through the SAME pick as decode steps
        self._pick1 = _cj(lambda lg, t, k, tp, s, p_: _pick(
            lg[None], t[None], k[None], tp[None], s[None], p_[None])[0],
            "pick1")

        self._chunk = None if prefill_chunk is None else int(prefill_chunk)
        if tp_mesh is None:
            self._prefill_start = prefill_start
            self._prefill_chunk = _cj(prefill_chunk_fn, "prefill_chunk",
                                      donate=(3, 4))
        # slot -> [req, kc1, vc1, consumed_offset, chunk_width]
        self._prefilling = {}
        # registered shared prefixes: pid -> (ids, kc1, vc1). The chunk fn
        # DONATES its cache args, so admissions consume a fresh COPY
        self._prefixes = {}
        self._next_pid = 0
        self._copy_cache = _cj(
            lambda c: jax.tree_util.tree_map(jnp.array, c), "copy_cache")

        # --- speculative decoding: a draft model proposes spec_k tokens
        # per round, the target verifies them in ONE multi-token forward
        # at PER-SLOT positions and accepts the longest matching prefix
        # plus its own fix-up token — 1..spec_k+1 tokens per round, output
        # bit-identical to plain greedy (same scheme as
        # generate_speculative, batched over slots; the cache invariant —
        # junk columns past the accepted frontier are causally invisible
        # and overwritten — is the one admission prefill already relies
        # on). Rounds run only while EVERY active slot is greedy with
        # spec_k+1 columns of cache headroom; otherwise the engine falls
        # back to single-token steps (still exact).
        self._draft = None
        if draft_model is not None:
            self._spec_k = K = int(spec_k)
            params_d, dm_d_aux = dm_d.extract_params(
                draft_model, "the draft model")
            if self._compute_dtype is not None:
                params_d = {n: (v.astype(self._compute_dtype)
                                if jnp.issubdtype(v.dtype, jnp.floating)
                                else v) for n, v in params_d.items()}
            # the draft is small by design: it stays replicated (dense
            # fns) even when the target serves tensor-parallel
            fwd_d, logits_d, cache_init_d = dm_d.decode_fns(
                draft_model.cfg, dm_d_aux, cache_dtype=cache_dtype)
            self._params_d = params_d
            self._kc_d, self._vc_d = cache_init_d(self.B, self.T, cache_dt)

            def draft_row():
                return cache_init_d(1, self.T, cache_dt)

            def draft_feed(pd, ids_padded, offset, kc1, vc1):
                """Write a token block's draft KV at `offset` (whole-prompt
                prefill at 0, or one chunk of a chunked admission)."""
                _, kc1, vc1 = fwd_d(pd, ids_padded, offset, kc1, vc1)
                return kc1, vc1

            def draft_propose(pd, kc_d, vc_d, last, pos_vec):
                """K sequential draft steps at per-row positions; also
                writes the K-th proposal's KV (an all-accepted round
                continues PAST that column — an unwritten column inside
                the accepted prefix would poison later attention)."""
                d_cur = last
                props = []
                for j in range(K):
                    xd, kc_d, vc_d = fwd_d(pd, d_cur[:, None], pos_vec + j,
                                           kc_d, vc_d)
                    d_cur = jnp.argmax(
                        logits_d(pd, xd[:, 0]).astype(jnp.float32),
                        -1).astype(jnp.int32)
                    props.append(d_cur)
                _, kc_d, vc_d = fwd_d(pd, d_cur[:, None], pos_vec + K,
                                      kc_d, vc_d)
                return jnp.stack(props, axis=1), kc_d, vc_d

            def verify(p, kc, vc, last, pos_vec, props):
                """One (K+1)-token target forward per slot row: accept the
                longest prefix where each proposal equals the target's own
                argmax after the same context, emit it plus the target's
                fix-up token. emit[s, j] is meaningful for j <= m[s]."""
                seq = jnp.concatenate([last[:, None], props], axis=1)
                x, kc, vc = fwd(p, seq, pos_vec, kc, vc)
                preds = jnp.argmax(
                    logits_of(p, x).astype(jnp.float32),
                    -1).astype(jnp.int32)                     # [B, K+1]
                matches = (props == preds[:, :K]).astype(jnp.int32)
                m = jnp.cumprod(matches, axis=1).sum(axis=1)  # [B] 0..K
                fix = jnp.take_along_axis(preds, m[:, None], axis=1)
                j_idx = jnp.arange(K + 1)[None]
                padded = jnp.pad(props, ((0, 0), (0, 1)))
                emit = jnp.where(j_idx < m[:, None], padded, fix)
                return emit, m, kc, vc

            def draft_sync(pd, kc_d, vc_d, last, pos_vec):
                """One 1-token draft forward at per-row positions: keeps
                the draft KV cache in lockstep during single-token
                FALLBACK steps (sampling neighbors / near-capacity), so a
                slot that lives through a fallback resumes speculative
                rounds with an intact draft context instead of a
                permanently cold one."""
                _, kc_d, vc_d = fwd_d(pd, last[:, None], pos_vec,
                                      kc_d, vc_d)
                return kc_d, vc_d

            self._draft = draft_model
            self._draft_row = draft_row
            self._draft_sync = _cj(draft_sync, "draft_sync", donate=(1, 2))
            self._draft_feed = _cj(draft_feed, "draft_feed", donate=(3, 4))
            self._draft_propose = _cj(draft_propose, "draft_propose",
                                      donate=(1, 2))
            if tp_mesh is None:
                self._verify = _cj(verify, "verify", donate=(1, 2))
            else:
                from jax.sharding import PartitionSpec as P

                cs = self._cache_spec
                self._verify = _cj(jit=dm.tp_wrap(
                    verify, tp_mesh, tp_specs, 0, (P(), P(), cs, cs),
                    in_specs=(tp_specs, cs, cs, P(), P(), P()),
                    donate=(1, 2)), label="verify")

        # async double-buffered rounds (FLAGS_async_dispatch, docs/
        # PERF.md): consumed at ENGINE CONSTRUCTION like the trainer's
        # copy of the flag. Armed, step() dispatches round N's decode
        # FIRST and runs round N+1's admission/bookkeeping while the
        # device computes, fetching tokens last — the host work hides
        # behind device compute. Speculative engines keep the sync step
        # (the draft round's host orchestration is itself the dispatch).
        self._async = bool(_flags.get_flag("async_dispatch", False))
        self._async_ms = ({"dispatch_ms": 0.0, "overlap_ms": 0.0,
                           "fetch_ms": 0.0, "rounds": 0}
                          if self._async else None)

        # engine-local observability accumulators (the module-level monitor
        # metrics aggregate across engines; stats() reports THIS engine)
        self._m = {"submitted": 0, "finished": {}, "tokens": 0,
                   "steps": {}, "step_ms": {}, "spec_proposed": 0,
                   "spec_accepted": 0,
                   "prefix_hit": 0, "prefix_miss": 0,
                   "occupancy_sum": 0, "occupancy_steps": 0,
                   "queue_wait_ms": _MsSummary(), "ttft_ms": _MsSummary(),
                   "inter_token_ms": _MsSummary()}

        # host-side slot state
        self._slot_req = [None] * self.B        # Request or None
        self._pos = np.zeros(self.B, np.int32)  # next write column
        self._last = np.zeros(self.B, np.int32)
        self._temps = np.zeros(self.B, np.float32)   # 0 = greedy
        self._topk = np.full(self.B, self.cfg.vocab_size, np.int32)
        self._topp = np.ones(self.B, np.float32)     # 1.0 = no nucleus
        self._seeds = np.zeros(self.B, np.int32)
        self._queue = []
        # disaggregated prefill->decode handoff (admit_prefilled): rows
        # whose prompt KV arrived already prefilled, waiting for a slot.
        # Plain engines never touch it beyond an empty-list truthiness
        # check per step (gate-pinned in tests/test_router_gate.py).
        self._handoff = []
        self._next_rid = 0
        self._finished = {}
        # robustness state: draining stops admission; step/error counters
        # feed health()'s ok|degraded|draining verdict
        self._draining = False
        self._deadline_live = 0   # unfinished requests carrying deadline_ms
        self._step_no = 0
        self._last_error_step = None
        # perf ledger (FLAGS_perf_ledger, docs/OBSERVABILITY.md):
        # consumed at ENGINE CONSTRUCTION like the trainer's copy.
        # Non-structural — host-side accounting only; disarmed, step()
        # pays one `is not None`
        self._perf_ledger = None
        self._perf_rounds = 0
        if _flags.get_flag("perf_ledger", False):
            from ..monitor import perfledger as _perfledger

            self._perf_ledger = _perfledger.get_ledger()
        # weight-version lineage (framework/lineage.py, ISSUE 20):
        # always-on host metadata — the engine mints a version for the
        # params it was built with, bumps it on hot_swap(), and stamps
        # every accepted request with the version it will decode under.
        # Adapter slots carry their own load-time stamps. METRIC
        # publication (serving_weight_version gauge, stale-session
        # counter) rides the goodput accountant, consumed here like the
        # perf ledger: disarmed costs one `is not None` per finish.
        self._weight_version = _lineage.WeightVersion(
            _lineage.new_run_id(), 0, "init")
        self._adapter_versions = {}   # adapter name -> WeightVersion
        self._goodput = None
        if _flags.get_flag("goodput", False):
            from ..monitor import goodput as _goodput

            self._goodput = _goodput
            _goodput.note_serving_version(self._weight_version.counter)

        # blackbox dump bundles carry every live engine's in-flight
        # request table (weakly held; only read at dump time)
        _blackbox.register_provider("serving_engine", self,
                                    _blackbox_request_table)

    # -- API -----------------------------------------------------------------
    def register_prefix(self, prefix_ids, adapter=None):
        """Prefill a shared prefix (e.g. a system prompt) ONCE and cache
        its KV; returns a prefix id for submit(prefix_id=...). Requests
        using it prefill only their suffix.

        Paged engines (FLAGS_paged_kv): the prefix's full blocks land in
        the pool ONCE and every session submitting with this prefix_id
        maps them SHARED (refcounted; a partial boundary block is copied
        private at admission — copy-on-write). ``adapter=`` prefills the
        prefix under that loaded adapter's delta; sessions share the
        frames only when their adapter matches."""
        import jax.numpy as jnp

        if adapter is not None and not self._paged:
            raise ValueError(
                "register_prefix(adapter=) needs FLAGS_paged_kv=1")
        ids = prefix_ids._data if isinstance(prefix_ids, Tensor) \
            else np.asarray(prefix_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if len(ids) == 0:
            raise ValueError("empty prefix")
        if len(ids) + 2 > self.T:
            raise ValueError(
                f"prefix ({len(ids)}) too long for max_seq_len {self.T}")
        n = len(ids)
        pb = self._bucket(n)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :n] = ids
        if self._paged:
            aid = self._resolve_adapter_slot(adapter)
            t0 = time.perf_counter()
            kc1, vc1, _ = self._prefill_pg(
                self._params, jnp.asarray(padded), np.int32(n),
                self._lora, jnp.asarray([aid], np.int32))
            self._acc_ms("prefill", t0)
            pid = self._next_pid
            self._next_pid += 1
            # full blocks land in the pool once (put_prefix may raise
            # PagePoolFullError — nothing is registered then); the dense
            # row is dropped, sessions re-block only their suffix
            self._pool.put_prefix(pid, kc1[:, 0], vc1[:, 0], n)
            self._prefixes[pid] = (ids, "paged", adapter, None, None)
            return pid
        # accounted as a "prefill" slice: the prefill PROGRAM runs here,
        # so its wall time must land in the same breakdown kind its
        # executed-flops counters feed — otherwise stats()['breakdown']
        # reports registration FLOPs with zero matching wall time
        t0 = time.perf_counter()
        kc1, vc1, _ = self._prefill(self._params, jnp.asarray(padded),
                                    np.int32(n))
        kc1d = vc1d = None
        if self._draft is not None:  # the draft replays suffixes from its
            # own cached prefix KV, like the target
            kc1d, vc1d = self._draft_feed(self._params_d,
                                          jnp.asarray(padded), np.int32(0),
                                          *self._draft_row())
        self._acc_ms("prefill", t0)
        pid = self._next_pid
        self._next_pid += 1
        self._prefixes[pid] = (ids, kc1, vc1, kc1d, vc1d)
        return pid

    def warmup(self, batch_shapes=None, sampling=True):
        """Compile the engine's whole jitted program family BEFORE traffic,
        from shape specs only — no real prompts, nothing executed, the KV
        cache untouched. With FLAGS_jit_cache_dir set the executables load
        from (or persist into) the on-disk AOT cache, so a fresh server
        process performs zero XLA compiles; without the flag the programs
        are still AOT-compiled in memory (submit/step then pay none).

        batch_shapes: iterable of prompt lengths to warm prefill buckets
        for (bucketed exactly like submit(); default: every configured
        bucket). sampling=False skips the sampling decode step for
        all-greedy deployments. Returns {program: warmed-signature count}.
        """
        import jax
        import jax.numpy as jnp

        def aval(t):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=getattr(a, "sharding", None)),
                t)

        def f32(shape=()):
            return jax.ShapeDtypeStruct(shape, jnp.float32)

        def i32(shape=()):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        counts = {}

        def warm(cj, *specs):
            counts[cj._label] = counts.get(cj._label, 0) + \
                (1 if cj.warm(*specs) else 0)

        B, V = self.B, self.cfg.vocab_size
        p = aval(self._params)
        if self._paged:
            lens = (list(batch_shapes) if batch_shapes is not None
                    else list(self._buckets))
            lora = aval(self._lora)
            kp, vp = aval(self._pool.kp), aval(self._pool.vp)
            tb = i32((B, self._pool.maxb))
            for pb in sorted({self._bucket(int(n)) for n in lens}):
                warm(self._prefill_pg, p, i32((1, pb)), i32(), lora,
                     i32((1,)))
            warm(self._step_greedy_pg, p, kp, vp, tb, i32((B,)),
                 i32((B,)), lora, i32((B,)))
            if sampling:
                warm(self._step_sample_pg, p, kp, vp, tb, i32((B,)),
                     i32((B,)), f32((B,)), i32((B,)), f32((B,)),
                     i32((B,)), lora, i32((B,)))
            warm(self._pick1, f32((V,)), f32(), i32(), f32(), i32(),
                 i32())
            return counts
        kc, vc = aval(self._kc), aval(self._vc)
        kc1, vc1 = jax.eval_shape(lambda: self._prefill_start())
        lg_spec = f32((V,))
        if self._tp_mesh is not None:
            # eval_shape drops out_shardings: re-attach the head-sharded
            # side-cache placement (same every-leaf recipe as the ctor's
            # side_alloc) or the warmed executables would be compiled for
            # unsharded rows and rejected at first admission. The prefill
            # logits likewise arrive mesh-replicated, so pick1's spec
            # must carry that placement too.
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self._tp_mesh, self._cache_spec)
            reshard = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh), t)
            kc1, vc1 = reshard(kc1), reshard(vc1)
            lg_spec = jax.ShapeDtypeStruct(
                (V,), jnp.float32,
                sharding=NamedSharding(self._tp_mesh, P()))
        lens = (list(batch_shapes) if batch_shapes is not None
                else list(self._buckets))
        buckets = sorted({self._bucket(int(n)) for n in lens})
        for pb in buckets:
            warm(self._prefill, p, i32((1, pb)), i32())
        warm(self._step_greedy, p, kc, vc, i32((B,)), i32((B,)))
        if sampling:
            warm(self._step_sample, p, kc, vc, i32((B,)), i32((B,)),
                 f32((B,)), i32((B,)), f32((B,)), i32((B,)))
        warm(self._pick1, lg_spec, f32(), i32(), f32(), i32(), i32())
        # slot index rides as a weakly-typed python int, exactly as the
        # live _activate call passes it
        warm(self._admit, kc, kc1, 0)
        warm(self._copy_cache, kc1)
        if self._chunk is not None:
            warm(self._prefill_chunk, p, i32((1, self._chunk)), i32(),
                 kc1, vc1, i32())
        if self._draft is not None:
            pd = aval(self._params_d)
            kcd, vcd = aval(self._kc_d), aval(self._vc_d)
            kc1d, vc1d = jax.eval_shape(self._draft_row)
            for pb in buckets:
                warm(self._draft_feed, pd, i32((1, pb)), i32(), kc1d, vc1d)
            if self._chunk is not None:
                warm(self._draft_feed, pd, i32((1, self._chunk)), i32(),
                     kc1d, vc1d)
            warm(self._draft_propose, pd, kcd, vcd, i32((B,)), i32((B,)))
            warm(self._verify, p, kc, vc, i32((B,)), i32((B,)),
                 i32((B, self._spec_k)))
            warm(self._draft_sync, pd, kcd, vcd, i32((B,)), i32((B,)))
            # admissions also row-copy into the DRAFT cache (its shapes
            # differ from the target's) and prefix reuse copies draft
            # side caches — warm those signatures too
            warm(self._admit, kcd, kc1d, 0)
            warm(self._copy_cache, kc1d)
        return counts

    def _count_step(self, kind):
        self._m["steps"][kind] = self._m["steps"].get(kind, 0) + 1
        _STEPS.labels(kind=kind).inc()

    def _acc_ms(self, kind, t0):
        """Accumulate one step-kind slice's wall time (host-observed) for
        stats()['breakdown']; returns the elapsed ms."""
        return self._acc_ms_value(kind, (time.perf_counter() - t0) * 1e3)

    def _acc_ms_value(self, kind, ms):
        """Accumulate an already-computed slice (the async step books
        dispatch+fetch windows only — the overlapped admission window is
        booked under its own kinds by _advance_and_admit, and counting
        it twice would make the kinds sum past real wall time)."""
        st = self._m["step_ms"].setdefault(kind, [0, 0.0])
        st[0] += 1
        st[1] += ms
        return ms

    def stats(self):
        """Engine-lifetime observability snapshot: request counts by
        outcome, token totals, step split (prefill/decode/speculative),
        batch-occupancy average, prefix-cache hit rate, speculative
        accept rate, and queue-wait/TTFT/inter-token latency summaries.
        Host-side accounting only — never touches the device. The same
        families stream into paddle_tpu.monitor (serving_* metrics) for
        the snapshot/Prometheus/JSONL exporters."""
        m = self._m
        occ = (m["occupancy_sum"] / m["occupancy_steps"]
               if m["occupancy_steps"] else 0.0)
        prefix_n = m["prefix_hit"] + m["prefix_miss"]
        out = {
            "slots": self.B,
            "requests": {"submitted": m["submitted"],
                         "queued": len(self._queue),
                         "handoff": len(self._handoff),
                         "prefilling": len(self._prefilling),
                         # decoding slots only: mid-prefill slots hold a
                         # _slot_req reservation but belong to "prefilling"
                         "running": sum(1 for s in range(self.B)
                                        if self._slot_req[s] is not None
                                        and s not in self._prefilling),
                         "finished": dict(m["finished"])},
            "tokens_generated": m["tokens"],
            "steps": dict(m["steps"]),
            "batch_occupancy_avg": occ,
            "prefix_cache": {"hit": m["prefix_hit"],
                             "miss": m["prefix_miss"],
                             "hit_rate": (m["prefix_hit"] / prefix_n
                                          if prefix_n else None)},
            "speculative": {"proposed": m["spec_proposed"],
                            "accepted": m["spec_accepted"],
                            "accept_rate": (m["spec_accepted"]
                                            / m["spec_proposed"]
                                            if m["spec_proposed"]
                                            else None)},
            "queue_wait_ms": m["queue_wait_ms"].to_dict(),
            "ttft_ms": m["ttft_ms"].to_dict(),
            "inter_token_ms": m["inter_token_ms"].to_dict(),
            "breakdown": self._breakdown(),
            "health": self.health(),
            # lineage (ISSUE 20): what the engine serves RIGHT NOW;
            # per-request stamps live in each request's stats()
            "weight_version": str(self._weight_version),
        }
        if self._adapter_versions:
            out["adapter_versions"] = {
                n: str(v)
                for n, v in sorted(self._adapter_versions.items())}
        if self._paged:
            pg = self._pool.stats()
            live = sum(1 for r in self._slot_req if r is not None)
            pg["live_sessions"] = live
            # pool bytes actually held per live session vs what the dense
            # engine pins per slot (one full-length row) — the paged-KV
            # memory win in one ratio (gate-asserted ≥ 2x under shared
            # prefixes in tests/test_paging_gate.py)
            pg["kv_bytes_per_session"] = (
                self._pool.bytes_in_use() / live if live else 0.0)
            pg["dense_bytes_per_session"] = (
                self._pool.block_bytes * self._pool.maxb)
            if self._adapters is not None:
                ad = self._adapters.stats()
                ad["loaded_names"] = sorted(self._adapters.loaded())
                pg["adapters"] = ad
            out["paging"] = pg
        return out

    def _kind_programs(self, kind):
        """THIS engine's CachedJit wrappers whose device work the kind's
        wall time covers (speculative = draft proposal + target verify).
        Two draft programs are deliberately unattributed because ONE
        wrapper's cumulative counters feed MORE than one kind and cannot
        be split: draft_sync runs inside both decode kinds' fallback
        steps, and draft_feed inside whole-prompt (prefill), chunked
        (prefill_chunk), AND prefix-registration windows — draft-enabled
        engines therefore understate those kinds' flops by the (small by
        design) draft model's share rather than double-count it."""
        progs = {
            "prefill": [getattr(self, "_prefill", None),
                        getattr(self, "_prefill_pg", None)],
            "prefill_chunk": [getattr(self, "_prefill_chunk", None)],
            "decode_greedy": [getattr(self, "_step_greedy", None),
                              getattr(self, "_step_greedy_pg", None)],
            "decode_sample": [getattr(self, "_step_sample", None),
                              getattr(self, "_step_sample_pg", None)],
            "speculative": [getattr(self, "_draft_propose", None),
                            getattr(self, "_verify", None)],
        }
        return [p for p in progs.get(kind, ())
                if isinstance(p, _aot.CachedJit)]

    def _breakdown(self):
        """Step-time breakdown: host wall time per step kind joined with
        THIS engine's executed device FLOPs (each program wrapper's own
        per-signature accounting — a bucketed prefill family weights
        every bucket's flops, and a second engine in the process cannot
        bleed into this one's numbers). flops fields appear once the
        program family has executables captured — FLAGS_trace=1,
        FLAGS_jit_cache_dir, or warmup() all populate them; without them
        the wall-time split still stands on its own."""
        total_ms = sum(st[1] for st in self._m["step_ms"].values())
        kinds = {}
        flops_total = 0.0
        flops_known = False
        for kind in sorted(self._m["step_ms"]):
            count, ms = self._m["step_ms"][kind]
            row = {"count": count, "wall_ms": ms,
                   "wall_fraction": (ms / total_ms) if total_ms else 0.0}
            wrappers = self._kind_programs(kind)
            ex_calls, ex_flops = 0, 0.0
            for w in wrappers:
                e = w.executed()
                ex_calls = max(ex_calls, e["calls"])
                ex_flops += e["flops"]
            per_call = total = None
            if ex_calls:
                total = ex_flops
                per_call = ex_flops / ex_calls
            else:
                # no execution accounting (e.g. programs ran before any
                # cost capture): fall back to the site-global latest
                # entries under the SAME wrappers' labels, so the two
                # paths agree on what one call covers
                entries = [_costs.get("serving", w._label)
                           for w in wrappers]
                entries = [e for e in entries if e is not None]
                if entries:
                    per_call = sum(e["flops"] for e in entries)
                    total = per_call * count
            if per_call is not None:
                row["flops_per_call"] = per_call
                row["device_flops_total"] = total
                flops_total += total
                flops_known = True
            kinds[kind] = row
        out = {"kinds": kinds, "wall_ms_total": total_ms}
        if self._async_ms is not None:
            # async rounds: how much of the decode wall time was host
            # dispatch vs the overlapped admission window vs the token
            # fetch — the dispatch-vs-sync fraction the async path
            # exists to shrink (docs/PERF.md)
            a = dict(self._async_ms)
            covered = a["dispatch_ms"] + a["overlap_ms"] + a["fetch_ms"]
            a["dispatch_fraction"] = (
                (a["dispatch_ms"] + a["overlap_ms"]) / covered
                if covered else 0.0)
            out["async_overlap"] = a
        if flops_known:
            out["device_flops_total"] = flops_total
            peak = _costs.peak_flops()
            if total_ms > 0 and peak:
                # achieved device FLOP/s over the engine's measured step
                # time, against the chip's peak — the serving-side MFU
                out["device_flops_per_sec"] = flops_total / (total_ms / 1e3)
                out["mfu"] = out["device_flops_per_sec"] / peak
        return out

    def get_request(self, rid):
        """The live Request object for a submitted id — queued, in-flight,
        or finished. The per-request observability surface: read
        output_ids as tokens stream, or req.stats() for queue-wait/TTFT/
        inter-token latencies (engine-level aggregates: stats()). Raises
        KeyError for an unknown id."""
        for req in self._queue:
            if req.rid == rid:
                return req
        for req in self._slot_req:
            if req is not None and req.rid == rid:
                return req
        for entry in self._prefilling.values():
            if entry[0].rid == rid:
                return entry[0]
        for entry in self._handoff:
            if entry[0].rid == rid:
                return entry[0]
        if rid in self._finished:
            return self._finished[rid]
        raise KeyError(f"unknown request id {rid}")

    def unregister_prefix(self, prefix_id):
        """Free a registered prefix's cached KV (each pins a [1, max_seq]
        side cache on device — long-lived engines rotating system prompts
        should release retired ones). In-flight requests that already
        copied it are unaffected; later submits with this id raise."""
        if prefix_id not in self._prefixes:
            raise ValueError(f"unknown prefix_id {prefix_id}")
        if self._paged and self._prefixes[prefix_id][1] == "paged":
            # drop the registry's frame references; frames still mapped by
            # live sessions stay alive until those sessions finish
            self._pool.drop_prefix(prefix_id)
        del self._prefixes[prefix_id]

    # -- multi-LoRA adapter management (FLAGS_paged_kv engines) --------------
    def _require_adapters(self):
        if not self._paged:
            raise RuntimeError(
                "multi-LoRA adapters need FLAGS_paged_kv=1 — the paged "
                "engine owns the adapter registry (docs/SERVING.md)")
        if self._adapters is None:
            raise RuntimeError(
                f"decode model {self._dm.name!r} does not support "
                "multi-LoRA serving (no lora_init), or the engine was "
                "built with max_adapters=0")

    def _resolve_adapter_slot(self, name):
        """Loaded adapter name -> device slot index (None -> 0 = base)."""
        if name is None:
            return 0
        self._require_adapters()
        slot = self._adapters.peek(name)
        if slot is None:
            raise ValueError(
                f"adapter {name!r} is not loaded — load_adapter() it "
                f"first (loaded: {sorted(self._adapters.loaded())})")
        return slot

    def load_adapter(self, name, exported, pin=False):
        """Hot-load one exported LoRA adapter (``incubate.lora.
        export_lora`` form) into a device slot of the stacked multi-LoRA
        factors; returns the slot index. Requests then select it with
        submit(adapter=name) — every loaded adapter decodes batched in
        the SAME jitted step (one gathered einsum per site; no
        per-adapter programs, no recompiles: the write below is a
        same-shape .at[slot].set).

        A full registry evicts the least-recently-used unpinned adapter;
        its in-flight sessions restart from the queue head and complete
        bit-identically once their adapter returns (greedy/seeded decode
        is deterministic — chaos-pinned by tools/chaos_check.py
        adapter_evict_under_load). pin=True exempts this adapter from
        LRU eviction; loading raises RuntimeError while every slot is
        pinned, ValueError for a malformed/duplicate adapter (a bad
        adapter never evicts a healthy one)."""
        self._require_adapters()
        if not isinstance(name, str) or not name:
            raise ValueError(
                f"adapter name must be a non-empty str, got {name!r}")
        if self._adapters.peek(name) is not None:
            raise ValueError(f"adapter {name!r} is already loaded")
        _fp.failpoint("serving/adapter")
        # pack BEFORE claiming a slot: packing validates rank/shape/layer
        # coverage, and a malformed adapter must leave the registry and
        # the device factors exactly as they were
        packed = self._dm.lora_pack(self.cfg, exported, self._lora_rank)
        slot, evicted = self._adapters.admit(name, pin=pin)
        if evicted is not None:
            self._restart_adapter_sessions(evicted)
            self._adapter_versions.pop(evicted, None)
        self._write_adapter_slot(slot, packed)
        # lineage stamp (ISSUE 20): which base-weight version this
        # adapter's factors were loaded under, origin adapter_load —
        # completions submitted with adapter=name carry it
        self._adapter_versions[name] = _lineage.WeightVersion(
            self._weight_version.run_id, self._weight_version.counter,
            "adapter_load")
        return slot

    def evict_adapter(self, name):
        """Explicitly evict a loaded adapter: its device slot zeroes and
        its in-flight sessions are reset and requeued at the head (they
        wait there — _AdapterUnavailable backpressure — and regenerate
        bit-identically once the adapter is loaded again). Returns the
        freed slot index; KeyError for an unknown name."""
        self._require_adapters()
        _fp.failpoint("serving/adapter")
        slot = self._adapters.evict(name)
        self._write_adapter_slot(slot, None)
        self._restart_adapter_sessions(name)
        self._adapter_versions.pop(name, None)
        return slot

    def _write_adapter_slot(self, slot, packed):
        """Write (packed) or zero (None) ONE slot of the stacked device
        factors — same-shape .at[slot].set updates only, so the decode
        programs never re-trace."""
        import jax.numpy as jnp

        lora = dict(self._lora)
        scale = 0.0 if packed is None else float(packed["scale"])
        lora["scale"] = lora["scale"].at[slot].set(scale)
        for kind in self._lora:
            if kind == "scale":
                continue
            fac = dict(self._lora[kind])
            for side in ("A", "B"):
                new = 0.0 if packed is None else jnp.asarray(
                    packed[kind][side], fac[side].dtype)
                fac[side] = fac[side].at[slot].set(new)
            lora[kind] = fac
        self._lora = lora

    def _restart_adapter_sessions(self, name):
        """An evicted adapter's in-flight sessions cannot keep decoding
        (their slot's factors just zeroed): free each session's blocks,
        reset it to its pre-admission state, and requeue it at the head.
        Deterministic decode (greedy, or the per-request seeded PRNG
        stream) regenerates the SAME tokens on re-admission, so an evict
        + reload mid-stream is invisible in the output."""
        for s in range(self.B):
            req = self._slot_req[s]
            if req is None or req.adapter != name:
                continue
            self._pool.free_slot(s)
            self._slot_req[s] = None
            self._prefilling.pop(s, None)
            self._adapter_slot[s] = 0
            req.output_ids.clear()
            req.first_token_time = None
            req.last_token_time = None
            req._inter_token = _MsSummary()
            self._queue.insert(0, req)

    def hot_swap(self, model, decode_model=None):
        """Replace the served weights IN PLACE with `model`'s — same
        architecture, same shapes/dtypes — without recompiling or
        dropping sessions, and bump the engine's weight version (origin
        ``hot_swap``). The params are step ARGUMENTS, not closure
        captures, so identically-shaped replacements reuse every warmed
        executable.

        Sessions already in flight keep decoding — each finishes under
        the replacement weights but CARRIES its submission-time version
        stamp, so its completion is attributable to the lineage it
        started on (and counts ``serving_stale_sessions_total`` under
        FLAGS_goodput). Requests submitted after the swap carry the
        bumped version. Returns the new :class:`WeightVersion`.

        Rejects tensor-parallel engines (the Megatron re-split would
        re-place device state mid-flight) and any replacement whose
        extracted param tree differs in keys, shapes, or dtypes — a
        mismatched swap must fail loudly BEFORE touching served state."""
        import jax.numpy as jnp

        if self._tp_mesh is not None:
            raise ValueError(
                "hot_swap does not compose with tp_mesh= serving — "
                "restart the engine to replace tensor-parallel weights")
        dm = _dm_registry.resolve(model, decode_model)
        if type(dm) is not type(self._dm):
            raise ValueError(
                f"hot_swap: replacement model resolves to decode adapter "
                f"{type(dm).__name__}, engine serves "
                f"{type(self._dm).__name__}")
        dm.check_config(model.cfg)
        params, _ = dm.extract_params(model, "the replacement model")
        if self._compute_dtype is not None:
            params = {k: (v.astype(self._compute_dtype)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}
        if set(params) != set(self._params):
            missing = sorted(set(self._params) - set(params))
            extra = sorted(set(params) - set(self._params))
            raise ValueError(
                f"hot_swap: param tree mismatch (missing {missing[:3]}, "
                f"unexpected {extra[:3]}) — the replacement must be the "
                "same architecture")
        for k in sorted(params):
            new, cur = params[k], self._params[k]
            if tuple(new.shape) != tuple(cur.shape) \
                    or new.dtype != cur.dtype:
                raise ValueError(
                    f"hot_swap: param {k!r} is {new.shape}/{new.dtype}, "
                    f"engine serves {cur.shape}/{cur.dtype} — shapes and "
                    "dtypes must match exactly (no recompiles)")
        self._params = params
        self._weight_version = self._weight_version.bump("hot_swap")
        if self._goodput is not None:
            self._goodput.note_serving_version(
                self._weight_version.counter)
        _blackbox.note("hot_swap",
                       version=str(self._weight_version))
        return self._weight_version

    def _validate_decode_args(self, ids, max_new_tokens, temperature,
                              deadline_ms, top_k, top_p, seed):
        """Shared submit()/admit_prefilled() argument validation; returns
        the int-converted seed (None stays None)."""
        if max_new_tokens < 1:   # generate()'s own validation, mirrored
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if deadline_ms is not None and not deadline_ms > 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if seed is not None:
            # fail HERE, not at admission steps later: the PRNG fold takes
            # an int32 (mask a 64-bit time/hash seed yourself if desired)
            seed = int(seed)
            if not -2**31 <= seed < 2**31:
                raise ValueError(
                    f"seed must fit int32, got {seed} (mask with "
                    "& 0x7FFFFFFF for hash/time-derived seeds)")
        if len(ids) == 0:
            raise ValueError("empty prompt")
        return seed

    def _new_request(self, ids, max_new_tokens, temperature, top_k, top_p,
                     seed, prefix_id, prefix_len, deadline_ms, priority,
                     trace_id=None, parent_span=None, adapter=None):
        """Accepted-request factory shared by submit()/admit_prefilled():
        mints the rid, stamps submit_time, opens the trace spans (a
        router/pool passes its own trace_id — and optionally its routing
        span as parent — so one request's spans thread
        router -> engine -> slot), and counts the submission."""
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, ids, max_new_tokens,
                      temperature=temperature, top_k=top_k,
                      top_p=top_p, seed=seed, prefix_id=prefix_id,
                      prefix_len=prefix_len, deadline_ms=deadline_ms,
                      priority=priority, adapter=adapter)
        req.submit_time = time.perf_counter()
        # lineage stamp (ISSUE 20): the version of the weights (and of
        # the selected adapter) this session will decode under — read at
        # finish to detect sessions that outlived a hot_swap
        req.weight_version = self._weight_version
        if adapter is not None:
            req.adapter_version = self._adapter_versions.get(adapter)
        if _trace.is_enabled():
            # end-to-end trace: every request gets a trace_id here; all
            # later spans (queue-wait, prefill chunks, per-step decode,
            # speculative, finish) parent back to this root span
            req.trace_id = trace_id or _trace.new_trace_id()
            req._span = _trace.start_span(
                "request", subsystem="serving", trace_id=req.trace_id,
                parent=parent_span, rid=rid, prompt_tokens=int(len(ids)),
                prefix_tokens=prefix_len, priority=priority)
            req._qspan = _trace.start_span(
                "queue_wait", subsystem="serving", parent=req._span)
        if deadline_ms is not None:
            self._deadline_live += 1
        self._m["submitted"] += 1
        _REQ_SUBMITTED.inc()
        return req

    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               top_k=None, top_p=None, seed=None, prefix_id=None,
               deadline_ms=None, priority=0, trace_id=None,
               parent_span=None, adapter=None):
        """Queue a prompt; returns the request id. temperature=0 (default)
        decodes greedy; temperature>0 samples (optionally top_k- and/or
        top_p/nucleus-truncated, same semantics as generate()) with a
        per-request deterministic PRNG stream (seed defaults to the
        request id).

        deadline_ms: wall-clock budget from submit; an overdue request is
        finished with reason="deadline" at the next step() (batch-mates
        are untouched). priority: higher values outrank on a FULL bounded
        queue (max_queue=): the lowest-priority queued request is shed
        (reason="shed") to admit a strictly-higher-priority arrival;
        otherwise submit raises QueueFullError.

        trace_id/parent_span: a fronting Router propagates its per-request
        trace id (and its routing span) so the engine's spans join the
        router's trace instead of minting a fresh one.

        adapter: name of a LOADED LoRA adapter (FLAGS_paged_kv engines,
        load_adapter()); its low-rank delta applies to this request only,
        batched with every other adapter's requests in the same decode
        step — outputs are byte-identical to a dedicated engine serving
        the merged adapter. None = base weights."""
        if self._draining:
            raise RuntimeError(
                "ServingEngine is draining — not accepting new requests "
                "(in-flight work runs to completion; see drain())")
        if adapter is not None:
            self._require_adapters()
            if self._adapters.lookup(adapter) is None:
                raise ValueError(
                    f"adapter {adapter!r} is not loaded — load_adapter() "
                    f"it first (loaded: "
                    f"{sorted(self._adapters.loaded())})")
        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        seed = self._validate_decode_args(ids, max_new_tokens, temperature,
                                          deadline_ms, top_k, top_p, seed)
        prefix_len = 0
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(f"unknown prefix_id {prefix_id}")
            prefix_ids = self._prefixes[prefix_id][0]
            prefix_len = len(prefix_ids)
            # the request's logical prompt = prefix + suffix; only the
            # suffix will be prefilled (from the cached prefix KV)
            ids = np.concatenate([prefix_ids, ids])
        if len(ids) + 1 > self.T:
            raise ValueError(
                f"prompt ({len(ids)}) too long for max_seq_len {self.T}")
        if self._paged:
            # reject requests that can NEVER fit the pool up front: the
            # whole-budget reservation (reserve-before-compute) would
            # otherwise raise PagePoolFullError at every admission attempt
            # and the request would requeue forever
            need = self._pool.blocks_for(
                min(self.T, len(ids) + int(max_new_tokens)))
            cap = self._pool.stats()["n_blocks"] - 1   # frame 0 = null
            if need > cap:
                raise ValueError(
                    f"request needs {need} KV blocks but the page pool "
                    f"only has {cap}; raise page_blocks or shorten the "
                    "request")
        priority = int(priority)
        if self._max_queue is not None and \
                len(self._queue) + len(self._handoff) >= self._max_queue:
            # the bound covers BOTH admission backlogs (queue + prefilled
            # handoff rows) — matching admit_prefilled and health().
            # Shed the lowest-priority queued request (newest among ties —
            # it has the least sunk wait) iff the arrival strictly
            # outranks it; handoff rows are never shed (their prefill is
            # already paid); otherwise reject the arrival
            victim_idx = None
            for i, r in enumerate(self._queue):
                if victim_idx is None \
                        or r.priority <= self._queue[victim_idx].priority:
                    victim_idx = i
            if victim_idx is not None \
                    and self._queue[victim_idx].priority < priority:
                victim = self._queue.pop(victim_idx)
                self._finish_req(victim, "shed")
                _SHED.labels(reason="preempted").inc()
            else:
                _SHED.labels(reason="queue_full").inc()
                raise QueueFullError(
                    f"admission queue full ({len(self._queue)} queued "
                    f"+ {len(self._handoff)} handoff / {self._max_queue});"
                    " request rejected — retry later or submit with a "
                    "higher priority")
        req = self._new_request(ids, max_new_tokens, temperature, top_k,
                                top_p, seed, prefix_id, prefix_len,
                                deadline_ms, priority, trace_id=trace_id,
                                parent_span=parent_span, adapter=adapter)
        self._queue.append(req)
        return req.rid

    def admit_prefilled(self, prompt_ids, kv_row, logits,
                        max_new_tokens=32, temperature=0.0, top_k=None,
                        top_p=None, seed=None, deadline_ms=None,
                        priority=0, trace_id=None, parent_span=None):
        """Disaggregated prefill->decode handoff (docs/SERVING.md): admit
        a request whose prompt KV was ALREADY prefilled elsewhere.

        ``kv_row`` is the (kc1, vc1) single-row cache pair matching this
        engine's DecodeModel cache spec — i.e. produced by a
        ``serving.PrefillWorker`` (or another engine) built from the SAME
        adapter, config, dtype and cache_dtype. ``logits`` is the
        prompt's last-position vocab logits [V] (f32). The row waits in
        the handoff queue until a slot frees, then the standard admission
        tail runs: row copy into the big cache + first token through the
        same pick program submit()'s own prefill uses — outputs are
        bit-identical to submitting the prompt to this engine directly
        (pinned by tests/test_serving_disagg.py).

        Returns the request id. Raises while draining; a bounded engine
        (max_queue=) rejects with QueueFullError when queue + handoff
        backlogs are at the bound (no priority shedding across handoff
        rows — the producer should back off or pick another engine);
        speculative engines (draft_model=) do not compose with handoff
        (the draft's side cache was never prefilled)."""
        if self._draining:
            raise RuntimeError(
                "ServingEngine is draining — not accepting new requests "
                "(in-flight work runs to completion; see drain())")
        if self._draft is not None:
            raise RuntimeError(
                "admit_prefilled does not compose with speculative "
                "decoding (draft_model=): the handoff row carries no "
                "draft-model KV — disaggregate with a plain engine")
        if self._paged:
            raise RuntimeError(
                "admit_prefilled does not compose with FLAGS_paged_kv: "
                "the handoff row targets the dense big cache, a paged "
                "engine re-blocks prompts locally — disaggregate with "
                "dense decode engines")
        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        seed = self._validate_decode_args(ids, max_new_tokens, temperature,
                                          deadline_ms, top_k, top_p, seed)
        if len(ids) + 1 > self.T:
            raise ValueError(
                f"prompt ({len(ids)}) too long for max_seq_len {self.T}")
        # typed transfer edge (ISSUE 13, docs/ANALYSIS.md): the row must
        # match the disagg_kv HANDOFF_SCHEMA — the SAME literal the
        # static auditor extracts and baselines — with symbolic dims
        # bound to THIS engine's cache. A drifted/misshaped row raises
        # here, naming the offending leaf, instead of corrupting a slot.
        kc1, vc1 = kv_row
        from ..analysis import handoff_schema as _hs
        from ..serving.disagg import HANDOFF_SCHEMA

        side = self._kc[0] if isinstance(self._kc, tuple) else self._kc
        dims = {}
        if getattr(side, "ndim", 0) == 5:
            L, _, KVh, T, hd = side.shape
            dims = {"L": int(L), "KVh": int(KVh), "T": int(T),
                    "hd": int(hd)}
        vocab = getattr(self.cfg, "vocab_size", None)
        if vocab:
            dims["V"] = int(vocab)
        _hs.validate(HANDOFF_SCHEMA,
                     {"kc": kc1, "vc": vc1, "logits": logits},
                     dims=dims, dtypes={"cache": str(side.dtype)})
        # the bound check runs AFTER validation (matching submit()): an
        # unservable request must fail permanently (ValueError), never
        # masquerade as retryable backpressure
        if self._max_queue is not None \
                and len(self._queue) + len(self._handoff) >= self._max_queue:
            _SHED.labels(reason="queue_full").inc()
            raise QueueFullError(
                f"admission queue full ({len(self._queue)} queued + "
                f"{len(self._handoff)} handoff / {self._max_queue}); "
                "handoff rejected — back off or target another engine")
        req = self._new_request(ids, max_new_tokens, temperature, top_k,
                                top_p, seed, None, 0, deadline_ms,
                                int(priority), trace_id=trace_id,
                                parent_span=parent_span)
        self._handoff.append([req, kc1, vc1, logits])
        return req.rid

    def _bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return self.T

    def _finish_req(self, req, reason, slot=None):
        """Terminal transition for a request wherever it lives: stamps the
        outcome, records it, and (slot given) frees the slot + any
        in-flight prefill reservation. Freed rows need no scrubbing — the
        next admission's row copy overwrites them (the invariant the whole
        engine rides on)."""
        req.finished = True
        req.finish_reason = reason
        req.finish_time = time.perf_counter()
        if req._qspan is not None:   # finished while still queued
            req._qspan.end()
            req._qspan = None
        if req._span is not None:
            req._span.end(finish_reason=reason,
                          new_tokens=len(req.output_ids))
            req._span = None
        if req.deadline_ms is not None:
            self._deadline_live -= 1
        self._m["finished"][reason] = self._m["finished"].get(reason, 0) + 1
        _REQ_FINISHED.labels(reason=reason).inc()
        if (self._goodput is not None and req.weight_version is not None
                and req.weight_version.counter
                < self._weight_version.counter):
            # the session finished under weights older than what the
            # engine now serves (a hot_swap landed mid-stream) — exactly
            # once per stale finish (FLAGS_goodput, ISSUE 20)
            self._goodput.note_stale_session()
        self._finished[req.rid] = req
        if slot is not None:
            self._slot_req[slot] = None
            self._prefilling.pop(slot, None)
            if self._paged:
                # return the session's frames (shared prefix frames only
                # deref); no-op for a slot that never reserved
                self._pool.free_slot(slot)
                self._adapter_slot[slot] = 0

    def _finish(self, slot, reason):
        self._finish_req(self._slot_req[slot], reason, slot=slot)

    def _note_error(self):
        self._last_error_step = self._step_no

    def cancel(self, rid):
        """Cancel a queued or in-flight request: it is finished immediately
        with reason="cancelled" and its slot (if any) freed for the next
        admission. Returns True if cancelled, False if the request had
        already finished; raises KeyError for an unknown id."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                self._queue.pop(i)
                self._finish_req(req, "cancelled")
                return True
        for slot, entry in list(self._prefilling.items()):
            if entry[0].rid == rid:
                self._finish_req(entry[0], "cancelled", slot=slot)
                return True
        for entry in list(self._handoff):
            if entry[0].rid == rid:
                self._handoff.remove(entry)
                self._finish_req(entry[0], "cancelled")
                return True
        for slot in range(self.B):
            req = self._slot_req[slot]
            if req is not None and req.rid == rid:
                self._finish_req(req, "cancelled", slot=slot)
                return True
        if rid in self._finished:
            return False
        raise KeyError(f"unknown request id {rid}")

    def drain(self, stop=True):
        """Graceful-shutdown valve: stop admitting new requests (submit()
        raises) while queued and in-flight work runs to completion via
        step()/run_until_complete(). health() reports "draining" until
        drain(False) re-opens admission."""
        self._draining = bool(stop)

    def health(self):
        """Liveness verdict for load balancers: state is "draining" after
        drain(), "degraded" when a request finished with reason="error" in
        the last 100 steps or the bounded queue is at >= 80% depth, else
        "ok". Also wired into stats()["health"]. queue_depth counts BOTH
        admission backlogs — the regular queue and the prefilled-handoff
        queue — so a disaggregated decode engine can't look idle while
        holding a deep handoff backlog."""
        depth = len(self._queue) + len(self._handoff)
        state = "ok"
        if self._draining:
            state = "draining"
        else:
            recent_error = (self._last_error_step is not None
                            and self._step_no - self._last_error_step <= 100)
            q_pressure = (self._max_queue is not None and depth
                          >= max(1, int(0.8 * self._max_queue)))
            if recent_error or q_pressure:
                state = "degraded"
        return {"state": state,
                "queue_depth": depth,
                "queue_limit": self._max_queue,
                "active_slots": sum(1 for r in self._slot_req
                                    if r is not None),
                "errors": self._m["finished"].get("error", 0),
                "steps": self._step_no}

    def _expire_deadlines(self):
        """Finish every overdue request (reason="deadline") wherever it
        lives — queue, mid-prefill, or an active slot. Batch-mates are
        untouched: a freed slot is just another don't-care row until the
        next admission overwrites it."""
        if not self._deadline_live:
            return   # nothing carries a deadline: keep step() O(1) here
        now = time.perf_counter()

        def overdue(req):
            return (req.deadline_ms is not None
                    and (now - req.submit_time) * 1e3 > req.deadline_ms)

        for req in [r for r in self._queue if overdue(r)]:
            self._queue.remove(req)
            self._finish_req(req, "deadline")
            _DEADLINE.inc()
        for entry in [e for e in self._handoff if overdue(e[0])]:
            self._handoff.remove(entry)
            self._finish_req(entry[0], "deadline")
            _DEADLINE.inc()
        for slot, entry in list(self._prefilling.items()):
            if overdue(entry[0]):
                self._finish_req(entry[0], "deadline", slot=slot)
                _DEADLINE.inc()
        for slot in range(self.B):
            req = self._slot_req[slot]
            if req is not None and slot not in self._prefilling \
                    and overdue(req):
                self._finish_req(req, "deadline", slot=slot)
                _DEADLINE.inc()

    def _activate(self, slot, req, kc1, vc1, logits, draft_caches=None):
        """Shared admission tail: copy the side cache(s) into the slot's
        row and emit the first generated token through the standard pick."""
        n = len(req.prompt_ids)
        if self._paged:
            # HANDOFF_SCHEMA "kv_page_admit" producer site: the prefilled
            # dense row re-blocks into the slot's reserved PRIVATE frames
            # (shared prefix frames stay untouched — admit_row writes only
            # past the shared span)
            self._pool.admit_row(slot, kc1[:, 0], vc1[:, 0])
            self._adapter_slot[slot] = self._resolve_adapter_slot(
                req.adapter)
        else:
            self._kc = self._admit(self._kc, kc1, slot)
            self._vc = self._admit(self._vc, vc1, slot)
        if draft_caches is not None:
            kc1d, vc1d = draft_caches
            self._kc_d = self._admit(self._kc_d, kc1d, slot)
            self._vc_d = self._admit(self._vc_d, vc1d, slot)
        temp = np.float32(req.temperature)
        topk = np.int32(req.top_k or self.cfg.vocab_size)
        topp = np.float32(1.0 if req.top_p is None else req.top_p)
        seed = np.int32(req.seed)
        # fold value = index of the context's last token (n-1), matching
        # the decode step's schedule (each emission folds a unique value)
        tok = int(self._pick1(logits, temp, topk, topp, seed,
                              np.int32(n - 1)))
        self._slot_req[slot] = req
        self._pos[slot] = n
        self._last[slot] = tok
        self._temps[slot] = temp
        self._topk[slot] = topk
        self._topp[slot] = topp
        self._seeds[slot] = seed
        req.output_ids.append(tok)
        self._after_emit(slot, req)

    def _note_admission(self, req):
        """Queue wait ends when admission work starts (prefill or slot
        reservation); prefix hit/miss is counted at the branch that
        actually decides reuse (_admit_one)."""
        req.admit_time = time.perf_counter()
        wait_ms = (req.admit_time - req.submit_time) * 1e3 \
            if req.submit_time is not None else 0.0
        self._m["queue_wait_ms"].add(wait_ms)
        _QUEUE_WAIT_MS.observe(wait_ms)
        if req._qspan is not None:
            req._qspan.end(wait_ms=wait_ms)
            req._qspan = None

    def _admit_one(self, slot, req):
        with _blackbox.progress("serving/admit"):
            self._admit_one_inner(slot, req)

    def _admit_one_inner(self, slot, req):
        import jax.numpy as jnp

        if self._paged:
            return self._admit_one_paged(slot, req)
        prefix_len = req.prefix_len
        n = len(req.prompt_ids)
        if prefix_len and req.prefix_id not in self._prefixes:
            # prefix unregistered while this request sat in the queue: the
            # combined prompt is already in prompt_ids — whole-prefill it
            prefix_len = 0
        self._note_admission(req)
        if prefix_len:
            # suffix-only prefill from a COPY of the cached prefix KV
            # (the chunk program donates its cache args); chunk width =
            # the engine's prefill_chunk or a default for prefix users
            C = self._chunk or min(64, self.T)
            end = prefix_len + -(-(n - prefix_len) // C) * C
            if end <= self.T:
                self._m["prefix_hit"] += 1
                _PREFIX.labels(event="hit").inc()
                sp = None if req._span is None else _trace.start_span(
                    "admit", subsystem="serving", parent=req._span,
                    slot=slot, prefix="hit", prefix_tokens=prefix_len)
                try:
                    _, kc_p, vc_p, kc_pd, vc_pd = \
                        self._prefixes[req.prefix_id]
                    kc1 = self._copy_cache(kc_p)
                    vc1 = self._copy_cache(vc_p)
                    kc1d = vc1d = None
                    if self._draft is not None:
                        kc1d = self._copy_cache(kc_pd)
                        vc1d = self._copy_cache(vc_pd)
                    self._slot_req[slot] = req
                    self._prefilling[slot] = [req, kc1, vc1, prefix_len, C,
                                              kc1d, vc1d]
                except BaseException:
                    if sp is not None:
                        sp.end(error=True)
                    raise
                if sp is not None:
                    sp.end()
                return
            # else: fall through to whole-prompt prefill (recomputes the
            # prefix — slower but correct near the capacity edge)
        if req.prefix_len:   # wanted prefix reuse, got a full recompute
            self._m["prefix_miss"] += 1
            _PREFIX.labels(event="miss").inc()
        n_chunks_end = 0 if self._chunk is None else \
            -(-n // self._chunk) * self._chunk
        if self._chunk is not None and n_chunks_end <= self.T:
            # chunked admission: reserve the slot, consume the prompt one
            # chunk per step() so active decodes run in between
            self._slot_req[slot] = req
            kc1d = vc1d = None
            if self._draft is not None:
                kc1d, vc1d = self._draft_row()
            self._prefilling[slot] = [req, *self._prefill_start(), 0,
                                      self._chunk, kc1d, vc1d]
            return
        # whole-prompt (bucketed) prefill — also the fallback when the
        # chunk schedule's fixed-width final write would cross max_seq_len
        # (dynamic_update_slice CLAMPS out-of-range starts, which would
        # silently shift tokens onto valid prefix columns)
        pb = self._bucket(n)
        t0 = time.perf_counter()
        sp = None if req._span is None else _trace.start_span(
            "prefill", subsystem="serving", parent=req._span, slot=slot,
            tokens=n, bucket=pb)
        try:
            padded = np.zeros((1, pb), np.int32)
            padded[0, :n] = req.prompt_ids
            kc1, vc1, logits = self._prefill(self._params,
                                             jnp.asarray(padded),
                                             np.int32(n))
            draft_caches = None
            if self._draft is not None:
                draft_caches = self._draft_feed(self._params_d,
                                                jnp.asarray(padded),
                                                np.int32(0),
                                                *self._draft_row())
            self._activate(slot, req, kc1, vc1, logits,
                           draft_caches=draft_caches)
        except BaseException:
            # the failing admission's span must still be recorded (the
            # request itself is finished reason="error" by step())
            if sp is not None:
                sp.end(error=True)
            raise
        self._acc_ms("prefill", t0)
        if sp is not None:
            sp.end()

    def _admit_one_paged(self, slot, req):
        """Paged admission: reserve the session's WHOLE block budget
        FIRST — a pool that cannot cover it raises PagePoolFullError
        here, before any prefill compute runs or any state mutates
        (_advance_and_admit turns that into requeue-at-head
        backpressure). A registered prefix under the SAME adapter maps
        its full blocks shared (refcount++, zero new bytes); a partial
        boundary block is re-blocked private (copy-on-write). Then one
        whole-prompt prefill (the request's adapter delta applied) and
        _activate re-blocks the row into the reserved private frames."""
        import jax.numpy as jnp

        aid = 0
        if req.adapter is not None:
            aid = None if self._adapters is None \
                else self._adapters.peek(req.adapter)
            if aid is None:
                raise _AdapterUnavailable(
                    f"adapter {req.adapter!r} is not loaded (evicted "
                    "mid-flight?) — the request waits at the queue head "
                    "for a reload")
        n = len(req.prompt_ids)
        shared, cow = (), False
        prefix_len = req.prefix_len
        entry = None
        if prefix_len and req.prefix_id in self._prefixes:
            entry = self._prefixes[req.prefix_id]
            if not (entry[1] == "paged" and entry[2] == req.adapter):
                entry = None   # foreign-adapter prefix: full recompute
        if entry is not None:
            # may raise PagePoolFullError while re-admitting cold pages —
            # before reserve(), so backpressure stays mutation-free
            frames = self._pool.prefix_frames(req.prefix_id)
            if frames:
                shared = frames
                cow = prefix_len % self._pool.bs != 0
        self._pool.reserve(slot, min(self.T, n + req.max_new_tokens),
                           shared_frames=shared, cow=cow)
        if prefix_len:   # counted only once reservation succeeds — a
            # backpressure retry must not inflate the hit rate
            ev = "hit" if shared else "miss"
            self._m[f"prefix_{ev}"] += 1
            _PREFIX.labels(event=ev).inc()
        self._note_admission(req)
        pb = self._bucket(n)
        t0 = time.perf_counter()
        sp = None if req._span is None else _trace.start_span(
            "prefill", subsystem="serving", parent=req._span, slot=slot,
            tokens=n, bucket=pb, paged=True)
        try:
            padded = np.zeros((1, pb), np.int32)
            padded[0, :n] = req.prompt_ids
            kc1, vc1, logits = self._prefill_pg(
                self._params, jnp.asarray(padded), np.int32(n),
                self._lora, jnp.asarray([aid], np.int32))
            self._activate(slot, req, kc1, vc1, logits)
        except BaseException:
            if sp is not None:
                sp.end(error=True)
            raise
        self._acc_ms("prefill", t0)
        if sp is not None:
            sp.end()

    def _note_occupancy(self, active):
        self._m["occupancy_sum"] += len(active)
        self._m["occupancy_steps"] += 1
        _OCCUPANCY.set(len(active))
        _trace.add_counter_sample("serving_batch_occupancy", len(active))

    def _dispatch_decode(self, active):
        """Enqueue ONE decode program for the active slots (device work
        starts immediately — jax dispatch is asynchronous). Host-side
        dispatch: an all-greedy batch keeps the lean argmax step (no
        sort/categorical in its compiled program at all); inactive slots
        ride along harmlessly — their rows are don't-care (freed) and
        re-prefilled on admission. Returns (device tokens, kind)."""
        import jax.numpy as jnp

        if self._paged:
            # block tables + adapter ids ride to the device each round
            # (tiny int32 [B, maxb]/[B] uploads); the pool sides donate
            # through the step like the dense big cache
            pool = self._pool
            tables = pool.tables_device()
            aids = jnp.asarray(self._adapter_slot)
            if any(self._temps[s] > 0 for s in active):
                kind = "decode_sample"
                next_toks, pool.kp, pool.vp = self._step_sample_pg(
                    self._params, pool.kp, pool.vp, tables,
                    jnp.asarray(self._last), jnp.asarray(self._pos),
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), jnp.asarray(self._seeds),
                    self._lora, aids)
            else:
                kind = "decode_greedy"
                next_toks, pool.kp, pool.vp = self._step_greedy_pg(
                    self._params, pool.kp, pool.vp, tables,
                    jnp.asarray(self._last), jnp.asarray(self._pos),
                    self._lora, aids)
            self._count_step(kind)
            return next_toks, kind
        if any(self._temps[s] > 0 for s in active):
            kind = "decode_sample"
            next_toks, self._kc, self._vc = self._step_sample(
                self._params, self._kc, self._vc,
                jnp.asarray(self._last), jnp.asarray(self._pos),
                jnp.asarray(self._temps), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._seeds))
        else:
            kind = "decode_greedy"
            next_toks, self._kc, self._vc = self._step_greedy(
                self._params, self._kc, self._vc,
                jnp.asarray(self._last), jnp.asarray(self._pos))
        self._count_step(kind)
        return next_toks, kind

    def _apply_decode(self, active, next_toks, kind, t0_ns, t1_ns):
        """Emit one fetched round's tokens slot by slot. Per-slot
        failures isolate (the failing request finishes reason="error");
        the slot-level decode span attributes the batched device step's
        window to each request."""
        for s in active:
            req = self._slot_req[s]
            try:
                _fp.failpoint("serving/slot")
                self._pos[s] += 1
                self._last[s] = next_toks[s]
                req.output_ids.append(int(next_toks[s]))
                if req._span is not None:
                    _trace.emit("decode", t0_ns, t1_ns,
                                subsystem="serving", parent=req._span,
                                slot=s, pos=int(self._pos[s]),
                                kind=kind, token=int(next_toks[s]))
                self._after_emit(s, req)
            except Exception:
                if self._slot_req[s] is not None:
                    self._finish_req(req, "error", slot=s)
                self._note_error()

    def _advance_and_admit(self):
        """The round's admission window, shared by the sync and async
        steps: advance every in-flight chunked prefill ONE chunk (so
        active decodes never wait for a whole long prefill), then admit
        queued/handoff requests into free slots. Per-request failures
        isolate: the failing request finishes reason="error" and the
        pass continues."""
        for slot in list(self._prefilling):
            req = self._prefilling[slot][0]
            try:
                self._advance_prefill(slot)
            except Exception:
                self._finish_req(req, "error", slot=slot)
                self._note_error()
        for slot in range(self.B):
            # while, not if: a request finishing DURING admission (eos on
            # its prefill token / max_new_tokens=1) frees the slot for the
            # next queued request in the same pass. Handoff rows admit
            # FIRST — their prefill is already paid, holding them behind
            # un-prefilled queue entries would waste the disaggregation
            while self._slot_req[slot] is None and (self._handoff
                                                    or self._queue):
                if self._handoff:
                    req, kc1, vc1, logits = self._handoff.pop(0)
                    try:
                        with _blackbox.progress("serving/admit"):
                            self._note_admission(req)
                            t0 = time.perf_counter()
                            self._activate(slot, req, kc1, vc1, logits)
                            self._acc_ms("handoff_admit", t0)
                    except Exception:
                        self._finish_req(req, "error", slot=slot)
                        self._note_error()
                        continue
                else:
                    req = self._queue.pop(0)
                    try:
                        self._admit_one(slot, req)
                    except Exception as e:
                        if self._paged and isinstance(
                                e, (self._paging.PagePoolFullError,
                                    _AdapterUnavailable)):
                            # admission BACKPRESSURE, not a failure: the
                            # pool cannot cover the request's whole block
                            # budget (or its adapter was evicted and not
                            # yet reloaded). Nothing ran and nothing was
                            # reserved — requeue at the head and stop
                            # admitting this round; finishing sessions
                            # free blocks for the retry
                            self._queue.insert(0, req)
                            return
                        # half-done admission must not leak a reservation
                        self._finish_req(req, "error", slot=slot)
                        self._note_error()
                        continue
                if self._slot_req[slot] is not None:
                    break

    def _advance_prefill(self, slot):
        """Consume one chunk of a reserved slot's prompt; on the final
        chunk, activate the slot."""
        import jax.numpy as jnp

        req, kc1, vc1, off, C, kc1d, vc1d = self._prefilling[slot]
        self._count_step("prefill_chunk")
        t0 = time.perf_counter()
        sp = None if req._span is None else _trace.start_span(
            "prefill_chunk", subsystem="serving", parent=req._span,
            slot=slot, offset=off, width=C)
        n = len(req.prompt_ids)
        end = min(off + C, n)
        try:
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :end - off] = req.prompt_ids[off:end]
            kc1, vc1, logits = self._prefill_chunk(
                self._params, jnp.asarray(chunk), np.int32(off), kc1, vc1,
                np.int32(end - off - 1))
            if self._draft is not None:
                kc1d, vc1d = self._draft_feed(self._params_d,
                                              jnp.asarray(chunk),
                                              np.int32(off), kc1d, vc1d)
            if end >= n:
                del self._prefilling[slot]
                self._slot_req[slot] = None   # _activate re-binds
                self._activate(slot, req, kc1, vc1, logits,
                               draft_caches=(None if self._draft is None
                                             else (kc1d, vc1d)))
            else:
                self._prefilling[slot] = [req, kc1, vc1, end, C, kc1d,
                                          vc1d]
        except BaseException:
            if sp is not None:   # record the failing chunk's span too
                sp.end(error=True)
            raise
        self._acc_ms("prefill_chunk", t0)
        if sp is not None:
            sp.end(consumed=end)

    def _after_emit(self, slot, req):
        now = time.perf_counter()
        gap_ms = req._note_token(now)
        self._m["tokens"] += 1
        _TOKENS.inc()
        if gap_ms is None:  # first generated token: TTFT
            if req.submit_time is not None:
                ttft = (now - req.submit_time) * 1e3
                self._m["ttft_ms"].add(ttft)
                _TTFT_MS.observe(ttft)
        else:
            self._m["inter_token_ms"].add(gap_ms)
            _ITL_MS.observe(gap_ms)
        if self.eos is not None and req.output_ids[-1] == self.eos:
            self._finish(slot, "eos")
        elif len(req.output_ids) >= req.max_new_tokens:
            self._finish(slot, "length")
        elif self._pos[slot] >= self.T:   # next write column out of cache
            self._finish(slot, "capacity")

    def step(self):
        """Admit queued requests into free slots, then run ONE decode step
        for every active slot. Returns requests finished this step.

        Per-request failure isolation: host-side per-slot work (admission,
        chunked-prefill advance, token emission) that throws finishes ONLY
        that slot's request with reason="error" and evicts it — the rest
        of the batch continues. A failure in the batched device program
        itself is not isolatable (one executable) and propagates."""
        # window beacon around the WHOLE step (the failpoint delay
        # included): a thread wedged anywhere inside leaves an active,
        # non-advancing site for the stall sentinel to name — and a
        # finished sibling engine cannot mask it, because the site only
        # deactivates when the LAST open step window closes
        with _blackbox.progress("serving/step"):
            if self._perf_ledger is None:
                return self._step_inner()
            t0 = time.perf_counter()
            try:
                return self._step_inner()
            finally:
                self._ledger_round((time.perf_counter() - t0) * 1e3)

    def _ledger_round(self, step_ms):
        """Armed-only (FLAGS_perf_ledger) per-round feed: the regression
        sentinel sees every round's wall ms; every
        FLAGS_perf_ledger_interval-th round appends the full
        stats()['breakdown'] ledger row (per-kind step ms, executed
        device flops, queue-wait/TTFT/inter-token digests)."""
        led = self._perf_ledger
        led.observe("serving", {"step_ms": step_ms})
        self._perf_rounds += 1
        if self._perf_rounds % led.interval == 0:
            from ..monitor import perfledger as _perfledger

            _perfledger.record_engine(self, ledger=led)

    def _paged_active(self):
        """Construction-consumed FLAGS_paged_kv vs the live flag: a
        post-construction disarm under a live paged engine raises (there
        is no dense cache to fall back to; the cached boolean also joins
        the AOT extra_key, so a rebuilt engine recompiles rather than
        aliasing paged executables). Dense engines short-circuit — they
        never read the flag per step."""
        if self._paged and not _flags.get_flag("paged_kv", False):
            raise RuntimeError(
                "FLAGS_paged_kv was disarmed under a live paged engine — "
                "the flag is consumed at ENGINE CONSTRUCTION; build a new "
                "engine instead of toggling it mid-flight")
        return self._paged

    def _step_inner(self):
        if self._paged_active():
            # cold-page sweep rides the step cadence: registry-only prefix
            # frames untouched for page_cold_steps sweeps compress to int8
            # host pages (host bookkeeping; no device sync)
            self._pool.sweep()
        # FLAGS_async_dispatch (construction-consumed): overlap round
        # N+1's host admission/bookkeeping with round N's device compute.
        # Speculative engines stay on the sync step (see __init__);
        # paged engines too (their admission mutates the pool the
        # dispatched step's tables were snapshotted from).
        if self._async and self._draft is None and not self._paged:
            return self._step_inner_async()
        return self._step_inner_sync()

    def _step_inner_async(self):
        """The async round (docs/PERF.md): dispatch the decode program
        for the slots active at entry (device starts immediately — jax
        dispatch is asynchronous), then run the HOST work of the next
        round — chunked-prefill advances and queue admissions — while
        the device computes, and only then fetch the round's tokens.
        Per-request token streams are bit-identical to the sync step
        (each slot's decode depends only on its own cache row/position);
        a request admitted this round starts decoding next round instead
        of this one, so drains may take one extra step() call."""
        _fp.failpoint("serving/step")
        self._step_no += 1
        before = set(self._finished)
        self._expire_deadlines()
        active = [s for s in range(self.B)
                  if self._slot_req[s] is not None
                  and s not in self._prefilling]
        self._note_occupancy(active)
        am = self._async_ms
        am["rounds"] += 1
        dispatched = None
        t0_ns = time.perf_counter_ns()
        if active:
            dispatched = self._dispatch_decode(active)
        t_disp_ns = time.perf_counter_ns()
        am["dispatch_ms"] += (t_disp_ns - t0_ns) / 1e6
        # ---- overlapped host window: round N+1's admission work runs
        # while round N's decode executes on device. The row copies the
        # admissions enqueue (_admit) sequence AFTER the in-flight decode
        # on its output cache — device-ordered, rows disjoint.
        self._advance_and_admit()
        t_ov_ns = time.perf_counter_ns()
        am["overlap_ms"] += (t_ov_ns - t_disp_ns) / 1e6
        if dispatched is not None:
            next_toks, kind = dispatched
            # THE round's one host sync: everything admission needed to
            # do already happened while the device was busy
            next_toks = np.asarray(next_toks)  # lint: allow(step-loop-host-sync)
            t1_ns = time.perf_counter_ns()
            am["fetch_ms"] += (t1_ns - t_ov_ns) / 1e6
            # the kind's wall slice = dispatch + fetch windows; the
            # overlapped admission window is already booked under its
            # own kinds by _advance_and_admit
            self._acc_ms_value(
                kind, (t_disp_ns - t0_ns + t1_ns - t_ov_ns) / 1e6)
            self._apply_decode(active, next_toks, kind, t0_ns, t1_ns)
        else:
            t1_ns = t_ov_ns
        if _trace.is_enabled():
            # the PR 5 dispatch-vs-sync breakdown, span-attributed: the
            # admission window rides INSIDE the device-compute window
            _trace.emit("dispatch/decode", t0_ns, t_disp_ns,
                        subsystem="serving", slots=len(active))
            _trace.emit("dispatch/overlap", t_disp_ns, t_ov_ns,
                        subsystem="serving")
            if dispatched is not None:
                _trace.emit("dispatch/fetch", t_ov_ns, t1_ns,
                            subsystem="serving")
        return [self._finished[r] for r in set(self._finished) - before]

    def _step_inner_sync(self):
        import jax.numpy as jnp

        _fp.failpoint("serving/step")
        self._step_no += 1
        before = set(self._finished)
        # after the snapshot: deadline expiries belong to THIS step's
        # returned finishes, same as error/eos/length
        self._expire_deadlines()
        # chunked admissions in flight advance ONE chunk each, so active
        # decodes below never wait for a whole long prefill
        self._advance_and_admit()

        active = [s for s in range(self.B)
                  if self._slot_req[s] is not None
                  and s not in self._prefilling]
        self._note_occupancy(active)
        if active:
            # speculative round: every active slot greedy AND spec_k+1
            # columns of headroom (near-capacity slots fall back to exact
            # single-token steps — junk writes past T would clamp)
            if (self._draft is not None
                    and all(self._temps[s] == 0 for s in active)
                    and all(int(self._pos[s]) + self._spec_k + 1 <= self.T
                            for s in active)):
                self._step_speculative(active)
                return [self._finished[r]
                        for r in set(self._finished) - before]
            # fallback (single-token) step with a draft around: mirror the
            # fed token into the draft cache so later speculative rounds
            # see an intact context (review r5: without this, one sampling
            # neighbor permanently cold-starts every survivor's draft)
            t0 = time.perf_counter()
            t0_ns = time.perf_counter_ns()
            if self._draft is not None:
                self._kc_d, self._vc_d = self._draft_sync(
                    self._params_d, self._kc_d, self._vc_d,
                    jnp.asarray(self._last), jnp.asarray(self._pos))
            next_toks, kind = self._dispatch_decode(active)
            next_toks = np.asarray(next_toks)  # lint: allow(step-loop-host-sync)
            self._acc_ms(kind, t0)
            t1_ns = time.perf_counter_ns()
            self._apply_decode(active, next_toks, kind, t0_ns, t1_ns)
        return [self._finished[r] for r in set(self._finished) - before]

    def _step_speculative(self, active):
        """One speculative round for all active (greedy) slots: K draft
        proposals per slot, one batched (K+1)-token target verify at
        per-slot positions, 1..K+1 tokens emitted per slot. Tokens are
        appended one at a time through the standard _after_emit, so
        eos/length finishing matches the single-token engine exactly;
        junk positions on freed/mid-prefill rows ride along like every
        other batched step. Clamping the draft's junk-row writes is safe
        for the same reason admission row-copies are: those rows are
        fully overwritten before they are read."""
        import jax.numpy as jnp

        self._count_step("speculative")
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        props, self._kc_d, self._vc_d = self._draft_propose(
            self._params_d, self._kc_d, self._vc_d,
            jnp.asarray(self._last), jnp.asarray(self._pos))
        t_draft_ns = time.perf_counter_ns()
        emit, m, self._kc, self._vc = self._verify(
            self._params, self._kc, self._vc, jnp.asarray(self._last),
            jnp.asarray(self._pos), props)
        emit = np.asarray(emit)  # lint: allow(step-loop-host-sync)
        m = np.asarray(m)  # lint: allow(step-loop-host-sync)
        t1_ns = time.perf_counter_ns()
        self._acc_ms("speculative", t0)
        if _trace.is_enabled():
            _trace.emit("spec_draft", t0_ns, t_draft_ns,
                        subsystem="serving", slots=len(active),
                        k=self._spec_k)
            _trace.emit("spec_verify", t_draft_ns, t1_ns,
                        subsystem="serving", slots=len(active))
        proposed = self._spec_k * len(active)
        accepted = int(sum(int(m[s]) for s in active))
        self._m["spec_proposed"] += proposed
        self._m["spec_accepted"] += accepted
        _SPEC.labels(event="proposed").inc(proposed)
        _SPEC.labels(event="accepted").inc(accepted)
        for s in active:
            req = self._slot_req[s]
            try:
                _fp.failpoint("serving/slot")
                n_acc = int(m[s]) + 1
                toks = emit[s, :n_acc]
                old_pos = int(self._pos[s])
                self._last[s] = int(toks[-1])
                if req._span is not None:
                    _trace.emit("decode", t0_ns, t1_ns,
                                subsystem="serving", parent=req._span,
                                slot=s, pos=old_pos, kind="speculative",
                                accepted=int(m[s]), emitted=n_acc)
                for i, t in enumerate(toks):
                    # advance pos PER TOKEN so _after_emit's eos/length/
                    # capacity decisions are made at exactly the state the
                    # single-token engine would have seen
                    self._pos[s] = old_pos + i + 1
                    req.output_ids.append(int(t))
                    self._after_emit(s, req)
                    if req.finished:
                        break
            except Exception:
                if self._slot_req[s] is not None:
                    self._finish_req(req, "error", slot=s)
                self._note_error()

    def has_work(self):
        return bool(self._queue) or bool(self._handoff) \
            or any(r is not None for r in self._slot_req)

    def run_until_complete(self, max_steps=100_000):
        """Drain the queue; returns {rid: Request}. Non-convergence fails
        every in-flight request with reason="engine_stalled" (nothing is
        left dangling for callers polling get_request) and raises with
        their rids."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                stalled = []
                # the dump captures the wedge's live state; the finishes
                # below rewrite it, so write the bundle FIRST
                dump_path = None
                if _blackbox.is_enabled():
                    dump_path = _blackbox.dump(
                        "stall", site="serving/step",
                        extra={"trigger": "run_until_complete",
                               "max_steps": max_steps})
                for req in list(self._queue):
                    self._queue.remove(req)
                    self._finish_req(req, "engine_stalled")
                    stalled.append(req.rid)
                for entry in list(self._handoff):
                    self._handoff.remove(entry)
                    self._finish_req(entry[0], "engine_stalled")
                    stalled.append(entry[0].rid)
                for slot, entry in list(self._prefilling.items()):
                    self._finish_req(entry[0], "engine_stalled", slot=slot)
                    stalled.append(entry[0].rid)
                for slot in range(self.B):
                    req = self._slot_req[slot]
                    if req is not None:
                        self._finish_req(req, "engine_stalled", slot=slot)
                        stalled.append(req.rid)
                raise RuntimeError(
                    "serving engine did not converge within "
                    f"{max_steps} steps; failed in-flight requests "
                    f"{sorted(set(stalled))} with reason='engine_stalled'"
                    + (f"; blackbox dump bundle: {dump_path}"
                       if dump_path else ""))
        return dict(self._finished)
