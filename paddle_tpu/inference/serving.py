"""Continuous-batching serving engine (beyond the reference).

The reference serves LMs request-at-a-time through its predictor; modern
LLM serving interleaves requests so a long generation never blocks a short
one. This engine is that recipe, TPU-shaped:

- a FIXED [max_batch, max_seq] KV cache (static shapes — one compiled
  decode program, ever);
- each slot carries its own sequence position: the decode step runs the
  whole batch with PER-ROW positions and per-row cache columns
  (models/gpt.py _decode_fns grew a vectorized-pos path for this);
- admission prefills a new prompt into a fresh single-row cache (prompt
  right-padded to a length bucket, so prefill compiles once per bucket)
  and copies that row into the big cache — one row copy per admission,
  nothing per step;
- right-pad junk in the prefill is never read: it sits at columns the
  causal mask hides until the decode loop OVERWRITES them (the store runs
  before attention each step);
- finished slots (eos / max_new_tokens / capacity) free immediately and
  the next queued request takes the slot on the following step() —
  continuous batching, not static batching.

Greedy decoding (exact parity with `model.generate(temperature=0)` per
request, asserted in tests). Composes with bf16 serving params/cache
(dtype="bfloat16") and the int8 KV cache (cache_dtype="int8").
"""
import numpy as np

from ..core.tensor import Tensor

__all__ = ["ServingEngine", "Request"]


class Request:
    """One submitted prompt and, when finished, its generated tokens."""

    def __init__(self, rid, prompt_ids, max_new_tokens):
        self.rid = rid
        self.prompt_ids = np.asarray(prompt_ids, np.int32).ravel()
        self.max_new_tokens = int(max_new_tokens)
        self.output_ids = []          # generated tokens (no prompt echo)
        self.finished = False
        self.finish_reason = None     # "eos" | "length" | "capacity"

    @property
    def tokens(self):
        return np.asarray(self.output_ids, np.int32)


class ServingEngine:
    def __init__(self, model, max_batch=4, dtype=None, cache_dtype=None,
                 eos_token_id=None, prompt_buckets=(32, 64, 128, 256, 512,
                                                    1024)):
        import jax
        import jax.numpy as jnp

        from ..models.gpt import (_check_decode_config, _decode_fns,
                                  _decode_compute_dtype, _decode_params)

        cfg = model.cfg
        _check_decode_config(cfg)
        self.cfg = cfg
        self.B = int(max_batch)
        self.T = cfg.max_seq_len
        self.eos = eos_token_id
        self._buckets = tuple(sorted(b for b in prompt_buckets
                                     if b <= self.T))
        if not self._buckets:
            raise ValueError("no prompt bucket fits max_seq_len")
        untied, untied_bias, params = _decode_params(model, "the model")
        self._compute_dtype = _decode_compute_dtype(dtype)
        if self._compute_dtype is not None:
            params = {k: (v.astype(self._compute_dtype)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}
        self._params = params
        fwd, logits_of, cache_init = _decode_fns(cfg, untied, untied_bias,
                                                 cache_dtype=cache_dtype)
        cache_dt = self._compute_dtype or jnp.float32

        self._kc, self._vc = cache_init(self.B, self.T, cache_dt)

        def prefill(p, ids_padded, true_len):
            """ids_padded [1, Pb] right-padded; returns (kc1, vc1,
            first_token). Junk beyond true_len is causally invisible and
            later overwritten by the decode loop."""
            kc1, vc1 = cache_init(1, self.T, cache_dt)
            x, kc1, vc1 = fwd(p, ids_padded, 0, kc1, vc1)
            x_last = jax.lax.dynamic_slice_in_dim(
                x, true_len - 1, 1, axis=1)[:, 0]
            logits = logits_of(p, x_last).astype(jnp.float32)
            return kc1, vc1, jnp.argmax(logits, -1).astype(jnp.int32)[0]

        def admit(big, row, r):
            """Copy a 1-row cache into row r of the big cache (r traced —
            one compile covers every slot)."""

            def put(b_leaf, r_leaf):
                return jax.lax.dynamic_update_slice(
                    b_leaf, r_leaf, (0, r, 0, 0, 0))

            if isinstance(big, tuple):
                return (put(big[0], row[0]), put(big[1], row[1]))
            return put(big, row)

        def step(p, kc, vc, last_toks, pos_vec):
            """One decode step for ALL slots at their own positions.
            last_toks [B], pos_vec [B] (the column each slot writes)."""
            x, kc, vc = fwd(p, last_toks[:, None], pos_vec, kc, vc)
            logits = logits_of(p, x[:, 0]).astype(jnp.float32)
            return jnp.argmax(logits, -1).astype(jnp.int32), kc, vc

        # donate the big cache through admit/step: XLA aliases it in place
        # instead of copying GBs of K/V per token (the loop this engine
        # exists to make fast); CPU backends that can't donate just warn
        self._prefill = jax.jit(prefill)
        self._admit = jax.jit(admit, donate_argnums=(0,))
        self._step = jax.jit(step, donate_argnums=(1, 2))

        # host-side slot state
        self._slot_req = [None] * self.B        # Request or None
        self._pos = np.zeros(self.B, np.int32)  # next write column
        self._last = np.zeros(self.B, np.int32)
        self._queue = []
        self._next_rid = 0
        self._finished = {}

    # -- API -----------------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=32):
        """Queue a prompt; returns the request id."""
        ids = prompt_ids._data if isinstance(prompt_ids, Tensor) \
            else np.asarray(prompt_ids)
        ids = np.asarray(ids, np.int32).ravel()
        if max_new_tokens < 1:   # generate()'s own validation, mirrored
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) + 1 > self.T:
            raise ValueError(
                f"prompt ({len(ids)}) too long for max_seq_len {self.T}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(Request(rid, ids, max_new_tokens))
        return rid

    def _bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return self.T

    def _finish(self, slot, reason):
        req = self._slot_req[slot]
        req.finished = True
        req.finish_reason = reason
        self._finished[req.rid] = req
        self._slot_req[slot] = None

    def _admit_one(self, slot, req):
        import jax.numpy as jnp

        n = len(req.prompt_ids)
        pb = self._bucket(n)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :n] = req.prompt_ids
        kc1, vc1, tok = self._prefill(self._params, jnp.asarray(padded),
                                      np.int32(n))
        self._kc = self._admit(self._kc, kc1, slot)
        self._vc = self._admit(self._vc, vc1, slot)
        tok = int(tok)
        self._slot_req[slot] = req
        self._pos[slot] = n
        self._last[slot] = tok
        req.output_ids.append(tok)
        self._after_emit(slot, req)

    def _after_emit(self, slot, req):
        if self.eos is not None and req.output_ids[-1] == self.eos:
            self._finish(slot, "eos")
        elif len(req.output_ids) >= req.max_new_tokens:
            self._finish(slot, "length")
        elif self._pos[slot] >= self.T:   # next write column out of cache
            self._finish(slot, "capacity")

    def step(self):
        """Admit queued requests into free slots, then run ONE decode step
        for every active slot. Returns requests finished this step."""
        import jax.numpy as jnp

        before = set(self._finished)
        for slot in range(self.B):
            # while, not if: a request finishing DURING admission (eos on
            # its prefill token / max_new_tokens=1) frees the slot for the
            # next queued request in the same step
            while self._slot_req[slot] is None and self._queue:
                self._admit_one(slot, self._queue.pop(0))
                if self._slot_req[slot] is not None:
                    break

        active = [s for s in range(self.B) if self._slot_req[s] is not None]
        if active:
            # inactive slots ride along harmlessly: their rows are
            # don't-care (freed) and re-prefilled on admission
            next_toks, self._kc, self._vc = self._step(
                self._params, self._kc, self._vc,
                jnp.asarray(self._last), jnp.asarray(self._pos))
            next_toks = np.asarray(next_toks)
            for s in active:
                self._pos[s] += 1
                self._last[s] = next_toks[s]
                req = self._slot_req[s]
                req.output_ids.append(int(next_toks[s]))
                self._after_emit(s, req)
        return [self._finished[r] for r in set(self._finished) - before]

    def has_work(self):
        return bool(self._queue) or any(r is not None
                                        for r in self._slot_req)

    def run_until_complete(self, max_steps=100_000):
        """Drain the queue; returns {rid: Request}."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("serving engine did not converge "
                                   f"within {max_steps} steps")
        return dict(self._finished)
